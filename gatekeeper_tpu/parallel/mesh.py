"""Multi-chip scaling: shard the resource axis over a device mesh.

The audit sweep is data-parallel over resources (SURVEY.md section 2.4): the
review-side arrays (leading dim R) shard across the mesh's "data" axis over
ICI, the constraint-side arrays replicate, and the [C, R] masks come back
sharded on R.  XLA inserts any collectives; per-constraint reductions
(violation counts) become psums over the data axis.

Integration model (idiomatic JAX): sharding is decided by INPUT PLACEMENT —
`shard_args` commits the argument trees to the mesh with `jax.device_put`,
and the driver's ONE fused jitted function compiles an SPMD executable from
those committed shardings.  No separate "distributed" code path exists for
the kernels themselves.

This is the framework's distributed backend — the analogue of what the
reference simply lacks (its audit is one goroutine; multi-pod scale-out is
independent re-evaluation, pkg/controller/constraintstatus).
"""

from __future__ import annotations

import os as _os
import queue as _queue
import threading as _threading
import time as _time
from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..util import join_thread


class MeshDispatchStall(RuntimeError):
    """A mesh-collective dispatch exceeded the watchdog budget (either the
    gate never freed — a previous dispatch is wedged holding it — or the
    guarded enqueue itself never returned).  The driver treats it as a
    backend failure: trips the breaker and re-shards the sweep narrower
    (docs/failure-modes.md, fleet failure matrix)."""


class DispatchGate:
    """The mesh-collective dispatch serializer, revocable.

    Lock semantics are the original DISPATCH_LOCK's: hold it across every
    collective-bearing enqueue so per-device launch order stays globally
    consistent (an inconsistent interleave deadlocks the AllReduce
    rendezvous — see the PR 6 notes below).  On top of a plain lock it
    adds what the dispatch watchdog needs:

    - ``acquire(timeout)`` returns a token (or None on timeout) so a
      bounded wait can distinguish "busy" from "wedged";
    - ``revoke()`` abandons the current holder: the gate swaps in a fresh
      generation, so after a stuck dispatch is written off, subsequent
      (narrower-topology) dispatches proceed instead of queueing forever
      behind a thread that will never release.  A waiter that was already
      blocked on the OLD generation when it was revoked re-checks the
      generation after acquiring and migrates to the current one — it can
      never end up holding an abandoned lock while a new-generation
      holder dispatches concurrently (that interleave is exactly the
      rendezvous deadlock the gate exists to prevent).  The abandoned
      holder's own eventual release is then harmless.

    Plain ``with DISPATCH_LOCK:`` keeps working (blocking acquire of the
    current generation), so every pre-existing dispatch site is
    unchanged.
    """

    def __init__(self):
        self._mu = _threading.Lock()        # guards the generation swap
        self._lock = _threading.Lock()      # the actual gate
        self._gen = 0
        self._tokens = _threading.local()   # per-thread ctx-manager stack
        self.revocations = 0                # observability (tests, stats)

    def _current(self):
        with self._mu:
            return self._lock, self._gen

    def acquire(self, timeout: Optional[float] = None):
        """-> opaque token for release(), or None when `timeout` elapsed.

        Generation-checked: if a revoke() landed while we waited, the
        lock we just acquired is the ABANDONED one — release it and
        re-acquire the current generation (within the same deadline for
        timed acquires).  Without this, a waiter woken by the wedged
        holder's late release would dispatch its collective under the
        old lock, unserialized against new-generation dispatches."""
        deadline = (
            _time.monotonic() + timeout if timeout is not None else None
        )
        while True:
            lock, gen = self._current()
            if deadline is None:
                got = lock.acquire()
            else:
                remaining = deadline - _time.monotonic()
                got = remaining > 0 and lock.acquire(timeout=remaining)
            if not got:
                return None
            with self._mu:
                if gen == self._gen:
                    return (lock, gen)
            # revoked while we waited: this lock is abandoned — drop it
            # and serialize against the CURRENT generation instead
            lock.release()

    def release(self, token):
        """Idempotent for abandoned holders: releasing a revoked
        generation's lock is safe (nothing acquires it again)."""
        lock, _gen = token
        try:
            lock.release()
        except RuntimeError:
            pass  # already released (defensive; should not happen)

    def revoke(self):
        """Abandon the current holder: fresh lock, new generation."""
        with self._mu:
            self._lock = _threading.Lock()
            self._gen += 1
            self.revocations += 1

    def locked(self) -> bool:
        return self._current()[0].locked()

    def __enter__(self):
        token = self.acquire()
        stack = getattr(self._tokens, "stack", None)
        if stack is None:
            stack = self._tokens.stack = []
        stack.append(token)
        return self

    def __exit__(self, *exc):
        self.release(self._tokens.stack.pop())
        return False


# Process-wide mesh-collective dispatch gate.  Two collective-bearing
# SPMD executables enqueued concurrently from different threads can
# interleave their per-device launch order (A before B on one device,
# B before A on another) and deadlock the cross-device rendezvous —
# observed as a hung AllReduce between the background delta-executable
# warm and a foreground sweep on the virtual CPU mesh, and the same
# hazard exists on any single-process multi-device topology (webhook
# request threads dispatch reviews while the audit thread sweeps).
# Hold it across the enqueue (the jitted call), not the result fetch:
# per-device execution is in-order, so a globally consistent enqueue
# order suffices, and device work still overlaps the host.
DISPATCH_LOCK = DispatchGate()


def audit_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n:
        raise RuntimeError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), ("data",))


def maybe_audit_mesh() -> Optional[Mesh]:
    """The production mesh: data-parallel over every visible device, or
    None when only one device exists (single-chip fast path)."""
    return audit_mesh() if len(jax.devices()) > 1 else None


def pad_rows(rows: int, multiple: int) -> int:
    """Smallest row count >= rows divisible by the mesh size."""
    return ((rows + multiple - 1) // multiple) * multiple


def virtual_mesh_env(n_devices: int, base: Optional[dict] = None) -> dict:
    """Subprocess environment for an ``n_devices`` virtual CPU mesh — the
    one recipe every bench/tool mesh lane uses: force the CPU platform,
    disable axon pool discovery, and replace any existing
    ``xla_force_host_platform_device_count`` XLA flag with ours.  Built
    over ``base`` (default: ``os.environ``); the caller's own process is
    never touched — pass the result to ``subprocess``."""
    env = dict(_os.environ if base is None else base)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f]
    kept.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(kept)
    return env


def shardings_for(mesh: Mesh, rows: int, args):
    """Shardings for the fused-fn argument tuple
    (review_arrays, constraint_arrays, cols, group_params): sharding is
    decided BY POSITION — only the review-side trees (args 0 and 2) shard
    their row-major arrays on "data"; the constraint side (args 1 and 3)
    replicates unconditionally, so a constraint-side array whose bucketed
    leading dim coincides with the row bucket can never be mis-sharded."""
    repl = NamedSharding(mesh, P())

    def row_sharded(x):
        if hasattr(x, "shape") and x.ndim >= 1 and x.shape[0] == rows:
            return NamedSharding(mesh, P("data", *([None] * (x.ndim - 1))))
        return repl  # e.g. vocab-sized keyset id tables

    def replicated(_x):
        return repl

    rv, cs, cols, group_params = args
    return (
        jax.tree_util.tree_map(row_sharded, rv),
        jax.tree_util.tree_map(replicated, cs),
        jax.tree_util.tree_map(row_sharded, cols),
        jax.tree_util.tree_map(replicated, group_params),
    )


def replicate_tree(mesh: Mesh, tree):
    """Commit a tree fully replicated onto the mesh (the constraint side —
    cacheable across calls while the constraint-side epoch is unchanged)."""
    repl = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, repl), tree)


# Slab size below which pipelined_shard_commit skips the packer thread:
# slicing a few thousand rows costs microseconds, so the 2-deep pipeline
# would only add thread-spawn + queue overhead (admission batches routed
# to the device path land here; the audit's 100k-row placements don't).
PIPELINE_MIN_SLAB_ROWS = 2048


def slab_rows(rows: int, mesh_size: int) -> tuple:
    """(padded row count, rows per shard) for a row axis laid over the
    mesh in contiguous slabs."""
    target = pad_rows(rows, mesh_size)
    return target, target // mesh_size


def owning_shards(rows, capacity: int, mesh_size: int) -> set:
    """The set of shard indices whose contiguous row slab holds any of
    `rows` — the shards a churn batch actually touches (everything else
    keeps its resident slab untouched)."""
    _target, slab = slab_rows(capacity, mesh_size)
    return {int(r) // slab for r in rows}


def _row_blocks(mesh: Mesh, target: int):
    """Authoritative (device, lo, hi) row-slab assignment for P("data")
    over a [target, ...] array, in ascending-row order — derived from the
    sharding's own index map, never assumed from device iteration order."""
    sh = NamedSharding(mesh, P("data"))
    blocks = []
    for dev, idx in sh.addressable_devices_indices_map((target,)).items():
        s = idx[0]
        lo = s.start or 0
        hi = s.stop if s.stop is not None else target
        blocks.append((dev, lo, hi))
    blocks.sort(key=lambda b: b[1])
    return blocks


def _slab_of(x: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Rows [lo, hi) of a (possibly shorter) row-major host array, zero-
    padded past its end.  The in-range case is a VIEW — the pipeline's
    host cost per slab is the device_put copy, nothing extra.  Zero
    padding is semantically inert: the match kernel ANDs every cell with
    the review-side `valid` flag (ops/matchkernel.py), which pads to
    False, so a padded row can never produce a positive cell."""
    if hi <= x.shape[0]:
        return x[lo:hi]
    live = x[lo: min(hi, x.shape[0])]
    widths = [(0, (hi - lo) - live.shape[0])] + [(0, 0)] * (x.ndim - 1)
    return np.pad(live, widths)


def pipelined_shard_commit(
    mesh: Mesh, rows: int, trees,
    record_shard: Optional[Callable] = None,
):
    """Commit row-major trees to the mesh slab-by-slab with a two-deep
    host-pack / device-commit pipeline: a packer thread slices+pads shard
    i+1's row slab while the main thread's `jax.device_put` of shard i is
    in flight (transfers are asynchronous, so the device DMA of slab i
    also overlaps the packing of i+1).  This replaces the serial
    pad-everything-then-put placement whose Python packing cost sat ahead
    of every dispatch.  Placements whose slabs are at most
    PIPELINE_MIN_SLAB_ROWS rows commit serially (same slabs, same
    telemetry): there the packing cost the pipeline would hide is smaller
    than the thread+queue overhead.

    trees: tuple of pytrees; leaves with leading dim == rows shard on
    "data" in contiguous slabs, everything else (vocab-sized tables)
    replicates.  record_shard(shard, n_rows, pack_t0, pack_t1, commit_t0,
    commit_t1) is invoked on the calling thread per committed shard.
    Returns (placed_trees, padded_rows)."""
    n = mesh.devices.size
    target, _slab = slab_rows(rows, n)
    repl = NamedSharding(mesh, P())
    leaves, treedef = jax.tree_util.tree_flatten(trees)
    row_idx = [
        i for i, x in enumerate(leaves)
        if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1
        and x.shape[0] == rows
    ]
    row_set = set(row_idx)
    placed = [
        x if i in row_set else jax.device_put(x, repl)
        for i, x in enumerate(leaves)
    ]
    if row_idx:
        row_leaves = [np.asarray(leaves[i]) for i in row_idx]
        blocks = _row_blocks(mesh, target)
        per_shard = [[] for _ in row_leaves]
        _slab_n = target // n
        if _slab_n <= PIPELINE_MIN_SLAB_ROWS:
            # small placement (e.g. an admission batch routed to the
            # device path): the packing cost the pipeline hides is
            # microseconds here, so the thread+queue machinery would be
            # pure overhead — commit serially, same telemetry
            for shard, (dev, lo, hi) in enumerate(blocks):
                pt0 = _time.perf_counter()
                slabs = [_slab_of(x, lo, hi) for x in row_leaves]
                pt1 = ct0 = _time.perf_counter()
                puts = jax.device_put(slabs, dev)  # async transfer
                for li, arr in enumerate(puts):
                    per_shard[li].append(arr)
                ct1 = _time.perf_counter()
                if record_shard is not None:
                    record_shard(shard, hi - lo, pt0, pt1, ct0, ct1)
        else:
            q: _queue.Queue = _queue.Queue(maxsize=1)  # pack i+1 / commit i
            stop = _threading.Event()

            def _put(item) -> bool:
                # bounded put: if the consumer died, its finally sets
                # `stop` and we bail instead of blocking forever on the
                # full queue (which would also stall the consumer's join)
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.05)
                        return True
                    except _queue.Full:
                        continue
                return False

            def packer():
                try:
                    for shard, (dev, lo, hi) in enumerate(blocks):
                        t0 = _time.perf_counter()
                        slabs = [_slab_of(x, lo, hi) for x in row_leaves]
                        if not _put((shard, dev, lo, hi, slabs,
                                     t0, _time.perf_counter())):
                            return
                    _put(None)
                except BaseException as e:  # surfaced on the consumer side
                    _put(e)

            t = _threading.Thread(target=packer, daemon=True,
                                  name="gk-shard-pack")
            t.start()
            try:
                while True:
                    item = q.get()
                    if item is None:
                        break
                    if isinstance(item, BaseException):
                        raise item
                    shard, dev, lo, hi, slabs, pt0, pt1 = item
                    ct0 = _time.perf_counter()
                    puts = jax.device_put(slabs, dev)  # async transfer
                    for li, arr in enumerate(puts):
                        per_shard[li].append(arr)
                    ct1 = _time.perf_counter()
                    if record_shard is not None:
                        record_shard(shard, hi - lo, pt0, pt1, ct0, ct1)
            finally:
                stop.set()
                join_thread(t, 5.0, "shard packer")
        for li, i in enumerate(row_idx):
            x = row_leaves[li]
            shape = (target,) + x.shape[1:]
            sh = NamedSharding(mesh, P("data", *([None] * (x.ndim - 1))))
            placed[i] = jax.make_array_from_single_device_arrays(
                shape, sh, per_shard[li]
            )
    out = jax.tree_util.tree_unflatten(treedef, placed)
    return out, target


def shard_review_side(mesh: Mesh, rows: int, rv, cols, record_shard=None):
    """Pad the row axis to a mesh multiple and commit the review-side trees
    with row-major arrays partitioned on "data" in contiguous slabs
    (everything else, e.g. vocab-sized tables, replicated) — slab by slab
    through the double-buffered pipeline (pipelined_shard_commit).
    Returns (rv, cols, padded_rows)."""
    (rv_p, cols_p), target = pipelined_shard_commit(
        mesh, rows, (rv, cols), record_shard=record_shard
    )
    return rv_p, cols_p, target


def shard_args(mesh: Mesh, rows: int, args):
    """Pad the row axis to a mesh multiple and commit every argument to the
    mesh (row-major review arrays partitioned on "data", everything else
    replicated).  Returns (sharded_args, padded_rows).  Calling the driver's
    fused jit on these committed inputs yields an SPMD executable."""
    rv, cs, cols, group_params = args
    rv_p, cols_p, target = shard_review_side(mesh, rows, rv, cols)
    cs_p, gp_p = replicate_tree(mesh, (cs, group_params))
    return (rv_p, cs_p, cols_p, gp_p), target


def sharded_masks(driver, reviews, mesh: Mesh):
    """compute_masks, sharded over the mesh: the full evaluation step (match
    kernel + all violation-program groups) jitted once over the mesh with
    the resource axis partitioned.  Returns (ordered, mask, autoreject) like
    TpuDriver.compute_masks (R axis trimmed back to the single-device
    bucket so results compare bit-for-bit)."""
    fn, ordered, rp, cp, cols, group_params, crow = driver._device_inputs(
        reviews
    )
    rows = len(rp.arrays["valid"])
    args = (rp.arrays, cp.arrays, cols, group_params)
    placed, target = shard_args(mesh, rows, args)
    with mesh:
        mask, autoreject = fn(*placed)
    both = np.asarray(jax.device_get((mask, autoreject)))
    # crow folds the group-major pad rows out (driver._constraint_side)
    return ordered, both[0][crow][:, :rows], both[1][crow][:, :rows]


def sharded_violation_counts(driver, reviews, mesh: Mesh):
    """Per-constraint violation counts with the reduction on-device:
    sum over the sharded R axis (an XLA psum over ICI) so only [C] ints
    cross back to the host."""
    fn, ordered, rp, cp, cols, group_params, crow = driver._device_inputs(
        reviews
    )
    rows = len(rp.arrays["valid"])
    args = (rp.arrays, cp.arrays, cols, group_params)
    placed, target = shard_args(mesh, rows, args)
    raw = fn.__wrapped__

    def counted(rv, cs, c, gp):
        mask, autoreject = raw(rv, cs, c, gp)
        return mask.sum(axis=1), autoreject.sum(axis=1)

    sharded = jax.jit(
        counted,
        out_shardings=(NamedSharding(mesh, P()), NamedSharding(mesh, P())),
    )
    with mesh:
        counts, rejects = sharded(*placed)
    return ordered, np.asarray(counts)[crow], np.asarray(rejects)[crow]
