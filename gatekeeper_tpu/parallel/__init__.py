from .mesh import audit_mesh, sharded_masks, shardings_for  # noqa: F401
