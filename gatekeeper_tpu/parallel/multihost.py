"""Multi-host distributed audit: the resource axis sharded across hosts
over DCN and across each host's chips over ICI.

This is the framework's answer to SURVEY §5.8 ("a distributed communication
backend … scales to multi-host the way the reference's NCCL/MPI backend
does" — the reference itself has none; its multi-pod story is independent
re-evaluation, pkg/controller/constraintstatus).  Design:

- every pod replicates the inventory (the store is derived state, rebuilt
  from the API server — same model as single-host), so no host ever needs
  another host's rows to PACK; sharding is purely a device-placement
  decision
- `jax.distributed.initialize` wires the processes; the global mesh lays
  the row axis over (host, local-device): contiguous row blocks live on one
  host's chips, so the fused sweep's only cross-host traffic is the final
  [C, 1+K] reduction (an all-reduce/all-gather of KBs over DCN) — the
  [C, R] intermediates never cross hosts
- inputs are built with `jax.make_array_from_callback`: each process
  materializes exactly its addressable row shards from its local (full)
  host arrays; the constraint side replicates
- outputs come back fully replicated, so every pod can render and write
  status for the constraints it owns

Validated without hardware by tests/test_multihost.py: two real OS
processes, four virtual CPU devices each, one 8-device global mesh, with
bit-parity against the single-process sweep.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_distributed(coordinator: str, num_processes: int,
                     process_id: int) -> None:
    """Join the process group (idempotent).  coordinator: "host:port" of
    process 0 — the DCN control plane (jax.distributed uses gRPC; the data
    plane is XLA collectives).  Must run before ANY backend-touching JAX
    call, so idempotency is detected from the error, not jax state."""
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise


def multihost_audit_mesh() -> Mesh:
    """Global 2D mesh (host, data): row blocks are contiguous per host so
    the sweep's heavy traffic stays on ICI; only reductions ride DCN."""
    procs = jax.process_count()
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    per_host = len(devs) // procs
    grid = np.array(devs).reshape(procs, per_host)
    return Mesh(grid, ("host", "data"))


def _row_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    # rows partitioned over BOTH mesh axes (host-major, then local device)
    return NamedSharding(mesh, P(("host", "data"), *([None] * (ndim - 1))))


def shard_rows_global(mesh: Mesh, rows: int, tree):
    """Commit a host-local tree as GLOBAL arrays: row-major leaves
    partitioned over (host, data), everything else replicated.  Every
    process holds the full host arrays (replicated store), so the callback
    just slices — each process materializes only its addressable shards."""
    n = mesh.devices.size
    target = ((rows + n - 1) // n) * n

    def place(x):
        x = np.asarray(x)
        if x.ndim >= 1 and x.shape[0] == rows:
            if target != rows:
                pad = [(0, target - rows)] + [(0, 0)] * (x.ndim - 1)
                x = np.pad(x, pad)
            sh = _row_sharding(mesh, x.ndim)
            return jax.make_array_from_callback(
                x.shape, sh, lambda idx, x=x: x[idx]
            )
        sh = NamedSharding(mesh, P())
        return jax.make_array_from_callback(
            x.shape, sh, lambda idx, x=x: x[idx]
        )

    return jax.tree_util.tree_map(place, tree), target


def multihost_capped_sweep(driver, K: int):
    """The full capped-audit device sweep over the multi-host mesh, built
    with shard_map: every shard evaluates ONLY its contiguous row slab and
    reduces it locally to [C, 1+K] (counts + first-K candidates translated
    to global row indices); an all_gather of those KB-scale reductions —
    the only DCN data-plane traffic — replicates them to every host, and
    the host-side merge (ops/driver._merge_sharded_packed) produces the
    global capped result.  Letting GSPMD partition a naive replicated-out
    jit instead all-gathers the [C, R] mask for the order-dependent top-k,
    making every shard re-reduce the full row axis (the r4 verdict's
    sharded-overhead finding).  -> (ordered, counts [C], topk [C, K])."""
    import jax.numpy as jnp

    from ..ops.driver import _merge_sharded_packed

    fn, ordered, cp, group_params, crow = driver._audit_inputs(K)
    if getattr(driver, "_active_join_plans", lambda: ())():
        # referential join plans take a trailing `joins` runtime arg and
        # (in trace mode) an all_gather over the in-process mesh axis;
        # the multi-host lane has not grown that plumbing — fail loudly
        # rather than sweep with a silently mis-shaped executable
        raise NotImplementedError(
            "referential join plans are not supported on the multi-host "
            "audit lane (docs/referential.md)"
        )
    ap = driver._audit_pack
    if ap.n_rows == 0:
        return [], None, None
    mesh = multihost_audit_mesh()
    (rv_g, cols_g), _target = shard_rows_global(
        mesh, ap.capacity, (ap.rp, ap.cols)
    )
    (cs_g, gp_g), _t2 = shard_rows_global(mesh, -1, (cp.arrays, group_params))
    # jit cached on the driver per (constraint epoch, K, mesh shape): a
    # fresh lambda per call would re-trace + recompile the fused kernel
    # every sweep (advisor r3)
    key = (driver._cs_epoch, K, tuple(sorted(mesh.shape.items())))
    cached = getattr(driver, "_multihost_jit", None)
    if cached is not None and cached[0] == key:
        sharded = cached[1]
    else:
        raw = fn.__wrapped__  # fused_audit: already packed-only, local rows

        def body(rv, cs, c, gp):
            packed = raw(rv, cs, c, gp)  # [C, 1+K'], local row indices
            rows_local = rv["valid"].shape[0]
            shard = jax.lax.axis_index(("host", "data"))
            idx = packed[:, 1:]
            idx = jnp.where(idx >= 0, idx + shard * rows_local, -1)
            packed = jnp.concatenate([packed[:, :1], idx], axis=1)
            # [N, C, 1+K'] replicated: the KB-scale DCN crossing
            return jax.lax.all_gather(packed, ("host", "data"))

        def row_spec(a):
            return P(("host", "data"), *([None] * (a.ndim - 1)))

        repl = P()
        in_specs = (
            jax.tree_util.tree_map(lambda a: row_spec(a), rv_g),
            jax.tree_util.tree_map(lambda a: repl, cs_g),
            jax.tree_util.tree_map(lambda a: row_spec(a), cols_g),
            jax.tree_util.tree_map(lambda a: repl, gp_g),
        )
        from ..util.jaxcompat import shard_map

        sharded = jax.jit(shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=repl,
            check_vma=False,
        ))
        driver._multihost_jit = (key, sharded)
    with mesh:
        allp = sharded(rv_g, cs_g, cols_g, gp_g)
    allp = np.asarray(allp.addressable_data(0))  # replicated [N, C, 1+K']
    # crow folds group-major pad rows out (driver._constraint_side);
    # merge back to the single-device width K
    packed = _merge_sharded_packed(allp, K)[crow]
    return ordered, packed[:, 0].astype(np.int64), packed[:, 1:]
