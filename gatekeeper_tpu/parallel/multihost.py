"""Multi-host distributed audit: the resource axis sharded across hosts
over DCN and across each host's chips over ICI.

This is the framework's answer to SURVEY §5.8 ("a distributed communication
backend … scales to multi-host the way the reference's NCCL/MPI backend
does" — the reference itself has none; its multi-pod story is independent
re-evaluation, pkg/controller/constraintstatus).  Design:

- every pod replicates the inventory (the store is derived state, rebuilt
  from the API server — same model as single-host), so no host ever needs
  another host's rows to PACK; sharding is purely a device-placement
  decision
- `jax.distributed.initialize` wires the processes; the global mesh lays
  the row axis over (host, local-device): contiguous row blocks live on one
  host's chips, so the fused sweep's only cross-host traffic is the final
  [C, 1+K] reduction (an all-reduce/all-gather of KBs over DCN) — the
  [C, R] intermediates never cross hosts
- inputs are built with `jax.make_array_from_callback`: each process
  materializes exactly its addressable row shards from its local (full)
  host arrays; the constraint side replicates
- outputs come back fully replicated, so every pod can render and write
  status for the constraints it owns

Validated without hardware by tests/test_multihost.py: two real OS
processes, four virtual CPU devices each, one 8-device global mesh, with
bit-parity against the single-process sweep.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_distributed(coordinator: str, num_processes: int,
                     process_id: int) -> None:
    """Join the process group (idempotent).  coordinator: "host:port" of
    process 0 — the DCN control plane (jax.distributed uses gRPC; the data
    plane is XLA collectives).  Must run before ANY backend-touching JAX
    call, so idempotency is detected from the error, not jax state."""
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise


def multihost_audit_mesh() -> Mesh:
    """Global 2D mesh (host, data): row blocks are contiguous per host so
    the sweep's heavy traffic stays on ICI; only reductions ride DCN."""
    procs = jax.process_count()
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    per_host = len(devs) // procs
    grid = np.array(devs).reshape(procs, per_host)
    return Mesh(grid, ("host", "data"))


def _row_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    # rows partitioned over BOTH mesh axes (host-major, then local device)
    return NamedSharding(mesh, P(("host", "data"), *([None] * (ndim - 1))))


def shard_rows_global(mesh: Mesh, rows: int, tree):
    """Commit a host-local tree as GLOBAL arrays: row-major leaves
    partitioned over (host, data), everything else replicated.  Every
    process holds the full host arrays (replicated store), so the callback
    just slices — each process materializes only its addressable shards."""
    n = mesh.devices.size
    target = ((rows + n - 1) // n) * n

    def place(x):
        x = np.asarray(x)
        if x.ndim >= 1 and x.shape[0] == rows:
            if target != rows:
                pad = [(0, target - rows)] + [(0, 0)] * (x.ndim - 1)
                x = np.pad(x, pad)
            sh = _row_sharding(mesh, x.ndim)
            return jax.make_array_from_callback(
                x.shape, sh, lambda idx, x=x: x[idx]
            )
        sh = NamedSharding(mesh, P())
        return jax.make_array_from_callback(
            x.shape, sh, lambda idx, x=x: x[idx]
        )

    return jax.tree_util.tree_map(place, tree), target


def multihost_capped_sweep(driver, K: int):
    """The full capped-audit device sweep over the multi-host mesh: fused
    evaluation + on-device [C, 1+K] reduction, returned REPLICATED so every
    host can render/write status.  -> (ordered, counts [C], topk [C, K])."""
    fn, ordered, cp, group_params, crow = driver._audit_inputs(K)
    ap = driver._audit_pack
    if ap.n_rows == 0:
        return [], None, None
    mesh = multihost_audit_mesh()
    (rv_g, cols_g), _target = shard_rows_global(
        mesh, ap.capacity, (ap.rp, ap.cols)
    )
    (cs_g, gp_g), _t2 = shard_rows_global(mesh, -1, (cp.arrays, group_params))
    # jit cached on the driver per (constraint epoch, K, mesh shape): a
    # fresh lambda per call would re-trace + recompile the fused kernel
    # every sweep (advisor r3)
    key = (driver._cs_epoch, K, tuple(sorted(mesh.shape.items())))
    cached = getattr(driver, "_multihost_jit", None)
    if cached is not None and cached[0] == key:
        sharded = cached[1]
    else:
        raw = fn.__wrapped__  # fused_audit: already packed-only
        sharded = jax.jit(
            lambda rv, cs, c, gp: raw(rv, cs, c, gp),
            out_shardings=NamedSharding(mesh, P()),
        )
        driver._multihost_jit = (key, sharded)
    with mesh:
        packed = sharded(rv_g, cs_g, cols_g, gp_g)
    # crow folds group-major pad rows out (driver._constraint_side)
    packed = np.asarray(packed.addressable_data(0))[crow]
    return ordered, packed[:, 0].astype(np.int64), packed[:, 1:]
