"""Audit manager — the periodic full-cluster sweep (reference
pkg/audit/manager.go).

Two modes, as the reference:
  from-cache  — one engine Audit over the replicated inventory
                (manager.go:195-207); with the TPU driver this is the
                batched constraints×resources device sweep
  discovery   — list every listable GVK from the API store and review each
                object (manager.go:233-404), with pagination
                (--audit-chunk-size), per-run namespace cache
                (manager.go:96-115) and kind pre-filtering
                (--audit-match-kind-only, manager.go:282-331)

TPU-first departure: discovery mode batches reviews through
client.review_batch — one device dispatch per chunk — instead of the
reference's serial per-object Review loop (manager.go:361-389).

Results land on each constraint's status.violations capped at
--constraint-violations-limit via a retrying update loop
(manager.go:555-620, 643-701).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from datetime import datetime, timezone

from .. import logging as gklog
from ..client.drivers import constraint_match_spec
from ..kube.inmem import GVK, InMemoryKube, NotFound
from ..obs import slo as obsslo
from ..obs import trace as obstrace
from ..process.excluder import AUDIT, Excluder
from ..target.target import AugmentedUnstructured
from ..util import KNOWN_ENFORCEMENT_ACTIONS, get_enforcement_action
from ..util import join_thread

log = gklog.get("audit")

CONSTRAINTS_GROUP = "constraints.gatekeeper.sh"
CONSTRAINTS_VERSION = "v1beta1"
TEMPLATES_CRD_NAME = "constrainttemplates.templates.gatekeeper.sh"
CRD_GVK = ("apiextensions.k8s.io", "v1", "CustomResourceDefinition")

MSG_SIZE = 256  # manager.go:41 msgSize
DEFAULT_AUDIT_INTERVAL = 60.0
DEFAULT_VIOLATIONS_LIMIT = 20
DEFAULT_REVIEW_BATCH = 512  # device dispatch width in discovery mode

# groups never audited as cluster resources (gatekeeper's own APIs)
_SKIP_GROUPS = {
    "templates.gatekeeper.sh",
    CONSTRAINTS_GROUP,
    "config.gatekeeper.sh",
    "status.gatekeeper.sh",
    "apiextensions.k8s.io",
}


@dataclass
class StatusViolation:
    """status.violations entry (manager.go StatusViolation)."""

    kind: str
    name: str
    namespace: str
    message: str
    enforcement_action: str

    def to_dict(self) -> dict:
        out = {
            "kind": self.kind,
            "name": self.name,
            "message": self.message,
            "enforcementAction": self.enforcement_action,
        }
        if self.namespace:
            out["namespace"] = self.namespace
        return out


def dt_rfc3339() -> str:
    """UTC RFC3339 timestamp (manager.go:148)."""
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def truncate(msg: str, size: int = MSG_SIZE) -> str:
    if len(msg) <= size:
        return msg
    if size > 3:
        size -= 3
    return msg[:size] + "..."


class AuditManager:
    def __init__(
        self,
        kube: InMemoryKube,
        client,                      # gatekeeper_tpu.client.Client
        excluder: Optional[Excluder] = None,
        reporter=None,
        interval_s: float = DEFAULT_AUDIT_INTERVAL,
        violations_limit: int = DEFAULT_VIOLATIONS_LIMIT,
        chunk_size: int = 0,
        from_cache: bool = False,
        match_kind_only: bool = False,
        emit_audit_events: bool = False,
        event_recorder: Optional[Callable[[dict], None]] = None,
        gk_namespace: str = "gatekeeper-system",
        review_batch: int = DEFAULT_REVIEW_BATCH,
        require_crd: bool = False,
        exact_totals: bool = False,
        snapshotter=None,
    ):
        self.kube = kube
        self.client = client
        self.excluder = excluder or Excluder()
        self.reporter = reporter
        self.interval_s = interval_s
        self.violations_limit = violations_limit
        self.chunk_size = chunk_size
        self.from_cache = from_cache
        self.match_kind_only = match_kind_only
        self.emit_audit_events = emit_audit_events
        self.event_recorder = event_recorder
        self.gk_namespace = gk_namespace
        self.review_batch = review_batch
        self.require_crd = require_crd
        # --audit-exact-totals: render EVERY violating cell so
        # status.totalViolations counts violation results exactly (reference
        # manager.go:188 semantics).  Off by default: the from-cache sweep
        # uses the driver's cap-aware device reduction, whose totals are
        # exact below the cap and "violating resources" at/over it.
        self.exact_totals = exact_totals
        # failure visibility: a silently failing audit (bare except in the
        # loop) must be observable — last-run status + consecutive-failure
        # streak, exported via Reporters.report_audit_status
        self.consecutive_failures = 0
        self.last_run_status: Optional[str] = None  # "ok" | "error"
        # warm-resume persistence (gatekeeper_tpu/snapshot/): a completed
        # sweep is the one moment the packed inventory is exactly synced
        # to the store, so each success re-arms the background writer
        self.snapshotter = snapshotter
        # decision-log transition basis (obs/decisionlog.py): the
        # previous sweep's reported violation keys, diffed each sweep so
        # the archive records new/resolved DELTAS, never the full set
        self._prev_violation_keys: Optional[set] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- loop (manager.go:406-431) ----------------------------------------

    def start(self):
        # idempotent: a second start() must not spawn a second audit loop
        # (two concurrent sweeps would race the driver and double every
        # status write) nor orphan the first thread
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="audit", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            join_thread(self._thread, 2.0, "audit loop")
            self._thread = None

    def _loop(self):
        from ..obs import brownout as _brownout

        while not self._stop.wait(timeout=self.interval_s):
            if _brownout.defer_background():
                # brownout ladder level >= 1 (docs/failure-modes.md):
                # a sweep competes with saturated admissions for the
                # same cores, so it steps aside — a skipped iteration,
                # not a cancelled loop; freshness staleness is visible
                # via audit_last_run_age_s and the SLO freshness probe
                log.info("audit sweep deferred by brownout ladder")
                continue
            self.run_once_guarded()

    def run_once_guarded(self) -> bool:
        """One audit sweep with failure accounting: the loop body.  Never
        raises; returns True on success.  Failures keep the loop alive
        (kube outage, device fault) but are no longer silent — the status
        and streak land in metrics and on this object."""
        try:
            self.audit_once()
        except Exception:
            self.consecutive_failures += 1
            self.last_run_status = "error"
            log.exception(
                "audit failed (%d consecutive)", self.consecutive_failures
            )
            self._report_status(False)
            return False
        self.consecutive_failures = 0
        self.last_run_status = "ok"
        self._report_status(True)
        # freshness anchor for the SLO engine's audit_last_run_age_s
        # gauge and audit_freshness probe (obs/slo.py) — success only:
        # a failing loop must read as stale, not fresh
        obsslo.observe_audit_run()
        if self.snapshotter is not None:
            try:
                self.snapshotter.notify_sweep()
            except Exception:
                log.exception("could not arm the snapshotter")
        return True

    def _report_status(self, ok: bool):
        if self.reporter is None:
            return
        try:
            self.reporter.report_audit_status(ok, self.consecutive_failures)
        except Exception:
            log.exception("could not report audit status")

    # ---- one sweep (manager.go:146-230) -----------------------------------

    def audit_once(self) -> Dict[str, List[StatusViolation]]:
        t0 = time.monotonic()
        timestamp = dt_rfc3339()
        # root span of the audit trace: the driver's sweep stages (pack /
        # per-shard dispatch / fetch / render) parent to it via the
        # context var since the whole sweep runs on this thread.  Manual
        # enter/exit (instead of re-indenting the body): __enter__ is
        # immediately followed by the try whose finally __exit__s with
        # the live exc_info, so the span can neither leak on this
        # long-lived thread nor lose error attribution
        _span_ctx = obstrace.root_span(
            "audit", audit_id=timestamp,
            mode="from-cache" if self.from_cache else "discovery",
        )
        _span = _span_ctx.__enter__()
        try:
            gklog.log_event(log, "auditing constraints and violations",
                            **{gklog.EVENT_TYPE: "audit_started",
                               gklog.AUDIT_ID: timestamp})
            if self.reporter:
                self.reporter.report_audit_last_run(time.time())  # wall-clock: ok (epoch gauge)
            if self.require_crd and not self._crd_exists():
                log.info("audit exits, required crd has not been deployed")
                return {}
            constraint_kinds = self._constraint_kinds()
            if not constraint_kinds:
                log.info("no constraint kinds found")
                return {}

            update_lists: Dict[str, List[StatusViolation]] = {}
            totals_per_constraint: Dict[str, int] = {}
            # totals are exact (reference totalViolations semantics) unless
            # the capped driver reduction reports an approximation for a
            # constraint ("resources": device-candidate count past the cap)
            totals_exact: Dict[str, bool] = {}
            totals_per_action: Dict[str, int] = {
                a: 0 for a in KNOWN_ENFORCEMENT_ACTIONS
            }

            if self.from_cache:
                capped = (
                    not self.exact_totals
                    and hasattr(self.client, "audit_capped")
                )
                if capped:
                    responses, driver_totals = self.client.audit_capped(
                        self.violations_limit
                    )
                    results = responses.results()
                else:
                    results = self.client.audit().results()
                # the sweep owner surfaces the sharded-path shape: mesh
                # width, per-shard row work and (steady state) the
                # O(churn) delta row count ride the audit root span and
                # the audit_finished event, so an operator can read the
                # pipeline's behavior off one trace
                self._annotate_sweep(_span)
                self._add_results(
                    results, update_lists, totals_per_constraint,
                    totals_per_action, timestamp,
                )
                if capped:
                    # driver-reported totals override the (capped) result
                    # iteration counts; the status key must match what
                    # _add_results derived from the constraint object
                    rendered_per: Dict[Tuple[str, str], int] = {}
                    for r in results:
                        kk = (r.constraint.get("kind", ""),
                              (r.constraint.get("metadata") or {}).get("name", ""))
                        rendered_per[kk] = rendered_per.get(kk, 0) + 1
                    for kk, (n, how) in driver_totals.items():
                        cobj = None
                        if hasattr(self.client, "get_constraint"):
                            cobj = self.client.get_constraint(*kk)
                        key = (
                            self._constraint_key(cobj) if cobj
                            else f"{kk[0]}//{kk[1]}"
                        )
                        totals_per_constraint[key] = n
                        totals_exact[key] = how == "exact"
                        extra = n - rendered_per.get(kk, 0)
                        if extra > 0:
                            a = get_enforcement_action(cobj or {})
                            totals_per_action[a] = (
                                totals_per_action.get(a, 0) + extra
                            )
            else:
                self._audit_resources(
                    update_lists, totals_per_constraint, totals_per_action,
                    timestamp,
                )

            for key in update_lists:
                gklog.log_event(
                    log, "audit results for constraint",
                    **{gklog.EVENT_TYPE: "constraint_audited",
                       gklog.CONSTRAINT_NAME: key.rsplit("/", 1)[-1],
                       "total_violations": totals_per_constraint.get(key, 0)},
                )
            if self.reporter:
                for action, n in totals_per_action.items():
                    self.reporter.report_total_violations(action, n)

            with obstrace.span("audit.status_write",
                               stage=obstrace.STATUS_WRITE,
                               constraints=len(update_lists)):
                self._write_audit_results(
                    constraint_kinds, update_lists, timestamp,
                    totals_per_constraint, totals_exact,
                )
            self._record_transitions(update_lists, timestamp)
            return update_lists
        finally:
            dur = time.monotonic() - t0
            if self.reporter:
                self.reporter.report_audit_duration(dur)
            gklog.log_event(log, "auditing is complete",
                            **{gklog.EVENT_TYPE: "audit_finished",
                               gklog.AUDIT_ID: timestamp,
                               **self._sweep_shape()})
            import sys as _sys

            _span_ctx.__exit__(*_sys.exc_info())

    # ---- helpers -----------------------------------------------------------

    def _record_transitions(self, update_lists, timestamp):
        """Decision-log feed (obs/decisionlog.py): diff this sweep's
        REPORTED violation set (update_lists — per-constraint capped at
        violations_limit, the same set the status writes publish)
        against the previous sweep's, and record only the new/resolved
        deltas.  A restart's first sweep reports everything as new (no
        basis).  Guarded: provenance must never fail the sweep."""
        try:
            from ..obs import decisionlog as obsdlog

            # the O(reported violations) digest + diff below is pure
            # decision-log feed work — skip it entirely when recording
            # is off (the next enabled sweep reports all-new, same as a
            # restart's first sweep)
            if not obsdlog.get_log().record_enabled:
                self._prev_violation_keys = None
                return
            cur = set()
            for ck, violations in update_lists.items():
                for v in violations:
                    cur.add((ck, v.kind, v.namespace, v.name,
                             obsdlog.message_digest(v.message)))
            prev = self._prev_violation_keys
            self._prev_violation_keys = cur
            if prev is None:
                prev = set()
            new = sorted(cur - prev)
            resolved = sorted(prev - cur)
            if new or resolved:
                obsdlog.record_audit_transitions(new, resolved, timestamp)
        except Exception:
            log.exception("could not record decision-log transitions")

    # last_sweep_stats keys the audit owner republishes (sharded-path
    # shape: mesh width, per-shard work, steady-state churn row count)
    _SWEEP_SHAPE_KEYS = (
        "shards", "rows_per_shard", "rows", "delta_rows", "delta_shards",
    )

    def _sweep_shape(self) -> Dict[str, float]:
        """The driver's last sweep shape, filtered to the sharded-path
        keys; {} when the engine exposes no sweep stats (interp tier)."""
        drv = getattr(self.client, "driver", None)
        stats = getattr(drv, "last_sweep_stats", None)
        if not isinstance(stats, dict):
            return {}
        return {k: stats[k] for k in self._SWEEP_SHAPE_KEYS if k in stats}

    def _annotate_sweep(self, span):
        try:
            shape = self._sweep_shape()
            if shape:
                span.set_attrs(**shape)
        except Exception:  # telemetry must never fail the sweep
            log.exception("could not annotate the audit span")

    def _crd_exists(self) -> bool:
        try:
            self.kube.get(CRD_GVK, TEMPLATES_CRD_NAME)
            return True
        except NotFound:
            return False

    def _constraint_kinds(self) -> List[GVK]:
        """getAllConstraintKinds (manager.go:438-460): every constraint kind
        served under constraints.gatekeeper.sh/v1beta1.  Discovery here is
        the engine's installed-template list unioned with kinds present in
        the API store."""
        kinds = {k for k in self.client.templates()}
        for gvk in self.kube.list_gvks():
            if gvk[0] == CONSTRAINTS_GROUP:
                kinds.add(gvk[2])
        return [(CONSTRAINTS_GROUP, CONSTRAINTS_VERSION, k) for k in sorted(kinds)]

    def _constraint_key(self, constraint: dict) -> str:
        """selfLink analogue: unique key per constraint object."""
        meta = constraint.get("metadata") or {}
        return f"{constraint.get('kind', '')}/{meta.get('namespace', '')}/{meta.get('name', '')}"

    def _matched_kinds(self, constraint_kinds: List[GVK]) -> set:
        """Kind pre-filter from constraint spec.match.kinds
        (--audit-match-kind-only, manager.go:282-331)."""
        if not self.match_kind_only:
            return {"*"}
        matched = set()
        for cgvk in constraint_kinds:
            for constraint in self.kube.list(cgvk):
                kinds_list = constraint_match_spec(constraint).get("kinds")
                if kinds_list is None:
                    return {"*"}
                for entry in kinds_list:
                    if not isinstance(entry, dict):
                        continue
                    for kk in entry.get("kinds") or []:
                        if kk in ("", "*"):
                            return {"*"}
                        matched.add(kk)
        return matched

    def _audit_resources(
        self, update_lists, totals_per_constraint, totals_per_action,
        timestamp,
    ):
        """Discovery-mode sweep with batched device dispatches.  The
        inventory span covers the whole list+review walk (the listing
        interleaves with dispatch flushes, so the driver's pack/dispatch
        spans nest inside it — audit stages overlap by design, unlike the
        webhook's disjoint stages; docs/tracing.md)."""
        constraint_kinds = self._constraint_kinds()
        matched = self._matched_kinds(constraint_kinds)
        ns_cache: Dict[str, Optional[dict]] = {}

        def lookup_ns(name: str) -> Optional[dict]:
            if name not in ns_cache:
                try:
                    ns_cache[name] = self.kube.get(("", "v1", "Namespace"), name)
                except NotFound:
                    ns_cache[name] = None
            return ns_cache[name]

        pending: List[AugmentedUnstructured] = []

        def flush():
            if not pending:
                return
            for resp in self.client.review_batch(list(pending)):
                self._add_results(
                    resp.results(), update_lists, totals_per_constraint,
                    totals_per_action, timestamp,
                )
            pending.clear()

        with obstrace.span("audit.inventory", stage=obstrace.INVENTORY):
            for gvk in self.kube.list_gvks():
                if gvk[0] in _SKIP_GROUPS:
                    continue
                if "*" not in matched and gvk[2] not in matched:
                    continue
                # STREAMED paging (--audit-chunk-size): each page arrives
                # via the kube surface's limit+continue chunking, so host
                # memory is bounded by the chunk size, not the cluster size
                # (reference manager.go:342-396); each page then fills
                # device-width review batches.  Kube clients without
                # list_pages fall back to one full-list page.
                if self.chunk_size and hasattr(self.kube, "list_pages"):
                    pages = self.kube.list_pages(gvk, limit=self.chunk_size)
                else:
                    pages = iter([self.kube.list(gvk)])
                for page in pages:
                    for obj in page:
                        ns = (obj.get("metadata") or {}).get("namespace") or ""
                        # a Namespace object is excluded by its own name —
                        # an excluded namespace shouldn't surface via its
                        # Namespace object either (deliberate tightening of
                        # manager.go:362)
                        if not ns and gvk == ("", "v1", "Namespace"):
                            ns = (obj.get("metadata") or {}).get("name") or ""
                        if self.excluder.is_namespace_excluded(AUDIT, ns):
                            continue
                        ns_obj = lookup_ns(ns) if ns else None
                        pending.append(
                            AugmentedUnstructured(object=obj, namespace=ns_obj)
                        )
                        if len(pending) >= self.review_batch:
                            flush()
            flush()

    def _add_results(
        self, results, update_lists, totals_per_constraint,
        totals_per_action, timestamp,
    ):
        """addAuditResponsesToUpdateLists (manager.go:462-508)."""
        for r in results:
            key = self._constraint_key(r.constraint)
            totals_per_constraint[key] = totals_per_constraint.get(key, 0) + 1
            action = r.enforcement_action
            totals_per_action[action] = totals_per_action.get(action, 0) + 1
            resource = r.resource or {}
            rmeta = resource.get("metadata") or {}
            if len(update_lists.setdefault(key, [])) < self.violations_limit:
                update_lists[key].append(
                    StatusViolation(
                        kind=resource.get("kind", ""),
                        name=rmeta.get("name", ""),
                        namespace=rmeta.get("namespace", "") or "",
                        message=truncate(r.msg),
                        enforcement_action=action,
                    )
                )
            cmeta = r.constraint.get("metadata") or {}
            gklog.log_event(
                log, "audit violation",
                **{gklog.PROCESS: "audit",
                   gklog.EVENT_TYPE: "violation_audited",
                   gklog.CONSTRAINT_NAME: cmeta.get("name", ""),
                   gklog.CONSTRAINT_KIND: r.constraint.get("kind", ""),
                   gklog.CONSTRAINT_ACTION: action,
                   gklog.RESOURCE_KIND: resource.get("kind", ""),
                   gklog.RESOURCE_NAMESPACE: rmeta.get("namespace", ""),
                   gklog.RESOURCE_NAME: rmeta.get("name", ""),
                   gklog.AUDIT_ID: timestamp},
            )
            if self.emit_audit_events and self.event_recorder:
                capi = r.constraint.get("apiVersion", "")
                cgroup, _, cversion = capi.rpartition("/")
                rapi = resource.get("apiVersion", "")
                rgroup, _, rversion = rapi.rpartition("/")
                self.event_recorder({
                    "reason": "AuditViolation",
                    "type": "Warning",
                    "message": (
                        f"Timestamp: {timestamp}, Resource Namespace: "
                        f"{rmeta.get('namespace', '')}, Constraint: "
                        f"{cmeta.get('name', '')}, Message: {r.msg}"
                    ),
                    # annotation set of manager.go:755-770 emitEvent
                    "annotations": {
                        "process": "audit",
                        "auditTimestamp": timestamp,
                        gklog.EVENT_TYPE: "violation_audited",
                        gklog.CONSTRAINT_GROUP: cgroup,
                        gklog.CONSTRAINT_API_VERSION: cversion,
                        gklog.CONSTRAINT_KIND: r.constraint.get("kind", ""),
                        gklog.CONSTRAINT_NAME: cmeta.get("name", ""),
                        gklog.CONSTRAINT_NAMESPACE: cmeta.get("namespace", ""),
                        gklog.CONSTRAINT_ACTION: action,
                        gklog.RESOURCE_GROUP: rgroup,
                        gklog.RESOURCE_API_VERSION: rversion,
                        gklog.RESOURCE_KIND: resource.get("kind", ""),
                        gklog.RESOURCE_NAMESPACE: rmeta.get("namespace", ""),
                        gklog.RESOURCE_NAME: rmeta.get("name", ""),
                    },
                    "namespace": self.gk_namespace,
                })

    def _write_audit_results(
        self, constraint_kinds, update_lists, timestamp, totals_per_constraint,
        totals_exact,
    ):
        """writeAuditResults + updateConstraintLoop (manager.go:510-549,
        643-701): per-constraint status writes with retry/backoff."""
        for cgvk in constraint_kinds:
            remaining = {
                self._constraint_key(c): c for c in self.kube.list(cgvk)
            }
            backoff = 0.05
            for _attempt in range(5):
                for key in list(remaining):
                    try:
                        self._update_constraint_status(
                            remaining[key], update_lists.get(key, []),
                            timestamp, totals_per_constraint.get(key, 0),
                            totals_exact.get(key, True),
                        )
                        del remaining[key]
                    except NotFound:
                        # constraint deleted mid-audit: nothing to update
                        del remaining[key]
                    except Exception:
                        log.exception(
                            "could not update constraint status: %s", key
                        )
                if not remaining:
                    break
                time.sleep(backoff)
                backoff *= 2

    def _update_constraint_status(
        self, constraint: dict, violations: List[StatusViolation],
        timestamp: str, total: int, total_exact: bool = True,
    ):
        """updateConstraintStatus (manager.go:555-620)."""
        meta = constraint.get("metadata") or {}
        gvk = (CONSTRAINTS_GROUP, CONSTRAINTS_VERSION, constraint.get("kind", ""))
        latest = self.kube.get(gvk, meta.get("name", ""),
                               meta.get("namespace", "") or "")
        status = latest.setdefault("status", {})
        status["auditTimestamp"] = timestamp
        status["totalViolations"] = total
        # exact/approximate marker (r2 VERDICT #9): False only when the cap
        # cut rendering short AND the constraint's vectorized program is not
        # provably count-exact, so the total counts device-candidate
        # resources rather than violations
        status["totalViolationsExact"] = bool(total_exact)
        if violations:
            status["violations"] = [
                v.to_dict() for v in violations[: self.violations_limit]
            ]
        else:
            status.pop("violations", None)
        # Status().Update (manager.go:604): constraint CRDs declare the
        # status subresource, so the write must go via .../status
        self.kube.update(latest, check_version=True, subresource="status")
