"""Audit subsystem (reference pkg/audit/)."""

from .manager import AuditManager, StatusViolation

__all__ = ["AuditManager", "StatusViolation"]
