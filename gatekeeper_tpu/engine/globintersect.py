"""Glob-language intersection for ``regex.globs_match``.

The reference evaluates this builtin via a vendored glob-intersection
library (reference: vendor/github.com/open-policy-agent/opa/topdown/
regex.go:119, which delegates to vendor/github.com/yashtewari/
glob-intersection/non_empty.go).  The glob dialect is regex-flavoured:

    token   := CHAR | '.' | '[' set ']'        (CHAR may be '\\'-escaped)
    flagged := token ('+' | '*')?              (at most one flag per token)
    set     := (CHAR | CHAR '-' CHAR)*         ('-' ranges, inclusive)

OPA documents the builtin as "true if the intersection of the two globs
matches a non-empty set of non-empty strings".  We implement exactly that
— each glob is lowered to a small NFA over character classes and the
product automaton is searched for an accepting path of length >= 1 —
rather than re-deriving the vendored library's greedy token-gobbling
scan.  The greedy scan has false negatives (e.g. ``a*`` vs ``a*b*`` is
reported empty even though "a" is in both languages) and answers true
for two empty globs (whose only common string is empty).  Both
divergences-toward-the-documented-spec are listed in docs/rego.md.

Resource bounds (globs may be attacker-derived via AdmissionReview
content): character classes are interval lists, never materialized
per-codepoint (``[\\x20-\\U0010FFFE]`` is one (lo, hi) pair), and two
caps raise GlobLimitError -> whole-query error, failing CLOSED like
net.cidr_expand's expansion cap — a violation rule must not be silenced
(nor the webhook wedged) by a pathological glob:

- FLAGGED_TOKEN_CAP bounds only ``*``/``+``-flagged tokens.  Flags are
  what make the product search expensive (self-loops + epsilon edges);
  unflagged tokens advance both automata in lock-step, so a long
  literal-only glob — a >=65-char image/registry path is routine — is
  linear and must NOT be rejected (the former raw 64-token cap did).
- VISIT_CAP bounds the product-BFS visited set directly, the actual
  resource being protected, so no token-shape argument needs to be
  airtight for the worst case to stay bounded.

Tokenisation validity rules mirror the reference library so that the
same inputs error (and the builtin call becomes undefined): stray ']',
a flag with no preceding token, doubled flags, trailing backslash,
unterminated sets, and malformed '-' ranges are all rejected.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

__all__ = [
    "FLAGGED_TOKEN_CAP",
    "GlobError",
    "GlobLimitError",
    "TOKEN_CAP",
    "TOTAL_TOKEN_CAP",
    "VISIT_CAP",
    "globs_intersect",
]


class GlobError(ValueError):
    """Raised for inputs the glob dialect rejects (-> undefined)."""


class GlobLimitError(ValueError):
    """Raised for globs over the resource cap (-> whole-query error)."""


# Worst-case product-BFS work grows ~quartically in FLAGGED token count
# for adversarial all-starred globs; 64 keeps that under ~100ms while
# being far beyond any real-world match pattern.  Literal tokens do not
# count: they cost O(1) BFS states each.
FLAGGED_TOKEN_CAP = 64
# back-compat alias (the former raw per-token cap carried this name)
TOKEN_CAP = FLAGGED_TOKEN_CAP

# Hard ceiling on product-BFS visited states — the resource actually
# being protected.  (A+1)(B+1)*2 states for token counts A, B: two
# 350-token all-literal globs stay well under it, while an adversarial
# blob that somehow slips the flag cap still terminates in ~ms.
VISIT_CAP = 250_000

# Generous pre-parse bound on TOTAL tokens: without it a multi-MB blob
# of literals allocates millions of token tuples and two multi-million-
# state automata before either cap above can fire.  64k covers any real
# image/registry/path literal by orders of magnitude.
TOTAL_TOKEN_CAP = 65_536

# A character class is None for '.' (any character) or a merged, sorted
# tuple of (lo, hi) inclusive codepoint intervals — possibly empty: the
# literal '[]' admits no character.  A token is (cls, flag) with flag in
# {'', '+', '*'}.
Cls = Optional[Tuple[Tuple[int, int], ...]]
Token = Tuple[Cls, str]

_FLAGS = {"+", "*"}
_DOT: Cls = None


def _merge_intervals(pairs: List[Tuple[int, int]]) -> Cls:
    if not pairs:
        return ()
    pairs.sort()
    out = [pairs[0]]
    for lo, hi in pairs[1:]:
        plo, phi = out[-1]
        if lo <= phi + 1:
            out[-1] = (plo, max(phi, hi))
        else:
            out.append((lo, hi))
    return tuple(out)


def _tokenize(pattern: str) -> List[Token]:
    chars = list(pattern)
    n = len(chars)
    i = 0
    flagged = 0
    out: List[Token] = []
    while i < n:
        c = chars[i]
        escaped = False
        if c == "\\":
            if i + 1 >= n:
                raise GlobError(f"glob {pattern!r}: trailing escape")
            i += 1
            c = chars[i]
            escaped = True
        if not escaped and c == "]":
            raise GlobError(f"glob {pattern!r}: ']' with no preceding '['")
        if not escaped and c in _FLAGS:
            raise GlobError(f"glob {pattern!r}: flag {c!r} must follow a token")
        if not escaped and c == ".":
            cls: Cls = _DOT
            i += 1
        elif not escaped and c == "[":
            cls, i = _scan_set(pattern, chars, i + 1)
        else:
            o = ord(c)
            cls = ((o, o),)
            i += 1
        flag = ""
        if i < n and chars[i] in _FLAGS:
            flag = chars[i]
            i += 1
            flagged += 1
            if flagged > FLAGGED_TOKEN_CAP:
                raise GlobLimitError(
                    f"glob exceeds {FLAGGED_TOKEN_CAP} flagged (*/+) "
                    f"tokens (length {len(pattern)})"
                )
        out.append((cls, flag))
        if len(out) > TOTAL_TOKEN_CAP:
            raise GlobLimitError(
                f"glob exceeds {TOTAL_TOKEN_CAP} tokens "
                f"(length {len(pattern)})"
            )
    return out


def _scan_set(pattern: str, chars: List[str], i: int) -> Tuple[Cls, int]:
    """Scan a '[...]' class body starting just past the '['."""
    n = len(chars)
    pairs: List[Tuple[int, int]] = []
    prev: Optional[str] = None  # last single member, eligible as range start
    while i < n:
        c = chars[i]
        escaped = False
        if c == "\\":
            if i + 1 >= n:
                raise GlobError(f"glob {pattern!r}: trailing escape in set")
            i += 1
            c = chars[i]
            escaped = True
        if not escaped and c == "]":
            return _merge_intervals(pairs), i + 1
        if not escaped and c == "-":
            if prev is None:
                raise GlobError(f"glob {pattern!r}: '-' needs a range start")
            if i + 1 >= n:
                raise GlobError(f"glob {pattern!r}: '-' needs a range end")
            i += 1
            hi = chars[i]
            if hi == "\\":
                if i + 1 >= n:
                    raise GlobError(f"glob {pattern!r}: trailing escape in set")
                i += 1
                hi = chars[i]
            elif hi in ("]", "-"):
                raise GlobError(f"glob {pattern!r}: bad '-' range end {hi!r}")
            if hi < prev:
                raise GlobError(
                    f"glob {pattern!r}: range {prev!r}-{hi!r} out of order"
                )
            pairs.append((ord(prev), ord(hi)))
            prev = None
            i += 1
            continue
        pairs.append((ord(c), ord(c)))
        prev = c
        i += 1
    raise GlobError(f"glob {pattern!r}: '[' without matching ']'")


def _classes_meet(a: Cls, b: Cls) -> bool:
    if a is _DOT:
        return b is _DOT or bool(b)
    if b is _DOT:
        return bool(a)
    # two-pointer sweep over the sorted interval lists
    ia = ib = 0
    while ia < len(a) and ib < len(b):
        alo, ahi = a[ia]
        blo, bhi = b[ib]
        if ahi < blo:
            ia += 1
        elif bhi < alo:
            ib += 1
        else:
            return True
    return False


class _Nfa:
    """NFA over character classes for one glob.

    States are 0..len(tokens); state k sits *before* token k and
    len(tokens) is the sole accepting state.  Consuming edges carry the
    token's class; '*' additionally makes its state skippable (an
    epsilon edge k -> k+1) and both flags add a self-loop so the class
    may repeat ('+' loops on the target state: a+ == a a*).

    Epsilon edges stay EXPLICIT (never closure-expanded): the product
    BFS walks them as zero-cost moves.  Each state has at most 3 raw
    consuming edges, so total BFS work is O(|states_a| * |states_b|) —
    closure expansion would make adversarial all-starred globs
    quartic (the code-review DoS finding).
    """

    def __init__(self, tokens: List[Token]):
        self.n = len(tokens)
        self.accept = self.n
        self.edges: List[List[Tuple[Cls, int]]] = [
            [] for _ in range(self.n + 1)
        ]
        self.eps_next: List[bool] = [False] * (self.n + 1)
        for k, (cls, flag) in enumerate(tokens):
            self.edges[k].append((cls, k + 1))
            if flag == "+":
                self.edges[k + 1].append((cls, k + 1))
            elif flag == "*":
                self.edges[k].append((cls, k))
                self.eps_next[k] = True


def globs_intersect(lhs: str, rhs: str) -> bool:
    """True iff some non-empty string is matched by both globs."""
    a = _Nfa(_tokenize(lhs))
    b = _Nfa(_tokenize(rhs))
    # Product-automaton BFS over (state_a, state_b, consumed) triples,
    # where consumed records whether >= 1 character has been jointly
    # consumed — acceptance only counts with consumed=1, which encodes
    # OPA's documented "non-empty string" requirement.  Epsilon moves
    # advance one side for free and never change consumed.
    start = (0, 0, 0)
    seen = {start}
    stack = [start]
    while stack:
        if len(seen) > VISIT_CAP:
            raise GlobLimitError(
                f"glob intersection exceeds {VISIT_CAP} product states "
                f"(lengths {len(lhs)}, {len(rhs)})"
            )
        p, q, consumed = stack.pop()
        if p == a.accept and q == b.accept and consumed:
            return True
        if a.eps_next[p]:
            t = (p + 1, q, consumed)
            if t not in seen:
                seen.add(t)
                stack.append(t)
        if b.eps_next[q]:
            t = (p, q + 1, consumed)
            if t not in seen:
                seen.add(t)
                stack.append(t)
        for (ca, p2) in a.edges[p]:
            for (cb, q2) in b.edges[q]:
                if not _classes_meet(ca, cb):
                    continue
                t = (p2, q2, 1)
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
    return False
