"""Rego value model.

Values are represented as immutable ("frozen") Python objects so that they can
be hashed (set members, object keys) and unified structurally:

  null    -> None
  boolean -> bool
  number  -> int | float  (arbitrary-precision ints preserved, matching OPA's
             json.Number semantics; see the 10**21 literals in the
             k8scontainerlimits corpus template, reference
             demo/agilebank/templates/k8scontainterlimits_template.yaml)
  string  -> str
  array   -> tuple
  object  -> FrozenDict (key-sorted canonical iteration order)
  set     -> RSet (canonically ordered frozen set)

`UNDEFINED` is the out-of-band marker for undefined expressions; it never
appears inside a frozen document.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator


class _Undefined:
    """Singleton marking an undefined Rego value."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "<undefined>"

    def __bool__(self):
        return False


UNDEFINED = _Undefined()


def _type_rank(v: Any) -> int:
    # Canonical sort order across types, mirroring OPA's ast.Compare:
    # null < false < true < number < string < array < object < set
    if v is None:
        return 0
    if isinstance(v, bool):
        return 1
    if isinstance(v, (int, float)):
        return 2
    if isinstance(v, str):
        return 3
    if isinstance(v, tuple):
        return 4
    if isinstance(v, FrozenDict):
        return 5
    if isinstance(v, RSet):
        return 6
    raise TypeError(f"not a rego value: {type(v)!r}")


def compare(a: Any, b: Any) -> int:
    """Total order over frozen values (OPA ast.Compare semantics)."""
    ra, rb = _type_rank(a), _type_rank(b)
    if ra != rb:
        return -1 if ra < rb else 1
    if ra == 0:
        return 0
    if ra == 1:
        return (a > b) - (a < b)
    if ra == 2:
        return (a > b) - (a < b)
    if ra == 3:
        return (a > b) - (a < b)
    if ra == 4:
        for x, y in zip(a, b):
            c = compare(x, y)
            if c:
                return c
        return (len(a) > len(b)) - (len(a) < len(b))
    if ra == 5:
        ka, kb = a.sorted_keys(), b.sorted_keys()
        for x, y in zip(ka, kb):
            c = compare(x, y)
            if c:
                return c
            c = compare(a[x], b[y])
            if c:
                return c
        return (len(ka) > len(kb)) - (len(ka) < len(kb))
    # set
    ea, eb = a.sorted_items(), b.sorted_items()
    for x, y in zip(ea, eb):
        c = compare(x, y)
        if c:
            return c
    return (len(ea) > len(eb)) - (len(ea) < len(eb))


def values_equal(a: Any, b: Any) -> bool:
    """Type-strict equality (true != 1, unlike raw Python)."""
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, (int, float)) and not isinstance(a, bool):
        if not isinstance(b, (int, float)) or isinstance(b, bool):
            return False
        return a == b
    if type(a) is not type(b) and not (
        isinstance(a, FrozenDict) and isinstance(b, FrozenDict)
    ):
        return False
    return a == b


class FrozenDict:
    """Immutable, hashable mapping with canonical (sorted) key order."""

    __slots__ = ("_d", "_hash", "_sorted")

    def __init__(self, d: dict):
        self._d = d
        self._hash = None
        self._sorted = None

    def __getitem__(self, k):
        return self._d[k]

    def get(self, k, default=None):
        return self._d.get(k, default)

    def __contains__(self, k):
        return k in self._d

    def __len__(self):
        return len(self._d)

    def sorted_keys(self):
        if self._sorted is None:
            import functools

            self._sorted = sorted(self._d.keys(), key=functools.cmp_to_key(compare))
        return self._sorted

    def __iter__(self) -> Iterator:
        return iter(self.sorted_keys())

    def items(self):
        for k in self.sorted_keys():
            yield k, self._d[k]

    def keys(self):
        return self.sorted_keys()

    def values(self):
        for k in self.sorted_keys():
            yield self._d[k]

    def __eq__(self, other):
        if isinstance(other, FrozenDict):
            return self._d == other._d
        return NotImplemented

    def __hash__(self):
        if self._hash is None:
            self._hash = hash(frozenset(self._d.items()))
        return self._hash

    def __repr__(self):
        return "FrozenDict(%r)" % (self._d,)


class RSet:
    """Immutable Rego set with canonical (sorted) iteration order."""

    __slots__ = ("_s", "_hash", "_sorted")

    def __init__(self, items: Iterable = ()):
        self._s = frozenset(items)
        self._hash = None
        self._sorted = None

    def sorted_items(self):
        if self._sorted is None:
            import functools

            self._sorted = sorted(self._s, key=functools.cmp_to_key(compare))
        return self._sorted

    def __iter__(self):
        return iter(self.sorted_items())

    def __len__(self):
        return len(self._s)

    def __contains__(self, v):
        return v in self._s

    def union(self, other: "RSet") -> "RSet":
        return RSet(self._s | other._s)

    def intersection(self, other: "RSet") -> "RSet":
        return RSet(self._s & other._s)

    def difference(self, other: "RSet") -> "RSet":
        return RSet(self._s - other._s)

    def __eq__(self, other):
        if isinstance(other, RSet):
            return self._s == other._s
        return NotImplemented

    def __hash__(self):
        if self._hash is None:
            self._hash = hash(self._s)
        return self._hash

    def __repr__(self):
        return "RSet(%r)" % (self.sorted_items(),)


def _freeze_py(v: Any) -> Any:
    """JSON-like Python value -> frozen Rego value (pure-Python reference;
    the native fast path below is differentially tested against this)."""
    if v is None or isinstance(v, (bool, str)):
        return v
    if isinstance(v, float):
        # Canonicalize integral floats (JSON "1.0") to ints like OPA's
        # json.Number round-trip does for arithmetic purposes.
        if v.is_integer():
            return int(v)
        return v
    if isinstance(v, int):
        return v
    if isinstance(v, (list, tuple)):
        return tuple(_freeze_py(x) for x in v)
    if isinstance(v, (dict, FrozenDict)):
        return FrozenDict({_freeze_py(k): _freeze_py(val) for k, val in v.items()})
    if isinstance(v, (set, frozenset, RSet)):
        return RSet(_freeze_py(x) for x in v)
    raise TypeError(f"cannot freeze {type(v)!r}")


def _resolve_freeze():
    """Prefer the C freeze (native/_gknative.cpp freeze_core): data
    ingestion is ~90% freeze time on the profiled cold path.  Falls back
    to the Python implementation when the extension is unavailable —
    except under GK_NATIVE=require, whose fail-hard contract must not be
    swallowed here (the loader caches failure, so a swallow would poison
    every later load() too)."""
    import os

    try:
        from ..native import load as _load_native

        mod = _load_native()
        if mod is not None and hasattr(mod, "freeze_core"):
            mod.freeze_init(FrozenDict, RSet)
            return mod.freeze_core
        if os.environ.get("GK_NATIVE") == "require":
            raise RuntimeError(
                "GK_NATIVE=require but the loaded extension lacks "
                "freeze_core (stale _gknative.so?)"
            )
    except Exception:
        if os.environ.get("GK_NATIVE") == "require":
            raise
    return _freeze_py


_freeze_impl = None


def freeze(v: Any) -> Any:
    """JSON-like Python value -> frozen Rego value.  Resolves the native
    fast path lazily on first use: resolving at import time would make
    merely importing this module spawn the g++ build subprocess."""
    global _freeze_impl
    if _freeze_impl is None:
        _freeze_impl = _resolve_freeze()
    return _freeze_impl(v)


def _thaw_py(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, tuple):
        return [_thaw_py(x) for x in v]
    if isinstance(v, FrozenDict):
        return {_thaw_py(k): _thaw_py(val) for k, val in v.items()}
    if isinstance(v, RSet):
        return [_thaw_py(x) for x in v.sorted_items()]
    raise TypeError(f"cannot thaw {type(v)!r}")


_thaw_impl = None


def thaw(v: Any) -> Any:
    """Frozen Rego value -> plain JSON-able Python value (sets -> sorted
    lists).  Native fast path (thaw_core) when available: the audit pack
    rebuild thaws every cached object on a cold start, and pure-Python
    recursion dominated warm-restart time.  Resolution mirrors freeze's
    (the same freeze_init registration covers both)."""
    global _thaw_impl
    if _thaw_impl is None:
        global _freeze_impl
        if _freeze_impl is None:
            _freeze_impl = _resolve_freeze()  # registers classes natively
        try:
            from ..native import load as _load_native

            mod = _load_native()
            if mod is not None and hasattr(mod, "thaw_core"):
                _thaw_impl = mod.thaw_core
            else:
                _thaw_impl = _thaw_py
        except Exception:
            _thaw_impl = _thaw_py
    return _thaw_impl(v)


def is_number(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def format_value(v: Any) -> str:
    """OPA-style rendering used by sprintf %v (topdown builtin semantics):
    top-level strings print raw; strings nested in composites print quoted."""
    return _fmt(v, top=True)


def _fmt(v: Any, top: bool = False) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if is_number(v):
        if isinstance(v, float):
            return repr(v)
        return str(v)
    if isinstance(v, str):
        if top:
            return v
        import json

        return json.dumps(v)
    if isinstance(v, tuple):
        return "[" + ", ".join(_fmt(x) for x in v) + "]"
    if isinstance(v, FrozenDict):
        return "{" + ", ".join(f"{_fmt(k)}: {_fmt(val)}" for k, val in v.items()) + "}"
    if isinstance(v, RSet):
        if len(v) == 0:
            return "set()"
        return "{" + ", ".join(_fmt(x) for x in v) + "}"
    raise TypeError(f"cannot format {type(v)!r}")
