from .value import UNDEFINED, FrozenDict, RSet, freeze, thaw  # noqa: F401
