"""Reference interpreter for the Rego subset — the correctness oracle.

This replaces the reference's vendored topdown interpreter
(vendor/github.com/open-policy-agent/opa/topdown/query.go:319) for the
template-policy subset this framework compiles.  The TPU vectorized path
(gatekeeper_tpu.ops) is validated cell-by-cell against this engine.

Evaluation model: generator-based backtracking search.  Bindings are
immutable dicts threaded through generators; every generator yields
`(value, bindings)` (terms) or `bindings` (bodies), so no undo-trail is
needed and early exits are always safe.

Undefined propagation follows OPA: an expression that evaluates to undefined
(missing key, failed builtin, no function clause) fails the body; `not`
succeeds exactly when its operand has no solutions; bodies that evaluate to
`false` fail.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

from ..rego.ast import (
    ArrayCompr,
    ArrayTerm,
    BinOp,
    Body,
    Call,
    Expr,
    Module,
    Node,
    ObjectCompr,
    ObjectTerm,
    Ref,
    RegoCompileError,
    Rule,
    Scalar,
    SetCompr,
    SetTerm,
    UnaryMinus,
    Var,
)
from ..rego.parser import parse_module
from . import builtins as bi
from .value import (
    FrozenDict,
    RSet,
    UNDEFINED,
    compare,
    freeze,
    is_number,
    thaw,
    values_equal,
)

Bindings = Dict[str, Any]


class RegoEvalError(Exception):
    pass


class CompiledModule:
    __slots__ = ("module", "rules")

    def __init__(self, module: Module):
        from ..rego.safety import reorder_module

        module = reorder_module(module)
        self.module = module
        self.rules: Dict[str, List[Rule]] = {}
        for r in module.rules:
            self.rules.setdefault(r.name, []).append(r)


class TemplatePolicy:
    """A compiled ConstraintTemplate policy: the entry module (which must
    define `violation`, mirroring createTemplateArtifacts at
    vendored client/client.go:312-316) plus its libs (packages under lib.*,
    as enforced by the reference's regorewriter)."""

    def __init__(self, main: CompiledModule, libs: Dict[Tuple[str, ...], CompiledModule]):
        self.main = main
        self.libs = libs
        self._arity_cache: Dict[Tuple[int, Tuple[str, ...]], Optional[int]] = {}

    # -- compile ------------------------------------------------------------

    @staticmethod
    def compile(rego_src: str, lib_srcs: Tuple[str, ...] = (), entry: str = "violation") -> "TemplatePolicy":
        main = CompiledModule(parse_module(rego_src))
        if entry not in main.rules:
            raise RegoCompileError(f"template must define a '{entry}' rule")
        libs: Dict[Tuple[str, ...], CompiledModule] = {}
        for src in lib_srcs:
            cm = CompiledModule(parse_module(src))
            if not cm.module.package or cm.module.package[0] != "lib":
                raise RegoCompileError(
                    f"lib package must begin with 'lib', got {'.'.join(cm.module.package)}"
                )
            libs[cm.module.package] = cm
        pol = TemplatePolicy(main, libs)
        pol._validate()
        return pol

    def _validate(self):
        # data refs may only touch data.inventory / data.lib (the reference
        # enforces this via regorewriter externs, client.go:291-299).
        # Records uses_inventory: policies that never read data.inventory
        # have violations that depend only on (review, parameters), which
        # lets evaluators memoize rendered cells across inventory changes.
        self.uses_inventory = False
        # memo_safe: a policy's verdict for a review depends only on the
        # review CONTENT (minus per-request metadata) and parameters.
        # False when the policy (a) calls a wall-clock/random builtin, or
        # (b) may read per-request metadata: input.review.uid, a dynamic
        # index under input.review, or the whole input/input.review value
        # (aliasing defeats static tracking).  Evaluators may cache
        # rendered cells for memo_safe policies keyed on content.
        self.memo_safe = True
        for cm in [self.main, *self.libs.values()]:
            for r in cm.module.rules:
                for node in _walk_rule(r):
                    if isinstance(node, Call) and node.path[:1] in (
                        ("time",), ("rand",), ("uuid",)
                    ):
                        self.memo_safe = False
                    if isinstance(node, Expr) and node.withs:
                        # `with` rebinds documents mid-query; rendered-cell
                        # memoization cannot see through the patches
                        self.memo_safe = False
                        if any(p[:2] == ("data", "inventory") for p, _v in node.withs):
                            self.uses_inventory = True
                    if isinstance(node, Ref) and isinstance(node.head, Var) and node.head.name == "input":
                        ops = node.operands
                        if not ops or not (
                            isinstance(ops[0], Scalar)
                            and ops[0].value in ("review", "parameters")
                        ):
                            self.memo_safe = False  # whole-input aliasing
                        elif ops[0].value == "review":
                            if len(ops) < 2:
                                self.memo_safe = False  # whole-review alias
                            elif not isinstance(ops[1], Scalar):
                                self.memo_safe = False  # dynamic field
                            elif ops[1].value == "uid":
                                self.memo_safe = False
                    if isinstance(node, Ref) and isinstance(node.head, Var) and node.head.name == "data":
                        if not node.operands:
                            raise RegoCompileError("bare 'data' reference not allowed")
                        first = node.operands[0]
                        if not (isinstance(first, Scalar) and first.value in ("inventory", "lib")):
                            raise RegoCompileError(
                                "data references are restricted to data.inventory and data.lib"
                            )
                        if isinstance(first, Scalar) and first.value == "inventory":
                            self.uses_inventory = True
        self._check_recursion()

    def _check_recursion(self):
        # Rule-name call graph (module-local names + data.lib refs), DFS.
        graph: Dict[Tuple[int, str], set] = {}

        def key(cm: CompiledModule, name: str):
            return (id(cm), name)

        def deps(cm: CompiledModule, r: Rule):
            out = set()
            for node in _walk_rule(r):
                if isinstance(node, Var) and node.name in cm.rules:
                    out.add(key(cm, node.name))
                elif isinstance(node, Ref) and isinstance(node.head, Var):
                    if node.head.name in cm.rules:
                        out.add(key(cm, node.head.name))
                    elif node.head.name == "data":
                        t = self._lib_target(node.operands)
                        if t:
                            out.add(key(*t))
                elif isinstance(node, Call):
                    if len(node.path) == 1 and node.path[0] in cm.rules:
                        out.add(key(cm, node.path[0]))
                    elif node.path[0] == "data":
                        t = self._lib_target(tuple(Scalar(p) for p in node.path[1:]))
                        if t:
                            out.add(key(*t))
            return out

        index: Dict[Tuple[int, str], Tuple[CompiledModule, str]] = {}
        for cm in [self.main, *self.libs.values()]:
            for name, rules in cm.rules.items():
                k = key(cm, name)
                index[k] = (cm, name)
                graph[k] = set()
                for r in rules:
                    graph[k] |= deps(cm, r)

        WHITE, GREY, BLACK = 0, 1, 2
        color = {k: WHITE for k in graph}

        def dfs(k, stack):
            color[k] = GREY
            for d in graph.get(k, ()):
                if color.get(d, BLACK) == GREY:
                    cyc = " -> ".join(index[x][1] for x in stack + [k, d])
                    raise RegoCompileError(f"rego_recursion_error: {cyc}")
                if color.get(d, BLACK) == WHITE:
                    dfs(d, stack + [k])
            color[k] = BLACK

        for k in graph:
            if color[k] == WHITE:
                dfs(k, [])

    def _lib_target(self, operands) -> Optional[Tuple[CompiledModule, str]]:
        # data.lib.<pkg...>.<rule> -> (module, rule)
        parts = []
        for op in operands:
            if isinstance(op, Scalar) and isinstance(op.value, str):
                parts.append(op.value)
            else:
                break
        if not parts or parts[0] != "lib":
            return None
        for cut in range(len(parts), 0, -1):
            pkg = tuple(parts[:cut])
            if pkg in self.libs and cut < len(parts):
                return (self.libs[pkg], parts[cut])
        return None

    # -- public evaluation API ---------------------------------------------

    def eval_violations(self, review: Any, parameters: Any, inventory: Any) -> List[Any]:
        """Evaluate the template's `violation` rule with
        input={"review": ..., "parameters": ...} and data.inventory bound,
        mirroring the hook shim (vendored client/regolib/src.go:23-41).
        Returns thawed violation objects (dicts with at least "msg")."""
        inp = freeze({"review": review, "parameters": parameters})
        ctx = QueryContext(self, inp, freeze(inventory) if not _is_frozen(inventory) else inventory)
        ext = ctx.partial_set_extent(self.main, "violation")
        return [thaw(v) for v in ext]

    def eval_rule(self, name: str, input_value: Any, inventory: Any = None) -> Any:
        """Generic entry for tests: returns a complete rule's value or a
        partial set rule's extent (thawed)."""
        ctx = QueryContext(self, freeze(input_value), freeze(inventory))
        rules = self.main.rules.get(name)
        if not rules:
            return UNDEFINED
        if rules[0].is_partial_set:
            return thaw(ctx.partial_set_extent(self.main, name))
        v = ctx.complete_value(self.main, name)
        return thaw(v) if v is not UNDEFINED else UNDEFINED


_ARITY_MISS = object()  # cache sentinel: None is a valid cached arity


def _upsert_path(doc: Any, segs: Tuple[str, ...], v: Any) -> Any:
    """Functional deep-set for `with` patches: replaces the value at segs,
    creating object levels as needed (OPA inserts missing paths into base
    documents)."""
    if not segs:
        return v
    base = doc if isinstance(doc, FrozenDict) else FrozenDict({})
    out = {k: base[k] for k in base.keys()}
    cur = base.get(segs[0], UNDEFINED)
    out[segs[0]] = _upsert_path(
        cur if cur is not UNDEFINED else FrozenDict({}), segs[1:], v
    )
    return FrozenDict(out)


def _is_frozen(v):
    return v is None or isinstance(v, (bool, int, float, str, tuple, FrozenDict, RSet))


def _walk_pairs(path: Tuple[Any, ...], v: Any) -> Iterator[Tuple[Any, Any]]:
    """Depth-first [path, value] enumeration for the walk builtin."""
    yield (path, v)
    if isinstance(v, FrozenDict):
        for k in v.keys():
            yield from _walk_pairs(path + (k,), v[k])
    elif isinstance(v, tuple):
        for i, item in enumerate(v):
            yield from _walk_pairs(path + (i,), item)
    elif isinstance(v, RSet):
        for item in v:
            yield from _walk_pairs(path + (item,), item)


def _walk_rule(r: Rule):
    stack: List[Node] = []
    clause: Optional[Rule] = r
    while clause is not None:  # head clause + its else chain
        if clause.args:
            stack.extend(clause.args)
        if clause.key is not None:
            stack.append(clause.key)
        if clause.value is not None:
            stack.append(clause.value)
        for e in clause.body:
            stack.append(e)  # type: ignore[arg-type]
        clause = clause.els
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, Expr):
            stack.extend(n.terms)  # type: ignore[arg-type]
            for _path, v in n.withs:
                stack.append(v)
        elif isinstance(n, Ref):
            stack.append(n.head)
            stack.extend(n.operands)
        elif isinstance(n, Call):
            stack.extend(n.args)
        elif isinstance(n, (ArrayTerm, SetTerm)):
            stack.extend(n.items)
        elif isinstance(n, ObjectTerm):
            for k, v in n.pairs:
                stack.append(k)
                stack.append(v)
        elif isinstance(n, (ArrayCompr, SetCompr)):
            stack.append(n.head)
            stack.extend(n.body)  # type: ignore[arg-type]
        elif isinstance(n, ObjectCompr):
            stack.append(n.key)
            stack.append(n.value)
            stack.extend(n.body)  # type: ignore[arg-type]
        elif isinstance(n, BinOp):
            stack.append(n.lhs)
            stack.append(n.rhs)
        elif isinstance(n, UnaryMinus):
            stack.append(n.operand)


class QueryContext:
    """Per-query evaluation state: input document, data.inventory, and
    memoization caches (complete-rule values, partial extents, function
    results) — the analogue of one topdown Query."""

    __slots__ = ("policy", "input", "inventory", "_complete", "_extent", "_func", "_depth")

    MAX_DEPTH = 256

    def __init__(self, policy: TemplatePolicy, input_value: Any, inventory: Any):
        self.policy = policy
        self.input = input_value
        self.inventory = inventory if inventory is not None else UNDEFINED
        self._complete: Dict[Tuple[int, str], Any] = {}
        self._extent: Dict[Tuple[int, str], Any] = {}
        self._func: Dict[Tuple[int, str, Tuple], Any] = {}
        self._depth = 0
        # a query boundary: OPA evaluates wall-clock builtins once per
        # query (every time.now_ns() in this evaluation sees one instant)
        bi.bump_query_epoch()

    # ---- rule evaluation --------------------------------------------------

    def complete_value(self, cm: CompiledModule, name: str) -> Any:
        key = (id(cm), name)
        if key in self._complete:
            return self._complete[key]
        self._complete[key] = UNDEFINED  # recursion guard (compile also checks)
        result = UNDEFINED
        default = UNDEFINED
        for r in cm.rules[name]:
            if r.is_default:
                default = next(self.eval_term(cm, r.value, {}))[0]
                continue
            got = self._clause_chain_value(cm, r, {})
            if got is UNDEFINED:
                continue
            if result is not UNDEFINED and not values_equal(result, got):
                # OPA topdown: eval_conflict_error (complete rules must
                # not produce multiple outputs).  Deliberately aborts the
                # WHOLE query, not just this template: the reference
                # evaluates all templates in one Rego query, so a conflict
                # anywhere errors the entire Review (rego.Eval err through
                # local.go:302-324 -> client.go:763 -> webhook 500)
                raise RegoEvalError(
                    f"eval_conflict_error: complete rules must not produce "
                    f"multiple outputs (rule '{name}')"
                )
            result = got
        if result is UNDEFINED:
            result = default
        self._complete[key] = result
        return result

    def _clause_chain_value(
        self, cm: CompiledModule, r: Rule, bindings: Bindings,
        what: str = "complete rules",
    ) -> Any:
        """Evaluate a clause and its `else` chain: the first clause whose
        body succeeds provides the value (true when the head has none).
        ALL body bindings of that clause are folded — different head
        values across bindings are OPA's eval_conflict_error, not
        first-wins."""
        clause: Optional[Rule] = r
        while clause is not None:
            if clause.value is None:
                for _b in self.eval_body(cm, clause.body, 0, bindings):
                    return True  # boolean head: every binding agrees
                clause = clause.els
                continue
            found = UNDEFINED
            for b in self.eval_body(cm, clause.body, 0, bindings):
                got = next(self.eval_term(cm, clause.value, b), None)
                if got is None:
                    continue
                if found is not UNDEFINED and not values_equal(found, got[0]):
                    raise RegoEvalError(
                        f"eval_conflict_error: {what} must not produce "
                        f"multiple outputs (rule '{clause.name}')"
                    )
                found = got[0]
            if found is not UNDEFINED:
                return found
            clause = clause.els
        return UNDEFINED

    def partial_set_extent(self, cm: CompiledModule, name: str) -> RSet:
        key = (id(cm), name)
        if key in self._extent:
            return self._extent[key]
        items = set()
        for r in cm.rules[name]:
            if not r.is_partial_set:
                continue
            for b in self.eval_body(cm, r.body, 0, {}):
                for v, _b2 in self.eval_term(cm, r.key, b):
                    items.add(v)
        ext = RSet(items)
        self._extent[key] = ext
        return ext

    def partial_object_extent(self, cm: CompiledModule, name: str) -> FrozenDict:
        key = (id(cm), "obj:" + name)
        if key in self._extent:
            return self._extent[key]
        out: Dict[Any, Any] = {}
        for r in cm.rules[name]:
            if not r.is_partial_object:
                continue
            for b in self.eval_body(cm, r.body, 0, {}):
                for k, b2 in self.eval_term(cm, r.key, b):
                    for v, _ in self.eval_term(cm, r.value, b2):
                        if k in out and not values_equal(out[k], v):
                            # OPA: object keys must be unique
                            raise RegoEvalError(
                                f"eval_conflict_error: object keys must be "
                                f"unique (rule '{name}', key {k!r})"
                            )
                        out[k] = v
        ext = FrozenDict(out)
        self._extent[key] = ext
        return ext

    def rule_document(self, cm: CompiledModule, name: str) -> Any:
        """Value of a rule as a document: complete value, set extent, or
        object extent."""
        rules = cm.rules[name]
        r0 = rules[0]
        if r0.is_partial_set:
            return self.partial_set_extent(cm, name)
        if r0.is_partial_object:
            return self.partial_object_extent(cm, name)
        if r0.is_function:
            raise RegoEvalError(f"function '{name}' used as a document")
        return self.complete_value(cm, name)

    def call_function(self, cm: CompiledModule, name: str, args: Tuple[Any, ...]) -> Any:
        key = (id(cm), name, args)
        if key in self._func:
            return self._func[key]
        result = UNDEFINED
        for r in cm.rules[name]:
            if not r.is_function or len(r.args) != len(args):
                continue
            for b in self._unify_params(cm, r.args, args, {}):
                got = self._clause_chain_value(cm, r, b, what="functions")
                if got is UNDEFINED:
                    continue
                if result is not UNDEFINED and not values_equal(result, got):
                    # OPA: functions must not produce multiple outputs
                    # for the same inputs
                    raise RegoEvalError(
                        f"eval_conflict_error: functions must not produce "
                        f"multiple outputs for same inputs ('{name}')"
                    )
                result = got
        self._func[key] = result
        return result

    def _unify_params(self, cm, params, args, b) -> Iterator[Bindings]:
        def go(i, b):
            if i == len(params):
                yield b
                return
            for b2 in self.unify_pattern(cm, params[i], args[i], b):
                yield from go(i + 1, b2)

        yield from go(0, b)

    # ---- body / expression evaluation -------------------------------------

    def eval_body(self, cm: CompiledModule, body: Body, i: int, b: Bindings) -> Iterator[Bindings]:
        if i == len(body):
            yield b
            return
        for b2 in self.eval_expr(cm, body[i], b):
            yield from self.eval_body(cm, body, i + 1, b2)

    def _eval_with(self, cm: CompiledModule, e: Expr, b: Bindings) -> Iterator[Bindings]:
        """`with` modifiers (OPA v0.21 scope: input and base documents; the
        inventory is this engine's only base document).  Values resolve
        under the CURRENT context/bindings; the base literal then runs in a
        child context carrying the patched documents with fresh rule caches
        (cached rule values may depend on the patched docs).  The query
        clock is shared — `with` does not start a new query."""
        base = Expr(e.kind, e.terms, e.loc)

        def go(i, binds, inp, inv):
            if i == len(e.withs):
                child = self._child_context(inp, inv)
                yield from child.eval_expr(cm, base, binds)
                return
            path, vterm = e.withs[i]
            for v, b2 in self.eval_term(cm, vterm, binds):
                if path[0] == "input":
                    yield from go(i + 1, b2, _upsert_path(inp, path[1:], v), inv)
                else:  # ("data", "inventory", ...)
                    yield from go(i + 1, b2, inp, _upsert_path(inv, path[2:], v))

        yield from go(0, b, self.input, self.inventory)

    def _child_context(self, input_value: Any, inventory: Any) -> "QueryContext":
        child = QueryContext.__new__(QueryContext)
        child.policy = self.policy
        child.input = input_value
        child.inventory = inventory
        child._complete = {}
        child._extent = {}
        child._func = {}
        child._depth = self._depth
        return child

    def eval_expr(self, cm: CompiledModule, e: Expr, b: Bindings) -> Iterator[Bindings]:
        if e.withs:
            yield from self._eval_with(cm, e, b)
            return
        if e.kind == "some":
            yield b
            return
        if e.kind == "not":
            inner = e.terms[0]
            for _ in self.eval_expr(cm, inner, b):
                return
            yield b
            return
        if e.kind in ("unify", "assign"):
            yield from self.unify(cm, e.terms[0], e.terms[1], b)
            return
        # plain term: defined and not false
        for v, b2 in self.eval_term(cm, e.terms[0], b):
            if v is not False and v is not UNDEFINED:
                yield b2

    # ---- unification ------------------------------------------------------

    def unify(self, cm: CompiledModule, ta: Node, tb: Node, b: Bindings) -> Iterator[Bindings]:
        if isinstance(ta, Var) and ta.name not in b and not self._is_rule_var(cm, ta):
            for v, b2 in self.eval_term(cm, tb, b):
                yield self._bind(b2, ta, v)
            return
        if isinstance(tb, Var) and tb.name not in b and not self._is_rule_var(cm, tb):
            for v, b2 in self.eval_term(cm, ta, b):
                yield self._bind(b2, tb, v)
            return
        if isinstance(ta, (ArrayTerm, ObjectTerm)) and self._has_unbound(cm, ta, b):
            for v, b2 in self.eval_term(cm, tb, b):
                yield from self.unify_pattern(cm, ta, v, b2)
            return
        if isinstance(tb, (ArrayTerm, ObjectTerm)) and self._has_unbound(cm, tb, b):
            for v, b2 in self.eval_term(cm, ta, b):
                yield from self.unify_pattern(cm, tb, v, b2)
            return
        for va, b2 in self.eval_term(cm, ta, b):
            for vb, b3 in self.eval_term(cm, tb, b2):
                if values_equal(va, vb):
                    yield b3

    def unify_pattern(self, cm: CompiledModule, pat: Node, value: Any, b: Bindings) -> Iterator[Bindings]:
        """Unify a term pattern against a concrete value."""
        if isinstance(pat, Var):
            if pat.is_wildcard:
                yield b
                return
            if pat.name in b:
                if values_equal(b[pat.name], value):
                    yield b
                return
            if self._is_rule_var(cm, pat):
                doc = self.rule_document(cm, pat.name)
                if doc is not UNDEFINED and values_equal(doc, value):
                    yield b
                return
            yield self._bind(b, pat, value)
            return
        if isinstance(pat, Scalar):
            if values_equal(freeze(pat.value), value):
                yield b
            return
        if isinstance(pat, ArrayTerm):
            if not isinstance(value, tuple) or len(value) != len(pat.items):
                return

            def go_arr(i, b):
                if i == len(pat.items):
                    yield b
                    return
                for b2 in self.unify_pattern(cm, pat.items[i], value[i], b):
                    yield from go_arr(i + 1, b2)

            yield from go_arr(0, b)
            return
        if isinstance(pat, ObjectTerm):
            if not isinstance(value, FrozenDict) or len(value) != len(pat.pairs):
                return

            def go_obj(i, b):
                if i == len(pat.pairs):
                    yield b
                    return
                kt, vt = pat.pairs[i]
                got = next(self.eval_term(cm, kt, b), None)
                if got is None:
                    return
                k, b2 = got
                if k not in value:
                    return
                for b3 in self.unify_pattern(cm, vt, value[k], b2):
                    yield from go_obj(i + 1, b3)

            yield from go_obj(0, b)
            return
        # evaluable pattern (ref/call/binop/set/scalar composite)
        for v, b2 in self.eval_term(cm, pat, b):
            if values_equal(v, value):
                yield b2

    def _bind(self, b: Bindings, var: Var, val: Any) -> Bindings:
        if var.is_wildcard:
            return b
        b2 = dict(b)
        b2[var.name] = val
        return b2

    def _is_rule_var(self, cm: CompiledModule, v: Var) -> bool:
        return v.name in cm.rules

    def _has_unbound(self, cm: CompiledModule, t: Node, b: Bindings) -> bool:
        if isinstance(t, Var):
            return (
                t.name not in b
                and t.name not in ("input", "data")
                and not self._is_rule_var(cm, t)
            )
        if isinstance(t, ArrayTerm) or isinstance(t, SetTerm):
            return any(self._has_unbound(cm, x, b) for x in t.items)
        if isinstance(t, ObjectTerm):
            return any(
                self._has_unbound(cm, k, b) or self._has_unbound(cm, v, b)
                for k, v in t.pairs
            )
        return False

    # ---- term evaluation --------------------------------------------------

    def eval_term(self, cm: CompiledModule, t: Node, b: Bindings) -> Iterator[Tuple[Any, Bindings]]:
        if isinstance(t, Scalar):
            yield freeze(t.value), b
            return
        if isinstance(t, Var):
            if t.name in b:
                yield b[t.name], b
                return
            if t.name == "input":
                if self.input is not UNDEFINED:
                    yield self.input, b
                return
            if self._is_rule_var(cm, t):
                doc = self.rule_document(cm, t.name)
                if doc is not UNDEFINED:
                    yield doc, b
                return
            raise RegoEvalError(f"unsafe variable: {t.name}")
        if isinstance(t, Ref):
            yield from self._eval_ref(cm, t, b)
            return
        if isinstance(t, Call):
            yield from self._eval_call(cm, t, b)
            return
        if isinstance(t, BinOp):
            yield from self._eval_binop(cm, t, b)
            return
        if isinstance(t, UnaryMinus):
            for v, b2 in self.eval_term(cm, t.operand, b):
                if is_number(v):
                    yield -v, b2
            return
        if isinstance(t, ArrayTerm):
            yield from self._eval_product(cm, t.items, b, lambda vs: tuple(vs))
            return
        if isinstance(t, SetTerm):
            yield from self._eval_product(cm, t.items, b, lambda vs: RSet(vs))
            return
        if isinstance(t, ObjectTerm):
            flat: List[Node] = []
            for k, v in t.pairs:
                flat.append(k)
                flat.append(v)

            def mk_obj(vs):
                d = {}
                for i in range(0, len(vs), 2):
                    d[vs[i]] = vs[i + 1]
                return FrozenDict(d)

            yield from self._eval_product(cm, tuple(flat), b, mk_obj)
            return
        if isinstance(t, ArrayCompr):
            out = []
            for b2 in self.eval_body(cm, t.body, 0, b):
                got = next(self.eval_term(cm, t.head, b2), None)
                if got is not None:
                    out.append(got[0])
            yield tuple(out), b
            return
        if isinstance(t, SetCompr):
            items = set()
            for b2 in self.eval_body(cm, t.body, 0, b):
                got = next(self.eval_term(cm, t.head, b2), None)
                if got is not None:
                    items.add(got[0])
            yield RSet(items), b
            return
        if isinstance(t, ObjectCompr):
            d: Dict[Any, Any] = {}
            for b2 in self.eval_body(cm, t.body, 0, b):
                gk = next(self.eval_term(cm, t.key, b2), None)
                if gk is None:
                    continue
                gv = next(self.eval_term(cm, t.value, gk[1]), None)
                if gv is None:
                    continue
                d[gk[0]] = gv[0]
            yield FrozenDict(d), b
            return
        raise RegoEvalError(f"cannot evaluate {type(t).__name__}")

    def _eval_product(self, cm, terms, b, mk):
        def go(i, acc, b):
            if i == len(terms):
                yield mk(acc), b
                return
            for v, b2 in self.eval_term(cm, terms[i], b):
                yield from go(i + 1, acc + [v], b2)

        yield from go(0, [], b)

    # ---- refs -------------------------------------------------------------

    def _eval_ref(self, cm: CompiledModule, t: Ref, b: Bindings) -> Iterator[Tuple[Any, Bindings]]:
        head = t.head
        if isinstance(head, Var):
            name = head.name
            if name in b:
                yield from self._walk(cm, b[name], t.operands, 0, b)
                return
            if name == "input":
                if self.input is UNDEFINED:
                    return
                yield from self._walk(cm, self.input, t.operands, 0, b)
                return
            if name == "data":
                yield from self._eval_data_ref(cm, t.operands, b)
                return
            if self._is_rule_var(cm, head):
                doc = self.rule_document(cm, name)
                if doc is UNDEFINED:
                    return
                yield from self._walk(cm, doc, t.operands, 0, b)
                return
            raise RegoEvalError(f"unsafe variable: {name}")
        # head is itself a term (call result / literal being indexed)
        for base, b2 in self.eval_term(cm, head, b):
            yield from self._walk(cm, base, t.operands, 0, b2)

    def _eval_data_ref(self, cm: CompiledModule, operands, b) -> Iterator[Tuple[Any, Bindings]]:
        if not operands:
            return
        first = operands[0]
        if isinstance(first, Scalar) and first.value == "inventory":
            if self.inventory is UNDEFINED:
                return
            yield from self._walk(cm, self.inventory, operands[1:], 0, b)
            return
        if isinstance(first, Scalar) and first.value == "lib":
            parts = []
            for op in operands:
                if isinstance(op, Scalar) and isinstance(op.value, str):
                    parts.append(op.value)
                else:
                    break
            for cut in range(len(parts), 0, -1):
                pkg = tuple(parts[:cut])
                libm = self.policy.libs.get(pkg)
                if libm is None:
                    continue
                if cut >= len(operands):
                    return  # bare package reference: not a document
                rule_name = parts[cut] if cut < len(parts) else None
                if rule_name is None or rule_name not in libm.rules:
                    return
                doc = self.rule_document(libm, rule_name)
                if doc is UNDEFINED:
                    return
                yield from self._walk(cm, doc, operands[cut + 1 :], 0, b)
                return
            return
        return  # other data roots are undefined (compile blocks them anyway)

    def _walk(self, cm, value, operands, i, b) -> Iterator[Tuple[Any, Bindings]]:
        if value is UNDEFINED:
            return
        if i == len(operands):
            yield value, b
            return
        op = operands[i]
        is_pattern = self._has_unbound(cm, op, b)
        if isinstance(value, FrozenDict):
            if is_pattern:
                for k in value.sorted_keys():
                    for b2 in self.unify_pattern(cm, op, k, b):
                        yield from self._walk(cm, value[k], operands, i + 1, b2)
            else:
                for k, b2 in self.eval_term(cm, op, b):
                    if k in value:
                        yield from self._walk(cm, value[k], operands, i + 1, b2)
            return
        if isinstance(value, tuple):
            if is_pattern:
                for idx, item in enumerate(value):
                    for b2 in self.unify_pattern(cm, op, idx, b):
                        yield from self._walk(cm, item, operands, i + 1, b2)
            else:
                for k, b2 in self.eval_term(cm, op, b):
                    if is_number(k) and not isinstance(k, bool):
                        idx = int(k)
                        if 0 <= idx < len(value):
                            yield from self._walk(cm, value[idx], operands, i + 1, b2)
            return
        if isinstance(value, RSet):
            if is_pattern:
                for item in value.sorted_items():
                    for b2 in self.unify_pattern(cm, op, item, b):
                        yield from self._walk(cm, item, operands, i + 1, b2)
            else:
                for k, b2 in self.eval_term(cm, op, b):
                    if k in value:
                        yield from self._walk(cm, k, operands, i + 1, b2)
            return
        return  # scalars are not indexable -> undefined

    # ---- calls ------------------------------------------------------------

    def _eval_call(self, cm: CompiledModule, t: Call, b: Bindings) -> Iterator[Tuple[Any, Bindings]]:
        self._depth += 1
        try:
            if self._depth > self.MAX_DEPTH:
                raise RegoEvalError("max evaluation depth exceeded")
            if t.path == ("walk",):
                yield from self._eval_walk(cm, t, b)
                return
            arity = self._call_arity(cm, t.path)
            if arity is not None and len(t.args) == arity + 1:
                # output-argument form: f(in..., out) unifies out with the
                # result (OPA allows this for every function; topdown
                # rewrites it to out = f(in...))
                for argv, b2 in self._eval_product(
                    cm, t.args[:-1], b, lambda vs: tuple(vs)
                ):
                    result = self._dispatch_call(cm, t.path, argv)
                    if result is not UNDEFINED:
                        for b3 in self.unify_pattern(cm, t.args[-1], result, b2):
                            yield True, b3
                return
            for argv, b2 in self._eval_product(cm, t.args, b, lambda vs: tuple(vs)):
                result = self._dispatch_call(cm, t.path, argv)
                if result is not UNDEFINED:
                    yield result, b2
        finally:
            self._depth -= 1

    def _call_arity(self, cm: CompiledModule, path: Tuple[str, ...]) -> Optional[int]:
        """Declared input arity of a builtin or user function, or None.
        Memoized on the policy: the answer is static per (module, path)
        and this sits on the interpreter's hottest path (every call in
        every rule body).  Dict get/set are atomic and the value is
        deterministic, so the shared cache is thread-safe."""
        cache = self.policy._arity_cache
        key = (id(cm), path)
        hit = cache.get(key, _ARITY_MISS)
        if hit is not _ARITY_MISS:
            return hit
        arity = self._call_arity_uncached(cm, path)
        cache[key] = arity
        return arity

    def _call_arity_uncached(self, cm: CompiledModule, path: Tuple[str, ...]) -> Optional[int]:
        if len(path) == 1 and path[0] in cm.rules:
            for r in cm.rules[path[0]]:
                if r.is_function:
                    return len(r.args or ())
            return None
        if path[0] == "data" and len(path) > 2 and path[1] == "lib":
            parts = path[1:]
            for cut in range(len(parts) - 1, 0, -1):
                libm = self.policy.libs.get(tuple(parts[:cut]))
                if libm is not None and parts[cut] in libm.rules:
                    for r in libm.rules[parts[cut]]:
                        if r.is_function:
                            return len(r.args or ())
                    return None
            return None
        fn = bi.lookup(path)
        if fn is None:
            return None
        # declared at @builtin registration; never introspect __code__
        # (builtins with *args/defaults would misreport)
        return fn._rego_arity

    def _eval_walk(self, cm: CompiledModule, t: Call, b: Bindings) -> Iterator[Tuple[Any, Bindings]]:
        """`walk` is OPA's only relational builtin: walk(x) enumerates
        [path, value] pairs over every nested element of x; walk(x, pat)
        unifies each pair with pat (topdown/walk.go semantics)."""
        if len(t.args) not in (1, 2):
            raise RegoEvalError("walk: expects 1 or 2 arguments")
        for doc, b2 in self.eval_term(cm, t.args[0], b):
            for pair in _walk_pairs((), doc):
                if len(t.args) == 1:
                    yield pair, b2
                else:
                    for b3 in self.unify_pattern(cm, t.args[1], pair, b2):
                        yield True, b3

    def _dispatch_call(self, cm: CompiledModule, path: Tuple[str, ...], args: Tuple[Any, ...]) -> Any:
        if len(path) == 1 and path[0] in cm.rules:
            return self.call_function(cm, path[0], args)
        if path[0] == "data":
            if len(path) > 2 and path[1] == "lib":
                parts = path[1:]
                for cut in range(len(parts) - 1, 0, -1):
                    pkg = tuple(parts[:cut])
                    libm = self.policy.libs.get(pkg)
                    if libm is not None and parts[cut] in libm.rules:
                        return self.call_function(libm, parts[cut], args)
            return UNDEFINED
        fn = bi.lookup(path)
        if fn is None:
            raise RegoEvalError(f"unknown function {'.'.join(path)}")
        try:
            out = fn(*args)
        except bi.BuiltinError:
            return UNDEFINED
        except (TypeError, ValueError, ZeroDivisionError):
            return UNDEFINED
        return freeze(out) if isinstance(out, (list, dict, set)) else out

    # ---- operators --------------------------------------------------------

    def _eval_binop(self, cm: CompiledModule, t: BinOp, b: Bindings) -> Iterator[Tuple[Any, Bindings]]:
        op = t.op
        for va, b2 in self.eval_term(cm, t.lhs, b):
            for vb, b3 in self.eval_term(cm, t.rhs, b2):
                r = _apply_binop(op, va, vb)
                if r is not UNDEFINED:
                    yield r, b3


def _apply_binop(op: str, a: Any, b: Any) -> Any:
    if op == "==":
        return values_equal(a, b)
    if op == "!=":
        return not values_equal(a, b)
    if op in ("<", "<=", ">", ">="):
        c = compare(a, b)
        return {"<": c < 0, "<=": c <= 0, ">": c > 0, ">=": c >= 0}[op]
    if isinstance(a, RSet) and isinstance(b, RSet):
        if op == "-":
            return a.difference(b)
        if op == "|":
            return a.union(b)
        if op == "&":
            return a.intersection(b)
        return UNDEFINED
    if is_number(a) and is_number(b):
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            if b == 0:
                return UNDEFINED
            r = a / b
            return int(r) if isinstance(r, float) and r.is_integer() else r
        if op == "%":
            if b == 0 or isinstance(a, float) or isinstance(b, float):
                return UNDEFINED
            return a % b
    return UNDEFINED
