"""Reference bug-compatibility switch (GK_BUG_COMPAT).

This engine deliberately diverges from the reference/OPA in a few
documented places (docs/rego.md "Known divergences") where the reference
behavior is a bug or a DoS hazard.  Deployments migrating from the
reference sometimes need the old behavior bit-for-bit; GK_BUG_COMPAT=1
switches the divergences that can be emulated safely:

- ``regex.globs_match("", "")`` answers **true** (the vendored
  glob-intersection library's answer for two empty globs; default: false,
  since the only shared string is empty and OPA documents "non-empty").
- ``bits.rsh`` accepts arbitrarily large shift counts and computes the
  exact result (a right shift only shrinks; default: counts above 2^20
  raise the fail-closed limit error).
- ``bits.lsh`` over-cap counts degrade to a plain builtin error
  (expression undefined — OPA's error contract never aborts the query)
  instead of the fail-closed whole-query error.  The magnitude cap itself
  stays: materializing a shifted-by-10^9 integer is an allocation bomb no
  compat flag should re-enable.

The greedy-scan **false negatives** of the vendored library
(``"a*"`` vs ``"a*b*"`` -> false there, though ``"a"`` is in both glob
languages) are NOT emulated: reproducing the library's scan bug-for-bug
would mean vendoring the bug, and a false negative only ever *widens*
what a policy permits.  The divergence is pinned by explicit assertions
instead (tests/test_bug_compat.py), so a silent behavior drift fails CI.

The flag is read per call (cheap: one dict lookup) so tests can flip it
without re-importing; production sets it once in the environment.
"""

from __future__ import annotations

import os


def bug_compat_enabled() -> bool:
    return os.environ.get("GK_BUG_COMPAT", "0") == "1"
