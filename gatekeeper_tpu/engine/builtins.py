"""Builtin function registry for the Rego subset.

Covers the builtin surface used by the reference's policy corpus
(SURVEY.md section 2.3: sprintf, count, concat, substring, replace, re_match,
endswith, startswith, to_number, is_*, split, contains, any/all, array.concat,
trim, sort) plus a few neighbours that cost nothing to support.

Builtin errors (bad types, division by zero, ...) make the calling expression
undefined, matching OPA's non-strict topdown behavior: raise BuiltinError and
the interpreter converts it into evaluation failure.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict

from .value import FrozenDict, RSet, UNDEFINED, compare, format_value, is_number


class BuiltinError(Exception):
    pass


REGISTRY: Dict[tuple, Callable] = {}


def builtin(*path: str):
    def deco(fn):
        REGISTRY[path] = fn
        return fn

    return deco


def _need(cond: bool, msg: str):
    if not cond:
        raise BuiltinError(msg)


# --------------------------------------------------------------------------
# Strings
# --------------------------------------------------------------------------


@builtin("sprintf")
def _sprintf(fmt: Any, args: Any):
    _need(isinstance(fmt, str), "sprintf: format must be string")
    _need(isinstance(args, tuple), "sprintf: args must be array")
    out = []
    ai = 0
    i, n = 0, len(fmt)
    while i < n:
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        i += 1
        if i < n and fmt[i] == "%":
            out.append("%")
            i += 1
            continue
        # skip flags/width/precision
        j = i
        while j < n and fmt[j] in "+-# 0123456789.":
            j += 1
        if j >= n:
            raise BuiltinError("sprintf: bad format")
        verb = fmt[j]
        spec = fmt[i:j]
        i = j + 1
        if ai >= len(args):
            out.append("%!" + verb + "(MISSING)")
            continue
        arg = args[ai]
        ai += 1
        if verb == "v" or verb == "s":
            out.append(format_value(arg))
        elif verb == "d":
            _need(is_number(arg), "sprintf: %d expects number")
            out.append(("%" + spec + "d") % int(arg))
        elif verb in "feg":
            _need(is_number(arg), "sprintf: %f expects number")
            out.append(("%" + spec + verb) % float(arg))
        elif verb == "x":
            out.append(("%" + spec + "x") % int(arg))
        elif verb == "t":
            out.append("true" if arg is True else "false")
        else:
            out.append(format_value(arg))
    return "".join(out)


@builtin("concat")
def _concat(delim: Any, coll: Any):
    _need(isinstance(delim, str), "concat: delimiter must be string")
    _need(isinstance(coll, (tuple, RSet)), "concat: collection must be array/set")
    items = list(coll)
    _need(all(isinstance(x, str) for x in items), "concat: elements must be strings")
    return delim.join(items)


@builtin("substring")
def _substring(s: Any, start: Any, length: Any):
    _need(isinstance(s, str), "substring: not a string")
    _need(is_number(start) and is_number(length), "substring: bad offsets")
    start, length = int(start), int(length)
    _need(start >= 0, "substring: negative start")
    if length < 0:
        return s[start:]
    return s[start : start + length]


@builtin("replace")
def _replace(s: Any, old: Any, new: Any):
    _need(
        isinstance(s, str) and isinstance(old, str) and isinstance(new, str),
        "replace: args must be strings",
    )
    return s.replace(old, new)


@builtin("trim")
def _trim(s: Any, cutset: Any):
    _need(isinstance(s, str) and isinstance(cutset, str), "trim: args must be strings")
    return s.strip(cutset)


@builtin("trim_left")
def _trim_left(s, cutset):
    _need(isinstance(s, str) and isinstance(cutset, str), "trim_left: strings")
    return s.lstrip(cutset)


@builtin("trim_right")
def _trim_right(s, cutset):
    _need(isinstance(s, str) and isinstance(cutset, str), "trim_right: strings")
    return s.rstrip(cutset)


@builtin("trim_prefix")
def _trim_prefix(s, prefix):
    _need(isinstance(s, str) and isinstance(prefix, str), "trim_prefix: strings")
    return s[len(prefix) :] if s.startswith(prefix) else s


@builtin("trim_suffix")
def _trim_suffix(s, suffix):
    _need(isinstance(s, str) and isinstance(suffix, str), "trim_suffix: strings")
    return s[: -len(suffix)] if suffix and s.endswith(suffix) else s


@builtin("split")
def _split(s: Any, delim: Any):
    _need(isinstance(s, str) and isinstance(delim, str), "split: args must be strings")
    return tuple(s.split(delim))


@builtin("contains")
def _contains(s: Any, sub: Any):
    _need(isinstance(s, str) and isinstance(sub, str), "contains: args must be strings")
    return sub in s


@builtin("startswith")
def _startswith(s: Any, prefix: Any):
    _need(isinstance(s, str) and isinstance(prefix, str), "startswith: strings")
    return s.startswith(prefix)


@builtin("endswith")
def _endswith(s: Any, suffix: Any):
    _need(isinstance(s, str) and isinstance(suffix, str), "endswith: strings")
    return s.endswith(suffix)


@builtin("lower")
def _lower(s: Any):
    _need(isinstance(s, str), "lower: not a string")
    return s.lower()


@builtin("upper")
def _upper(s: Any):
    _need(isinstance(s, str), "upper: not a string")
    return s.upper()


@builtin("format_int")
def _format_int(x: Any, base: Any):
    _need(is_number(x) and is_number(base), "format_int: numbers")
    digits = "0123456789abcdef"
    base = int(base)
    _need(base in (2, 8, 10, 16), "format_int: bad base")
    v = int(x)
    if v == 0:
        return "0"
    neg = v < 0
    v = abs(v)
    out = []
    while v:
        out.append(digits[v % base])
        v //= base
    return ("-" if neg else "") + "".join(reversed(out))


@builtin("indexof")
def _indexof(s: Any, sub: Any):
    _need(isinstance(s, str) and isinstance(sub, str), "indexof: strings")
    return s.find(sub)


# --------------------------------------------------------------------------
# Regex (Go RE2 syntax; Python re is a close superset for the corpus)
# --------------------------------------------------------------------------


def _compile_re(pattern: str):
    try:
        return re.compile(pattern)
    except re.error as e:
        raise BuiltinError(f"re_match: bad pattern: {e}")


@builtin("re_match")
@builtin("regex", "match")
def _re_match(pattern: Any, value: Any):
    _need(isinstance(pattern, str) and isinstance(value, str), "re_match: strings")
    return _compile_re(pattern).search(value) is not None


@builtin("regex", "split")
def _regex_split(pattern: Any, value: Any):
    _need(isinstance(pattern, str) and isinstance(value, str), "regex.split: strings")
    return tuple(_compile_re(pattern).split(value))


# --------------------------------------------------------------------------
# Aggregates
# --------------------------------------------------------------------------


@builtin("count")
def _count(x: Any):
    if isinstance(x, (str, tuple, RSet, FrozenDict)):
        return len(x)
    raise BuiltinError("count: not a collection or string")


@builtin("sum")
def _sum(x: Any):
    _need(isinstance(x, (tuple, RSet)), "sum: not a collection")
    items = list(x)
    _need(all(is_number(i) for i in items), "sum: non-numeric element")
    return sum(items)


@builtin("product")
def _product(x: Any):
    _need(isinstance(x, (tuple, RSet)), "product: not a collection")
    out = 1
    for i in x:
        _need(is_number(i), "product: non-numeric element")
        out *= i
    return out


@builtin("max")
def _max(x: Any):
    _need(isinstance(x, (tuple, RSet)) and len(x) > 0, "max: empty or not collection")
    import functools

    return sorted(x, key=functools.cmp_to_key(compare))[-1]


@builtin("min")
def _min(x: Any):
    _need(isinstance(x, (tuple, RSet)) and len(x) > 0, "min: empty or not collection")
    import functools

    return sorted(x, key=functools.cmp_to_key(compare))[0]


@builtin("sort")
def _sort(x: Any):
    _need(isinstance(x, (tuple, RSet)), "sort: not a collection")
    import functools

    return tuple(sorted(x, key=functools.cmp_to_key(compare)))


@builtin("all")
def _all(x: Any):
    _need(isinstance(x, (tuple, RSet)), "all: not a collection")
    return all(v is True for v in x)


@builtin("any")
def _any(x: Any):
    _need(isinstance(x, (tuple, RSet)), "any: not a collection")
    return any(v is True for v in x)


@builtin("abs")
def _abs(x: Any):
    _need(is_number(x), "abs: not a number")
    return abs(x)


@builtin("round")
def _round(x: Any):
    _need(is_number(x), "round: not a number")
    import math

    return int(math.floor(x + 0.5))


# --------------------------------------------------------------------------
# Types / conversion
# --------------------------------------------------------------------------


@builtin("to_number")
def _to_number(x: Any):
    if x is None:
        return 0
    if x is True:
        return 1
    if x is False:
        return 0
    if is_number(x):
        return x
    if isinstance(x, str):
        try:
            if re.fullmatch(r"-?\d+", x):
                return int(x)
            v = float(x)
            return int(v) if v.is_integer() else v
        except ValueError:
            raise BuiltinError(f"to_number: invalid {x!r}")
    raise BuiltinError("to_number: bad type")


@builtin("is_number")
def _is_number(x: Any):
    return is_number(x)


@builtin("is_string")
def _is_string(x: Any):
    return isinstance(x, str)


@builtin("is_boolean")
def _is_boolean(x: Any):
    return isinstance(x, bool)


@builtin("is_array")
def _is_array(x: Any):
    return isinstance(x, tuple)


@builtin("is_object")
def _is_object(x: Any):
    return isinstance(x, FrozenDict)


@builtin("is_set")
def _is_set(x: Any):
    return isinstance(x, RSet)


@builtin("is_null")
def _is_null(x: Any):
    return x is None


@builtin("type_name")
def _type_name(x: Any):
    if x is None:
        return "null"
    if isinstance(x, bool):
        return "boolean"
    if is_number(x):
        return "number"
    if isinstance(x, str):
        return "string"
    if isinstance(x, tuple):
        return "array"
    if isinstance(x, FrozenDict):
        return "object"
    if isinstance(x, RSet):
        return "set"
    raise BuiltinError("type_name: unknown")


# --------------------------------------------------------------------------
# Arrays / objects / sets
# --------------------------------------------------------------------------


@builtin("array", "concat")
def _array_concat(a: Any, b: Any):
    _need(isinstance(a, tuple) and isinstance(b, tuple), "array.concat: arrays")
    return a + b


@builtin("array", "slice")
def _array_slice(a: Any, start: Any, stop: Any):
    _need(isinstance(a, tuple), "array.slice: not an array")
    start = max(0, int(start))
    stop = min(len(a), int(stop))
    return a[start:stop] if start <= stop else ()


@builtin("object", "get")
def _object_get(obj: Any, key: Any, default: Any):
    _need(isinstance(obj, FrozenDict), "object.get: not an object")
    return obj.get(key, default)


@builtin("intersection")
def _intersection(xs: Any):
    _need(isinstance(xs, RSet) and len(xs) > 0, "intersection: set of sets")
    items = list(xs)
    out = items[0]
    for s in items[1:]:
        _need(isinstance(s, RSet), "intersection: set of sets")
        out = out.intersection(s)
    return out


@builtin("union")
def _union(xs: Any):
    _need(isinstance(xs, RSet), "union: set of sets")
    out = RSet()
    for s in xs:
        _need(isinstance(s, RSet), "union: set of sets")
        out = out.union(s)
    return out


# --------------------------------------------------------------------------
# JSON / encoding
# --------------------------------------------------------------------------


@builtin("json", "marshal")
def _json_marshal(x: Any):
    import json

    from .value import thaw

    return json.dumps(thaw(x), separators=(",", ":"), sort_keys=True)


@builtin("json", "unmarshal")
def _json_unmarshal(s: Any):
    import json

    from .value import freeze

    _need(isinstance(s, str), "json.unmarshal: not a string")
    try:
        return freeze(json.loads(s))
    except json.JSONDecodeError as e:
        raise BuiltinError(f"json.unmarshal: {e}")


@builtin("base64", "encode")
def _b64_encode(s: Any):
    import base64

    _need(isinstance(s, str), "base64.encode: not a string")
    return base64.b64encode(s.encode()).decode()


@builtin("base64", "decode")
def _b64_decode(s: Any):
    import base64

    _need(isinstance(s, str), "base64.decode: not a string")
    try:
        return base64.b64decode(s.encode()).decode()
    except Exception as e:
        raise BuiltinError(f"base64.decode: {e}")


# --------------------------------------------------------------------------
# Library-template neighbours: builtins common in the public
# gatekeeper-library policies (units.parse_bytes is what K8sContainerLimits
# canonifies memory quantities with)
# --------------------------------------------------------------------------

_UNIT_FACTORS = {
    "": 1,
    "k": 10 ** 3, "m": 10 ** 6, "g": 10 ** 9, "t": 10 ** 12,
    "p": 10 ** 15, "e": 10 ** 18,
    "ki": 2 ** 10, "mi": 2 ** 20, "gi": 2 ** 30, "ti": 2 ** 40,
    "pi": 2 ** 50, "ei": 2 ** 60,
}


@builtin("units", "parse_bytes")
def _units_parse_bytes(s: Any):
    """OPA units.parse_bytes: "1Gi" -> 2^30 etc (case-insensitive units,
    optional trailing "b")."""
    _need(isinstance(s, str), "units.parse_bytes: not a string")
    txt = s.strip().strip('"')
    m = re.fullmatch(r"([+-]?(?:\d+\.?\d*|\.\d+))([A-Za-z]*)", txt)
    _need(m is not None, f"units.parse_bytes: could not parse {s!r}")
    num, unit = m.group(1), m.group(2).lower()
    if unit.endswith("b"):
        unit = unit[:-1]
    _need(unit in _UNIT_FACTORS,
          f"units.parse_bytes: could not parse {s!r}")
    try:
        value = float(num)
    except ValueError:
        raise BuiltinError(f"units.parse_bytes: bad number in {s!r}")
    out = value * _UNIT_FACTORS[unit]
    return int(out) if float(out).is_integer() else out


@builtin("object", "union")
def _object_union(a: Any, b: Any):
    _need(isinstance(a, FrozenDict) and isinstance(b, FrozenDict),
          "object.union: not objects")

    def rec(x, y):
        if isinstance(x, FrozenDict) and isinstance(y, FrozenDict):
            out = dict(x._d)
            for k, v in y._d.items():
                out[k] = rec(out[k], v) if k in out else v
            return FrozenDict(out)
        return y

    return rec(a, b)


@builtin("object", "keys")
def _object_keys(o: Any):
    _need(isinstance(o, FrozenDict), "object.keys: not an object")
    return RSet(o._d.keys())


@builtin("cast_array")
def _cast_array(x: Any):
    import functools

    if isinstance(x, tuple):
        return x
    if isinstance(x, RSet):
        return tuple(sorted(x, key=functools.cmp_to_key(compare)))
    raise BuiltinError("cast_array: not an array or set")


@builtin("trim_space")
def _trim_space(s: Any):
    _need(isinstance(s, str), "trim_space: not a string")
    return s.strip()


@builtin("numbers", "range")
def _numbers_range(a: Any, b: Any):
    _need(is_number(a) and is_number(b), "numbers.range: not numbers")
    _need(float(a).is_integer() and float(b).is_integer(),
          "numbers.range: operands must be integers")
    a, b = int(a), int(b)
    step = 1 if b >= a else -1
    return tuple(range(a, b + step, step))


@builtin("glob", "match")
def _glob_match(pattern: Any, delimiters: Any, match: Any):
    """OPA glob.match: explicit separators limit * like a path glob; an
    EMPTY delimiters array defaults to ["."], while a null delimiters
    argument means separator-free matching (* crosses everything) — OPA
    topdown glob semantics.  ** always crosses separators; character
    classes support glob negation [!...]."""
    _need(isinstance(pattern, str) and isinstance(match, str),
          "glob.match: pattern and match must be strings")
    if delimiters is None:
        delims = []  # null: no separators, * crosses everything
    else:
        _need(isinstance(delimiters, tuple), "glob.match: delimiters array")
        delims = [d for d in delimiters if isinstance(d, str)]
        if not delims:
            delims = ["."]  # OPA: EMPTY delimiters default to ["."]
    sep = "".join(re.escape(d) for d in delims)
    out = []
    i, n = 0, len(pattern)
    while i < n:
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
            else:
                out.append(f"[^{sep}]*" if sep else ".*")
                i += 1
        elif c == "?":
            out.append(f"[^{sep}]" if sep else ".")
            i += 1
        elif c == "[":
            j = pattern.find("]", i + 1)
            if j == -1:
                out.append(re.escape(c))
                i += 1
            else:
                cls = pattern[i:j + 1]
                if cls.startswith("[!"):
                    cls = "[^" + cls[2:]  # glob negation -> regex negation
                out.append(cls)
                i = j + 1
        else:
            out.append(re.escape(c))
            i += 1
    return re.fullmatch("".join(out), match) is not None


@builtin("strings", "replace_n")
def _strings_replace_n(patterns: Any, s: Any):
    _need(isinstance(patterns, FrozenDict) and isinstance(s, str),
          "strings.replace_n: (object, string)")
    keys = []
    for k in patterns.sorted_keys():  # Rego objects iterate in key order
        _need(isinstance(k, str) and isinstance(patterns[k], str),
              "strings.replace_n: non-string mapping")
        if k:
            keys.append(k)
    # single left-to-right pass like Go's strings.Replacer (OPA topdown):
    # replacement OUTPUT is never re-replaced; at a given position the
    # first matching pattern in key order wins
    out = []
    i = 0
    while i < len(s):
        for k in keys:
            if s.startswith(k, i):
                out.append(patterns[k])
                i += len(k)
                break
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


@builtin("json", "is_valid")
def _json_is_valid(s: Any):
    import json

    _need(isinstance(s, str), "json.is_valid: not a string")
    try:
        json.loads(s)
        return True
    except (json.JSONDecodeError, RecursionError):
        return False


@builtin("semver", "compare")
def _semver_compare(a: Any, b: Any):
    _need(isinstance(a, str) and isinstance(b, str),
          "semver.compare: not strings")

    def parse(v):
        core = v.split("+", 1)[0]
        core, _, pre = core.partition("-")
        parts = core.split(".")
        _need(len(parts) == 3, f"semver.compare: bad version {v!r}")
        try:
            nums = tuple(int(p) for p in parts)
        except ValueError:
            raise BuiltinError(f"semver.compare: bad version {v!r}")
        return nums, pre

    na, pa = parse(a)
    nb, pb = parse(b)
    if na != nb:
        return -1 if na < nb else 1
    # a pre-release sorts before the release proper; pre-release tags
    # compare per dot-separated identifier (semver spec item 11: numeric
    # identifiers numerically and below alphanumeric ones)
    if pa == pb:
        return 0
    if pa == "":
        return 1
    if pb == "":
        return -1

    def ids(pre):
        out = []
        for part in pre.split("."):
            out.append((0, int(part), "") if part.isdigit() else (1, 0, part))
        return out

    ia, ib = ids(pa), ids(pb)
    for xa, xb in zip(ia, ib):
        if xa != xb:
            return -1 if xa < xb else 1
    if len(ia) != len(ib):  # more identifiers = higher precedence
        return -1 if len(ia) < len(ib) else 1
    return 0


# per-query clock cache: OPA evaluates time.now_ns once per query so every
# call within one evaluation sees the same instant.  THREAD-LOCAL: each
# query runs on one thread, and concurrent admission reviews (the webhook
# server is threaded) must not bump each other's epoch.  The interpreter
# bumps the epoch at each query boundary (interp.QueryContext).
import threading as _threading

_NOW_TLS = _threading.local()


def bump_query_epoch():
    _NOW_TLS.epoch = getattr(_NOW_TLS, "epoch", 0) + 1


@builtin("time", "now_ns")
def _time_now_ns():
    import time

    epoch = getattr(_NOW_TLS, "epoch", 0)
    if getattr(_NOW_TLS, "seen", None) != epoch:
        _NOW_TLS.seen = epoch
        _NOW_TLS.ns = time.time_ns()
    return _NOW_TLS.ns


def lookup(path: tuple):
    return REGISTRY.get(path)
