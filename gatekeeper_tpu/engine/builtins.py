"""Builtin function registry for the Rego subset.

Covers the builtin surface used by the reference's policy corpus
(SURVEY.md section 2.3: sprintf, count, concat, substring, replace, re_match,
endswith, startswith, to_number, is_*, split, contains, any/all, array.concat,
trim, sort) plus a few neighbours that cost nothing to support.

Builtin errors (bad types, division by zero, ...) make the calling expression
undefined, matching OPA's non-strict topdown behavior: raise BuiltinError and
the interpreter converts it into evaluation failure.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict

from .value import FrozenDict, RSet, UNDEFINED, compare, format_value, is_number


class BuiltinError(Exception):
    """Makes the calling expression undefined (OPA non-strict topdown)."""


class BuiltinLimitError(Exception):
    """Engine resource limit exceeded: propagates as a whole-query error
    (fail closed, like max evaluation depth) instead of the silent
    builtin-error -> undefined path — a policy hitting a capacity cap
    must not quietly stop firing its violation rules."""


REGISTRY: Dict[tuple, Callable] = {}


def builtin(*path: str, arity: int = None):
    """Register a builtin under `path`, stamping its declared input arity
    as fn._rego_arity.  The interpreter's output-argument dispatch and the
    safety reorderer read the stamp instead of introspecting __code__, so
    a builtin written with *args or defaults cannot silently misreport —
    such functions must pass arity= explicitly or registration fails."""

    def deco(fn):
        if arity is None:
            code = fn.__code__
            if (code.co_flags & 0x04) or fn.__defaults__:
                raise TypeError(
                    f"builtin {'.'.join(path)}: uses *args/defaults; "
                    "declare arity= explicitly"
                )
            fn._rego_arity = code.co_argcount
        else:
            fn._rego_arity = arity
        REGISTRY[path] = fn
        return fn

    return deco


def _need(cond: bool, msg: str):
    if not cond:
        raise BuiltinError(msg)


# --------------------------------------------------------------------------
# Strings
# --------------------------------------------------------------------------


@builtin("sprintf")
def _sprintf(fmt: Any, args: Any):
    _need(isinstance(fmt, str), "sprintf: format must be string")
    _need(isinstance(args, tuple), "sprintf: args must be array")
    out = []
    ai = 0
    i, n = 0, len(fmt)
    while i < n:
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        i += 1
        if i < n and fmt[i] == "%":
            out.append("%")
            i += 1
            continue
        # skip flags/width/precision
        j = i
        while j < n and fmt[j] in "+-# 0123456789.":
            j += 1
        if j >= n:
            raise BuiltinError("sprintf: bad format")
        verb = fmt[j]
        spec = fmt[i:j]
        i = j + 1
        if ai >= len(args):
            out.append("%!" + verb + "(MISSING)")
            continue
        arg = args[ai]
        ai += 1
        if verb == "v" or verb == "s":
            out.append(format_value(arg))
        elif verb == "d":
            _need(is_number(arg), "sprintf: %d expects number")
            out.append(("%" + spec + "d") % int(arg))
        elif verb in "feg":
            _need(is_number(arg), "sprintf: %f expects number")
            out.append(("%" + spec + verb) % float(arg))
        elif verb == "x":
            out.append(("%" + spec + "x") % int(arg))
        elif verb == "t":
            out.append("true" if arg is True else "false")
        else:
            out.append(format_value(arg))
    return "".join(out)


@builtin("concat")
def _concat(delim: Any, coll: Any):
    _need(isinstance(delim, str), "concat: delimiter must be string")
    _need(isinstance(coll, (tuple, RSet)), "concat: collection must be array/set")
    items = list(coll)
    _need(all(isinstance(x, str) for x in items), "concat: elements must be strings")
    return delim.join(items)


@builtin("substring")
def _substring(s: Any, start: Any, length: Any):
    _need(isinstance(s, str), "substring: not a string")
    _need(is_number(start) and is_number(length), "substring: bad offsets")
    start, length = int(start), int(length)
    _need(start >= 0, "substring: negative start")
    if length < 0:
        return s[start:]
    return s[start : start + length]


@builtin("replace")
def _replace(s: Any, old: Any, new: Any):
    _need(
        isinstance(s, str) and isinstance(old, str) and isinstance(new, str),
        "replace: args must be strings",
    )
    return s.replace(old, new)


@builtin("trim")
def _trim(s: Any, cutset: Any):
    _need(isinstance(s, str) and isinstance(cutset, str), "trim: args must be strings")
    return s.strip(cutset)


@builtin("trim_left")
def _trim_left(s, cutset):
    _need(isinstance(s, str) and isinstance(cutset, str), "trim_left: strings")
    return s.lstrip(cutset)


@builtin("trim_right")
def _trim_right(s, cutset):
    _need(isinstance(s, str) and isinstance(cutset, str), "trim_right: strings")
    return s.rstrip(cutset)


@builtin("trim_prefix")
def _trim_prefix(s, prefix):
    _need(isinstance(s, str) and isinstance(prefix, str), "trim_prefix: strings")
    return s[len(prefix) :] if s.startswith(prefix) else s


@builtin("trim_suffix")
def _trim_suffix(s, suffix):
    _need(isinstance(s, str) and isinstance(suffix, str), "trim_suffix: strings")
    return s[: -len(suffix)] if suffix and s.endswith(suffix) else s


@builtin("split")
def _split(s: Any, delim: Any):
    _need(isinstance(s, str) and isinstance(delim, str), "split: args must be strings")
    return tuple(s.split(delim))


@builtin("contains")
def _contains(s: Any, sub: Any):
    _need(isinstance(s, str) and isinstance(sub, str), "contains: args must be strings")
    return sub in s


@builtin("startswith")
def _startswith(s: Any, prefix: Any):
    _need(isinstance(s, str) and isinstance(prefix, str), "startswith: strings")
    return s.startswith(prefix)


@builtin("endswith")
def _endswith(s: Any, suffix: Any):
    _need(isinstance(s, str) and isinstance(suffix, str), "endswith: strings")
    return s.endswith(suffix)


@builtin("lower")
def _lower(s: Any):
    _need(isinstance(s, str), "lower: not a string")
    return s.lower()


@builtin("upper")
def _upper(s: Any):
    _need(isinstance(s, str), "upper: not a string")
    return s.upper()


@builtin("format_int")
def _format_int(x: Any, base: Any):
    _need(is_number(x) and is_number(base), "format_int: numbers")
    digits = "0123456789abcdef"
    base = int(base)
    _need(base in (2, 8, 10, 16), "format_int: bad base")
    v = int(x)
    if v == 0:
        return "0"
    neg = v < 0
    v = abs(v)
    out = []
    while v:
        out.append(digits[v % base])
        v //= base
    return ("-" if neg else "") + "".join(reversed(out))


@builtin("indexof")
def _indexof(s: Any, sub: Any):
    _need(isinstance(s, str) and isinstance(sub, str), "indexof: strings")
    return s.find(sub)


# --------------------------------------------------------------------------
# Regex (Go RE2 syntax; Python re is a close superset for the corpus)
# --------------------------------------------------------------------------


def _compile_re(pattern: str):
    try:
        return re.compile(pattern)
    except re.error as e:
        raise BuiltinError(f"re_match: bad pattern: {e}")


@builtin("re_match")
@builtin("regex", "match")
def _re_match(pattern: Any, value: Any):
    _need(isinstance(pattern, str) and isinstance(value, str), "re_match: strings")
    return _compile_re(pattern).search(value) is not None


@builtin("regex", "split")
def _regex_split(pattern: Any, value: Any):
    _need(isinstance(pattern, str) and isinstance(value, str), "regex.split: strings")
    return tuple(_compile_re(pattern).split(value))


# --------------------------------------------------------------------------
# Aggregates
# --------------------------------------------------------------------------


@builtin("count")
def _count(x: Any):
    if isinstance(x, (str, tuple, RSet, FrozenDict)):
        return len(x)
    raise BuiltinError("count: not a collection or string")


@builtin("sum")
def _sum(x: Any):
    _need(isinstance(x, (tuple, RSet)), "sum: not a collection")
    items = list(x)
    _need(all(is_number(i) for i in items), "sum: non-numeric element")
    return sum(items)


@builtin("product")
def _product(x: Any):
    _need(isinstance(x, (tuple, RSet)), "product: not a collection")
    out = 1
    for i in x:
        _need(is_number(i), "product: non-numeric element")
        out *= i
    return out


@builtin("max")
def _max(x: Any):
    _need(isinstance(x, (tuple, RSet)) and len(x) > 0, "max: empty or not collection")
    import functools

    return sorted(x, key=functools.cmp_to_key(compare))[-1]


@builtin("min")
def _min(x: Any):
    _need(isinstance(x, (tuple, RSet)) and len(x) > 0, "min: empty or not collection")
    import functools

    return sorted(x, key=functools.cmp_to_key(compare))[0]


@builtin("sort")
def _sort(x: Any):
    _need(isinstance(x, (tuple, RSet)), "sort: not a collection")
    import functools

    return tuple(sorted(x, key=functools.cmp_to_key(compare)))


@builtin("all")
def _all(x: Any):
    _need(isinstance(x, (tuple, RSet)), "all: not a collection")
    return all(v is True for v in x)


@builtin("any")
def _any(x: Any):
    _need(isinstance(x, (tuple, RSet)), "any: not a collection")
    return any(v is True for v in x)


@builtin("abs")
def _abs(x: Any):
    _need(is_number(x), "abs: not a number")
    return abs(x)


@builtin("round")
def _round(x: Any):
    _need(is_number(x), "round: not a number")
    import math

    return int(math.floor(x + 0.5))


# --------------------------------------------------------------------------
# Types / conversion
# --------------------------------------------------------------------------


@builtin("to_number")
def _to_number(x: Any):
    if x is None:
        return 0
    if x is True:
        return 1
    if x is False:
        return 0
    if is_number(x):
        return x
    if isinstance(x, str):
        try:
            if re.fullmatch(r"-?\d+", x):
                return int(x)
            v = float(x)
            return int(v) if v.is_integer() else v
        except ValueError:
            raise BuiltinError(f"to_number: invalid {x!r}")
    raise BuiltinError("to_number: bad type")


@builtin("is_number")
def _is_number(x: Any):
    return is_number(x)


@builtin("is_string")
def _is_string(x: Any):
    return isinstance(x, str)


@builtin("is_boolean")
def _is_boolean(x: Any):
    return isinstance(x, bool)


@builtin("is_array")
def _is_array(x: Any):
    return isinstance(x, tuple)


@builtin("is_object")
def _is_object(x: Any):
    return isinstance(x, FrozenDict)


@builtin("is_set")
def _is_set(x: Any):
    return isinstance(x, RSet)


@builtin("is_null")
def _is_null(x: Any):
    return x is None


@builtin("type_name")
def _type_name(x: Any):
    if x is None:
        return "null"
    if isinstance(x, bool):
        return "boolean"
    if is_number(x):
        return "number"
    if isinstance(x, str):
        return "string"
    if isinstance(x, tuple):
        return "array"
    if isinstance(x, FrozenDict):
        return "object"
    if isinstance(x, RSet):
        return "set"
    raise BuiltinError("type_name: unknown")


# --------------------------------------------------------------------------
# Arrays / objects / sets
# --------------------------------------------------------------------------


@builtin("array", "concat")
def _array_concat(a: Any, b: Any):
    _need(isinstance(a, tuple) and isinstance(b, tuple), "array.concat: arrays")
    return a + b


@builtin("array", "slice")
def _array_slice(a: Any, start: Any, stop: Any):
    _need(isinstance(a, tuple), "array.slice: not an array")
    start = max(0, int(start))
    stop = min(len(a), int(stop))
    return a[start:stop] if start <= stop else ()


@builtin("object", "get")
def _object_get(obj: Any, key: Any, default: Any):
    _need(isinstance(obj, FrozenDict), "object.get: not an object")
    return obj.get(key, default)


@builtin("intersection")
def _intersection(xs: Any):
    _need(isinstance(xs, RSet) and len(xs) > 0, "intersection: set of sets")
    items = list(xs)
    out = items[0]
    for s in items[1:]:
        _need(isinstance(s, RSet), "intersection: set of sets")
        out = out.intersection(s)
    return out


@builtin("union")
def _union(xs: Any):
    _need(isinstance(xs, RSet), "union: set of sets")
    out = RSet()
    for s in xs:
        _need(isinstance(s, RSet), "union: set of sets")
        out = out.union(s)
    return out


# --------------------------------------------------------------------------
# JSON / encoding
# --------------------------------------------------------------------------


@builtin("json", "marshal")
def _json_marshal(x: Any):
    import json

    from .value import thaw

    return json.dumps(thaw(x), separators=(",", ":"), sort_keys=True)


@builtin("json", "unmarshal")
def _json_unmarshal(s: Any):
    import json

    from .value import freeze

    _need(isinstance(s, str), "json.unmarshal: not a string")
    try:
        return freeze(json.loads(s))
    except json.JSONDecodeError as e:
        raise BuiltinError(f"json.unmarshal: {e}")


@builtin("base64", "encode")
def _b64_encode(s: Any):
    import base64

    _need(isinstance(s, str), "base64.encode: not a string")
    return base64.b64encode(s.encode()).decode()


@builtin("base64", "decode")
def _b64_decode(s: Any):
    import base64

    _need(isinstance(s, str), "base64.decode: not a string")
    try:
        return base64.b64decode(s.encode()).decode()
    except Exception as e:
        raise BuiltinError(f"base64.decode: {e}")


# --------------------------------------------------------------------------
# Library-template neighbours: builtins common in the public
# gatekeeper-library policies (units.parse_bytes is what K8sContainerLimits
# canonifies memory quantities with)
# --------------------------------------------------------------------------

_UNIT_FACTORS = {
    "": 1,
    "k": 10 ** 3, "m": 10 ** 6, "g": 10 ** 9, "t": 10 ** 12,
    "p": 10 ** 15, "e": 10 ** 18,
    "ki": 2 ** 10, "mi": 2 ** 20, "gi": 2 ** 30, "ti": 2 ** 40,
    "pi": 2 ** 50, "ei": 2 ** 60,
}


@builtin("units", "parse_bytes")
def _units_parse_bytes(s: Any):
    """OPA units.parse_bytes: "1Gi" -> 2^30 etc (case-insensitive units,
    optional trailing "b")."""
    _need(isinstance(s, str), "units.parse_bytes: not a string")
    txt = s.strip().strip('"')
    m = re.fullmatch(r"([+-]?(?:\d+\.?\d*|\.\d+))([A-Za-z]*)", txt)
    _need(m is not None, f"units.parse_bytes: could not parse {s!r}")
    num, unit = m.group(1), m.group(2).lower()
    if unit.endswith("b"):
        unit = unit[:-1]
    _need(unit in _UNIT_FACTORS,
          f"units.parse_bytes: could not parse {s!r}")
    try:
        value = float(num)
    except ValueError:
        raise BuiltinError(f"units.parse_bytes: bad number in {s!r}")
    out = value * _UNIT_FACTORS[unit]
    return int(out) if float(out).is_integer() else out


@builtin("object", "union")
def _object_union(a: Any, b: Any):
    _need(isinstance(a, FrozenDict) and isinstance(b, FrozenDict),
          "object.union: not objects")

    def rec(x, y):
        if isinstance(x, FrozenDict) and isinstance(y, FrozenDict):
            out = dict(x._d)
            for k, v in y._d.items():
                out[k] = rec(out[k], v) if k in out else v
            return FrozenDict(out)
        return y

    return rec(a, b)


@builtin("object", "keys")
def _object_keys(o: Any):
    _need(isinstance(o, FrozenDict), "object.keys: not an object")
    return RSet(o._d.keys())


@builtin("cast_array")
def _cast_array(x: Any):
    import functools

    if isinstance(x, tuple):
        return x
    if isinstance(x, RSet):
        return tuple(sorted(x, key=functools.cmp_to_key(compare)))
    raise BuiltinError("cast_array: not an array or set")


@builtin("trim_space")
def _trim_space(s: Any):
    _need(isinstance(s, str), "trim_space: not a string")
    return s.strip()


@builtin("numbers", "range")
def _numbers_range(a: Any, b: Any):
    _need(is_number(a) and is_number(b), "numbers.range: not numbers")
    _need(float(a).is_integer() and float(b).is_integer(),
          "numbers.range: operands must be integers")
    a, b = int(a), int(b)
    step = 1 if b >= a else -1
    return tuple(range(a, b + step, step))


@builtin("glob", "match")
def _glob_match(pattern: Any, delimiters: Any, match: Any):
    """OPA glob.match: explicit separators limit * like a path glob; an
    EMPTY delimiters array defaults to ["."], while a null delimiters
    argument means separator-free matching (* crosses everything) — OPA
    topdown glob semantics.  ** always crosses separators; character
    classes support glob negation [!...]."""
    _need(isinstance(pattern, str) and isinstance(match, str),
          "glob.match: pattern and match must be strings")
    if delimiters is None:
        delims = []  # null: no separators, * crosses everything
    else:
        _need(isinstance(delimiters, tuple), "glob.match: delimiters array")
        delims = [d for d in delimiters if isinstance(d, str)]
        if not delims:
            delims = ["."]  # OPA: EMPTY delimiters default to ["."]
    sep = "".join(re.escape(d) for d in delims)
    out = []
    i, n = 0, len(pattern)
    while i < n:
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
            else:
                out.append(f"[^{sep}]*" if sep else ".*")
                i += 1
        elif c == "?":
            out.append(f"[^{sep}]" if sep else ".")
            i += 1
        elif c == "[":
            j = pattern.find("]", i + 1)
            if j == -1:
                out.append(re.escape(c))
                i += 1
            else:
                cls = pattern[i:j + 1]
                if cls.startswith("[!"):
                    cls = "[^" + cls[2:]  # glob negation -> regex negation
                out.append(cls)
                i = j + 1
        else:
            out.append(re.escape(c))
            i += 1
    return re.fullmatch("".join(out), match) is not None


@builtin("strings", "replace_n")
def _strings_replace_n(patterns: Any, s: Any):
    _need(isinstance(patterns, FrozenDict) and isinstance(s, str),
          "strings.replace_n: (object, string)")
    keys = []
    for k in patterns.sorted_keys():  # Rego objects iterate in key order
        _need(isinstance(k, str) and isinstance(patterns[k], str),
              "strings.replace_n: non-string mapping")
        if k:
            keys.append(k)
    # single left-to-right pass like Go's strings.Replacer (OPA topdown):
    # replacement OUTPUT is never re-replaced; at a given position the
    # first matching pattern in key order wins
    out = []
    i = 0
    while i < len(s):
        for k in keys:
            if s.startswith(k, i):
                out.append(patterns[k])
                i += len(k)
                break
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


@builtin("json", "is_valid")
def _json_is_valid(s: Any):
    import json

    _need(isinstance(s, str), "json.is_valid: not a string")
    try:
        json.loads(s)
        return True
    except (json.JSONDecodeError, RecursionError):
        return False


@builtin("semver", "compare")
def _semver_compare(a: Any, b: Any):
    _need(isinstance(a, str) and isinstance(b, str),
          "semver.compare: not strings")

    def parse(v):
        core = v.split("+", 1)[0]
        core, _, pre = core.partition("-")
        parts = core.split(".")
        _need(len(parts) == 3, f"semver.compare: bad version {v!r}")
        try:
            nums = tuple(int(p) for p in parts)
        except ValueError:
            raise BuiltinError(f"semver.compare: bad version {v!r}")
        return nums, pre

    na, pa = parse(a)
    nb, pb = parse(b)
    if na != nb:
        return -1 if na < nb else 1
    # a pre-release sorts before the release proper; pre-release tags
    # compare per dot-separated identifier (semver spec item 11: numeric
    # identifiers numerically and below alphanumeric ones)
    if pa == pb:
        return 0
    if pa == "":
        return 1
    if pb == "":
        return -1

    def ids(pre):
        out = []
        for part in pre.split("."):
            out.append((0, int(part), "") if part.isdigit() else (1, 0, part))
        return out

    ia, ib = ids(pa), ids(pb)
    for xa, xb in zip(ia, ib):
        if xa != xb:
            return -1 if xa < xb else 1
    if len(ia) != len(ib):  # more identifiers = higher precedence
        return -1 if len(ia) < len(ib) else 1
    return 0


# per-query clock cache: OPA evaluates time.now_ns once per query so every
# call within one evaluation sees the same instant.  THREAD-LOCAL: each
# query runs on one thread, and concurrent admission reviews (the webhook
# server is threaded) must not bump each other's epoch.  The interpreter
# bumps the epoch at each query boundary (interp.QueryContext).
import threading as _threading

_NOW_TLS = _threading.local()


def bump_query_epoch():
    _NOW_TLS.epoch = getattr(_NOW_TLS, "epoch", 0) + 1


@builtin("time", "now_ns")
def _time_now_ns():
    import time

    epoch = getattr(_NOW_TLS, "epoch", 0)
    if getattr(_NOW_TLS, "seen", None) != epoch:
        _NOW_TLS.seen = epoch
        _NOW_TLS.ns = time.time_ns()
    return _NOW_TLS.ns


def lookup(path: tuple):
    return REGISTRY.get(path)


# --------------------------------------------------------------------------
# OPA v0.21 registry completion (vendored opa/ast/builtins.go).  Infix
# operators (plus/minus/eq/...) are native BinOps; the RSA/ECDSA JWT and
# X.509 families ride the installed `cryptography` package; only
# http.send (no egress) remains stubbed to a BuiltinError so policies
# see undefined rather than silently-wrong results.
# --------------------------------------------------------------------------


def _freeze(v):
    from .value import freeze

    return freeze(v)


def _thaw(v):
    from .value import thaw

    return thaw(v)


# ---- deprecated type casts (cast_array already above) ---------------------


@builtin("cast_string")
def _cast_string(x: Any):
    _need(isinstance(x, str), "cast_string: not a string")
    return x


@builtin("cast_boolean")
def _cast_boolean(x: Any):
    _need(isinstance(x, bool), "cast_boolean: not a boolean")
    return x


@builtin("cast_null")
def _cast_null(x: Any):
    _need(x is None, "cast_null: not null")
    return x


@builtin("cast_object")
def _cast_object(x: Any):
    _need(isinstance(x, FrozenDict), "cast_object: not an object")
    return x


@builtin("cast_set")
def _cast_set(x: Any):
    _need(isinstance(x, RSet), "cast_set: not a set")
    return x


@builtin("set_diff")
def _set_diff(a: Any, b: Any):
    _need(isinstance(a, RSet) and isinstance(b, RSet), "set_diff: not sets")
    return a.difference(b)


# ---- encoding -------------------------------------------------------------


@builtin("base64url", "encode")
def _base64url_encode(s: Any):
    import base64

    _need(isinstance(s, str), "base64url.encode: not a string")
    return base64.urlsafe_b64encode(s.encode()).decode()


@builtin("base64url", "decode")
def _base64url_decode(s: Any):
    import base64

    _need(isinstance(s, str), "base64url.decode: not a string")
    try:
        pad = s + "=" * (-len(s) % 4)
        return base64.urlsafe_b64decode(pad.encode()).decode()
    except Exception as e:
        raise BuiltinError(f"base64url.decode: {e}")


@builtin("urlquery", "encode")
def _urlquery_encode(s: Any):
    import urllib.parse

    _need(isinstance(s, str), "urlquery.encode: not a string")
    return urllib.parse.quote_plus(s)


@builtin("urlquery", "decode")
def _urlquery_decode(s: Any):
    import urllib.parse

    _need(isinstance(s, str), "urlquery.decode: not a string")
    return urllib.parse.unquote_plus(s)


@builtin("urlquery", "encode_object")
def _urlquery_encode_object(obj: Any):
    import urllib.parse

    _need(isinstance(obj, FrozenDict), "urlquery.encode_object: not an object")
    parts = []
    for k in obj.keys():
        v = obj[k]
        _need(isinstance(k, str), "urlquery.encode_object: non-string key")
        if isinstance(v, str):
            parts.append((k, v))
        elif isinstance(v, (tuple, RSet)):
            for item in v:
                _need(isinstance(item, str), "urlquery.encode_object: non-string value")
                parts.append((k, item))
        else:
            raise BuiltinError("urlquery.encode_object: unsupported value type")
    return urllib.parse.urlencode(parts)


@builtin("yaml", "marshal")
def _yaml_marshal(x: Any):
    import yaml

    return yaml.safe_dump(_thaw(x), default_flow_style=False)


@builtin("yaml", "unmarshal")
def _yaml_unmarshal(s: Any):
    import yaml

    _need(isinstance(s, str), "yaml.unmarshal: not a string")
    try:
        return _freeze(yaml.safe_load(s))
    except yaml.YAMLError as e:
        raise BuiltinError(f"yaml.unmarshal: {e}")


# ---- crypto digests -------------------------------------------------------


@builtin("crypto", "md5")
def _crypto_md5(s: Any):
    import hashlib

    _need(isinstance(s, str), "crypto.md5: not a string")
    return hashlib.md5(s.encode()).hexdigest()


@builtin("crypto", "sha1")
def _crypto_sha1(s: Any):
    import hashlib

    _need(isinstance(s, str), "crypto.sha1: not a string")
    return hashlib.sha1(s.encode()).hexdigest()


@builtin("crypto", "sha256")
def _crypto_sha256(s: Any):
    import hashlib

    _need(isinstance(s, str), "crypto.sha256: not a string")
    return hashlib.sha256(s.encode()).hexdigest()


# ---- bits -----------------------------------------------------------------


def _int_arg(x: Any, who: str) -> int:
    _need(is_number(x), f"{who}: not an integer")
    if isinstance(x, float):
        # float(x) == int(x) would reject exact ints above 2^53 (every
        # real ns timestamp); only true floats need the integrality check
        _need(x.is_integer(), f"{who}: not an integer")
    return int(x)


@builtin("bits", "or")
def _bits_or(a, b):
    return _int_arg(a, "bits.or") | _int_arg(b, "bits.or")


@builtin("bits", "and")
def _bits_and(a, b):
    return _int_arg(a, "bits.and") & _int_arg(b, "bits.and")


@builtin("bits", "xor")
def _bits_xor(a, b):
    return _int_arg(a, "bits.xor") ^ _int_arg(b, "bits.xor")


@builtin("bits", "negate")
def _bits_negate(a):
    return ~_int_arg(a, "bits.negate")


_SHIFT_CAP = 1 << 20


def _shift_arg(n: Any, who: str, compat_exact: bool = False) -> int:
    """Shift counts must be non-negative (Python << raises ValueError,
    which would surface as a whole-query error instead of OPA's
    builtin-error -> undefined) and bounded (bits.lsh(1, 10**9) would
    allocate a gigantic int).  Negative counts are a plain builtin error
    (undefined, matching OPA); over-cap counts fail CLOSED via
    BuiltinLimitError, like net.cidr_expand's cap — a violation rule must
    not silently stop firing because an attacker passed a huge shift.
    Under GK_BUG_COMPAT (engine/compat.py) an over-cap count degrades to
    a plain builtin error (undefined, OPA's never-abort error contract) —
    or, with compat_exact (bits.rsh, where the result only shrinks), is
    returned as-is for the caller to clamp and compute exactly."""
    v = _int_arg(n, who)
    _need(v >= 0, f"{who}: negative shift count")
    if v > _SHIFT_CAP:
        from .compat import bug_compat_enabled

        if bug_compat_enabled():
            if compat_exact:
                return v
            raise BuiltinError(f"{who}: shift count {v} exceeds cap 2^20")
        raise BuiltinLimitError(f"{who}: shift count {v} exceeds cap 2^20")
    return v


@builtin("bits", "lsh")
def _bits_lsh(a, n):
    return _int_arg(a, "bits.lsh") << _shift_arg(n, "bits.lsh")


@builtin("bits", "rsh")
def _bits_rsh(a, n):
    v = _int_arg(a, "bits.rsh")
    count = _shift_arg(n, "bits.rsh", compat_exact=True)
    # clamping to the bit length keeps Python from allocating an
    # over-cap count while preserving the exact (OPA) result
    return v >> min(count, v.bit_length() + 1)


# ---- objects / json documents --------------------------------------------


@builtin("object", "filter")
def _object_filter(obj: Any, keys: Any):
    _need(isinstance(obj, FrozenDict), "object.filter: not an object")
    _need(isinstance(keys, (tuple, RSet, FrozenDict)), "object.filter: bad keys")
    keep = set(keys.keys()) if isinstance(keys, FrozenDict) else set(keys)
    return FrozenDict({k: obj[k] for k in obj.keys() if k in keep})


@builtin("object", "remove")
def _object_remove(obj: Any, keys: Any):
    _need(isinstance(obj, FrozenDict), "object.remove: not an object")
    _need(isinstance(keys, (tuple, RSet, FrozenDict)), "object.remove: bad keys")
    drop = set(keys.keys()) if isinstance(keys, FrozenDict) else set(keys)
    return FrozenDict({k: obj[k] for k in obj.keys() if k not in drop})


def _json_paths(paths: Any, who: str):
    """OPA json.filter/json.remove paths: strings "a/b" or arrays of keys."""
    _need(isinstance(paths, (tuple, RSet)), f"{who}: paths must be array/set")
    out = []
    for p in paths:
        if isinstance(p, str):
            out.append(tuple(seg for seg in p.split("/") if seg != ""))
        elif isinstance(p, tuple):
            out.append(tuple(p))
        else:
            raise BuiltinError(f"{who}: bad path {p!r}")
    return out


def _json_filter_value(v: Any, paths):
    """Keep only the listed paths ('' roots keep everything)."""
    if any(len(p) == 0 for p in paths):
        return v
    if isinstance(v, FrozenDict):
        out = {}
        for k in v.keys():
            sub = [p[1:] for p in paths if p[0] == k]
            if sub:
                out[k] = _json_filter_value(v[k], sub)
        return FrozenDict(out)
    if isinstance(v, tuple):
        out_l = []
        for i, item in enumerate(v):
            sub = [p[1:] for p in paths if p[0] in (str(i), i)]
            if sub:
                out_l.append(_json_filter_value(item, sub))
        return tuple(out_l)
    return v


@builtin("json", "filter")
def _json_filter(obj: Any, paths: Any):
    _need(isinstance(obj, FrozenDict), "json.filter: not an object")
    return _json_filter_value(obj, _json_paths(paths, "json.filter"))


def _json_remove_value(v: Any, paths):
    drop_here = {p[0] for p in paths if len(p) == 1}
    deeper: Dict[Any, list] = {}
    for p in paths:
        if len(p) > 1:
            deeper.setdefault(p[0], []).append(p[1:])
    if isinstance(v, FrozenDict):
        out = {}
        for k in v.keys():
            if k in drop_here:
                continue
            if k in deeper:
                out[k] = _json_remove_value(v[k], deeper[k])
            else:
                out[k] = v[k]
        return FrozenDict(out)
    if isinstance(v, tuple):
        out_l = []
        for i, item in enumerate(v):
            if str(i) in drop_here or i in drop_here:
                continue
            subs = deeper.get(str(i), deeper.get(i))
            out_l.append(_json_remove_value(item, subs) if subs else item)
        return tuple(out_l)
    return v


@builtin("json", "remove")
def _json_remove(obj: Any, paths: Any):
    _need(isinstance(obj, FrozenDict), "json.remove: not an object")
    return _json_remove_value(obj, _json_paths(paths, "json.remove"))


# ---- graph ----------------------------------------------------------------


@builtin("graph", "reachable")
def _graph_reachable(graph: Any, initial: Any):
    _need(isinstance(graph, FrozenDict), "graph.reachable: not an object")
    _need(isinstance(initial, (tuple, RSet)), "graph.reachable: initial must be array/set")
    seen = set()
    stack = list(initial)
    while stack:
        n = stack.pop()
        if n in seen:
            continue
        seen.add(n)
        nbrs = graph.get(n, UNDEFINED)
        if nbrs is UNDEFINED or nbrs is None:
            continue
        if isinstance(nbrs, (tuple, RSet)):
            stack.extend(nbrs)
    return RSet(seen)


# ---- net ------------------------------------------------------------------


def _parse_net(s: Any, who: str):
    import ipaddress

    _need(isinstance(s, str), f"{who}: not a string")
    try:
        if "/" in s:
            return ipaddress.ip_network(s, strict=False)
        addr = ipaddress.ip_address(s)
        return ipaddress.ip_network(f"{addr}/{addr.max_prefixlen}")
    except ValueError as e:
        raise BuiltinError(f"{who}: {e}")


@builtin("net", "cidr_contains")
def _net_cidr_contains(cidr: Any, other: Any):
    a = _parse_net(cidr, "net.cidr_contains")
    b = _parse_net(other, "net.cidr_contains")
    if a.version != b.version:
        return False
    return b.subnet_of(a)


@builtin("net", "cidr_intersects")
def _net_cidr_intersects(a: Any, b: Any):
    na = _parse_net(a, "net.cidr_intersects")
    nb = _parse_net(b, "net.cidr_intersects")
    if na.version != nb.version:
        return False
    return na.overlaps(nb)


@builtin("net", "cidr_overlap")
def _net_cidr_overlap(cidr: Any, ip: Any):
    # deprecated alias of cidr_contains with an IP operand
    return _net_cidr_contains(cidr, ip)


@builtin("net", "cidr_expand")
def _net_cidr_expand(cidr: Any):
    n = _parse_net(cidr, "net.cidr_expand")
    if n.num_addresses > 65536:
        # OPA expands any size; this engine caps at a /16.  Fail CLOSED
        # (whole-query error) rather than undefined, so a policy
        # expanding e.g. a /15 errors loudly instead of its violation
        # rule silently never firing.  Documented in docs/rego.md.
        raise BuiltinLimitError(
            "net.cidr_expand: network larger than 65536 addresses "
            "(engine cap; OPA would expand it)"
        )
    return RSet({str(h) for h in n})


@builtin("net", "cidr_contains_matches")
def _net_cidr_contains_matches(cidrs: Any, cidrs_or_ips: Any):
    """Cross-product membership: pairs [cidr_index, candidate_index] (OPA
    returns index keys for array operands, values for sets/strings)."""

    def entries(x, who):
        if isinstance(x, str):
            return [(x, x)]
        if isinstance(x, tuple):
            out = []
            for i, v in enumerate(x):
                if isinstance(v, tuple) and v:  # [cidr, data...] tuples
                    out.append((i, v[0]))
                else:
                    out.append((i, v))
            return out
        if isinstance(x, RSet):
            return [(v, v) for v in x]
        if isinstance(x, FrozenDict):
            return [(k, x[k]) for k in x.keys()]
        raise BuiltinError(f"{who}: unsupported operand")

    out = set()
    for ka, va in entries(cidrs, "net.cidr_contains_matches"):
        for kb, vb in entries(cidrs_or_ips, "net.cidr_contains_matches"):
            try:
                if _net_cidr_contains(va, vb):
                    out.add((ka, kb))
            except BuiltinError:
                continue
    return RSet(out)


# ---- time -----------------------------------------------------------------

_GO_UNITS = {"ns": 1, "us": 1000, "µs": 1000, "ms": 10**6, "s": 10**9, "m": 60 * 10**9, "h": 3600 * 10**9}


@builtin("time", "parse_duration_ns")
def _time_parse_duration_ns(s: Any):
    from fractions import Fraction

    _need(isinstance(s, str), "time.parse_duration_ns: not a string")
    txt = s.strip()
    if txt in ("0", "+0", "-0"):  # Go ParseDuration's unitless zero
        return 0
    m = re.fullmatch(r"([+-])?((?:\d+\.?\d*|\.\d+)(?:ns|us|µs|ms|s|m|h))+", txt)
    _need(m is not None and txt not in ("", "+", "-"), f"time.parse_duration_ns: bad duration {s!r}")
    sign = -1 if txt.startswith("-") else 1
    total = Fraction(0)  # exact: float accumulation loses ns at large scales
    for num, unit in re.findall(r"(\d+\.?\d*|\.\d+)(ns|us|µs|ms|s|m|h)", txt):
        total += Fraction(num) * _GO_UNITS[unit]
    return sign * int(total)


def _go_layout_to_strptime(layout: str) -> str:
    """Map the common Go reference-time layouts to strptime directives."""
    subs = [
        ("2006", "%Y"), ("01", "%m"), ("02", "%d"), ("15", "%H"),
        ("04", "%M"), ("05", "%S"), ("Jan", "%b"), ("Monday", "%A"),
        ("Mon", "%a"), ("MST", "%Z"), ("Z07:00", "%z"), ("-07:00", "%z"),
        ("-0700", "%z"), (".000", ".%f"), (".999999999", ".%f"), (".999999", ".%f"),
    ]
    out = layout
    for go, py in subs:
        out = out.replace(go, py)
    return out


def _dt_to_ns(dt) -> int:
    import datetime

    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    # exact integer arithmetic: float64 timestamp() cannot carry ns precision
    delta = dt - datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
    return (delta.days * 86400 + delta.seconds) * 10**9 + delta.microseconds * 1000


@builtin("time", "parse_rfc3339_ns")
def _time_parse_rfc3339_ns(s: Any):
    import datetime

    _need(isinstance(s, str), "time.parse_rfc3339_ns: not a string")
    txt = s.strip()
    # datetime.fromisoformat (3.11+) accepts Z and fractional seconds
    try:
        dt = datetime.datetime.fromisoformat(txt.replace("Z", "+00:00"))
    except ValueError as e:
        raise BuiltinError(f"time.parse_rfc3339_ns: {e}")
    # preserve sub-microsecond digits lost by datetime
    ns = _dt_to_ns(dt)
    m = re.search(r"\.(\d{7,9})", txt)
    if m:
        frac = m.group(1).ljust(9, "0")[:9]
        ns = (ns // 10**9) * 10**9 + int(frac)
    return ns


@builtin("time", "parse_ns")
def _time_parse_ns(layout: Any, s: Any):
    import datetime

    _need(isinstance(layout, str) and isinstance(s, str), "time.parse_ns: not strings")
    try:
        dt = datetime.datetime.strptime(s, _go_layout_to_strptime(layout))
    except ValueError as e:
        raise BuiltinError(f"time.parse_ns: {e}")
    return _dt_to_ns(dt)


def _ns_arg(x: Any, who: str):
    """OPA time builtins take ns or [ns, tz]; only UTC/Local-free math here."""
    import datetime

    tz = None
    if isinstance(x, tuple):
        _need(len(x) >= 1, f"{who}: empty array operand")
        ns = x[0]
        if len(x) > 1 and x[1] not in ("", "UTC"):
            tz = x[1]
    else:
        ns = x
    ns = _int_arg(ns, who)
    # integer arithmetic: fromtimestamp(ns / 1e9) rounds across second
    # boundaries for large timestamps (float64 cannot carry ns)
    dt = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc) + datetime.timedelta(
        microseconds=ns // 1000
    )
    if tz is not None:
        # Go LoadLocation semantics via the system tz database; unknown
        # names fail closed (undefined) rather than silently return UTC
        _need(isinstance(tz, str), f"{who}: timezone must be a string")
        import zoneinfo

        try:
            dt = dt.astimezone(zoneinfo.ZoneInfo(tz))
        except (zoneinfo.ZoneInfoNotFoundError, ValueError) as e:
            raise BuiltinError(f"{who}: {e}")
    return ns, dt


@builtin("time", "date")
def _time_date(x: Any):
    _ns, dt = _ns_arg(x, "time.date")
    return (dt.year, dt.month, dt.day)


@builtin("time", "clock")
def _time_clock(x: Any):
    _ns, dt = _ns_arg(x, "time.clock")
    return (dt.hour, dt.minute, dt.second)


@builtin("time", "weekday")
def _time_weekday(x: Any):
    _ns, dt = _ns_arg(x, "time.weekday")
    return ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday", "Sunday"][dt.weekday()]


@builtin("time", "add_date")
def _time_add_date(ns: Any, years: Any, months: Any, days: Any):
    import calendar
    import datetime

    base_ns, dt = _ns_arg(ns, "time.add_date")
    y = _int_arg(years, "time.add_date")
    mo = _int_arg(months, "time.add_date")
    d = _int_arg(days, "time.add_date")
    total_months = (dt.year + y) * 12 + (dt.month - 1) + mo
    ny, nm = divmod(total_months, 12)
    nm += 1
    # Go normalizes out-of-range days by rolling over (Oct 31 + 1mo = Dec 1)
    day_overflow = dt.day - calendar.monthrange(ny, nm)[1]
    nd = dt.day
    if day_overflow > 0:
        nd = calendar.monthrange(ny, nm)[1]
    out = dt.replace(year=ny, month=nm, day=nd)
    if day_overflow > 0:
        out += datetime.timedelta(days=day_overflow)
    out += datetime.timedelta(days=d)
    return _dt_to_ns(out) + base_ns % 1000  # keep sub-microsecond digits


# ---- regex extras ---------------------------------------------------------


@builtin("regex", "find_n")
def _regex_find_n(pattern: Any, s: Any, n: Any):
    _need(isinstance(pattern, str) and isinstance(s, str), "regex.find_n: not strings")
    limit = _int_arg(n, "regex.find_n")
    out = []
    for m in _compile_re(pattern).finditer(s):
        if limit >= 0 and len(out) >= limit:
            break
        out.append(m.group(0))
    return tuple(out)


@builtin("regex", "find_all_string_submatch_n")
def _regex_find_all_string_submatch_n(pattern: Any, s: Any, n: Any):
    _need(isinstance(pattern, str) and isinstance(s, str),
          "regex.find_all_string_submatch_n: not strings")
    limit = _int_arg(n, "regex.find_all_string_submatch_n")
    out = []
    for m in _compile_re(pattern).finditer(s):
        if limit >= 0 and len(out) >= limit:
            break
        groups = [m.group(0)] + ["" if g is None else g for g in m.groups()]
        out.append(tuple(groups))
    return tuple(out)


@builtin("regex", "template_match")
def _regex_template_match(pattern: Any, s: Any, delim_start: Any, delim_end: Any):
    """Match s against pattern where {delimited} spans are regexes and the
    rest is literal (OPA topdown/regex.go builtinRegexMatchTemplate)."""
    for x in (pattern, s, delim_start, delim_end):
        _need(isinstance(x, str), "regex.template_match: not strings")
    _need(len(delim_start) == 1 and len(delim_end) == 1,
          "regex.template_match: delimiters must be single characters")
    parts = []
    i = 0
    while i < len(pattern):
        j = pattern.find(delim_start, i)
        if j < 0:
            parts.append(re.escape(pattern[i:]))
            break
        parts.append(re.escape(pattern[i:j]))
        k = pattern.find(delim_end, j + 1)
        _need(k >= 0, "regex.template_match: unbalanced delimiters")
        parts.append("(?:" + pattern[j + 1:k] + ")")
        i = k + 1
    try:
        return re.fullmatch("".join(parts), s) is not None
    except re.error as e:
        raise BuiltinError(f"regex.template_match: {e}")


@builtin("glob", "quote_meta")
def _glob_quote_meta(s: Any):
    _need(isinstance(s, str), "glob.quote_meta: not a string")
    return re.sub(r"([*?\[\]{}\\])", r"\\\1", s)


# ---- JWT (HMAC family: stdlib hmac; asymmetric family further down) -------


def _jwt_parts(token: Any, who: str):
    _need(isinstance(token, str), f"{who}: not a string")
    parts = token.split(".")
    _need(len(parts) == 3, f"{who}: not a JWS compact token")
    return (_b64u_decode(parts[0], who), _b64u_decode(parts[1], who),
            _b64u_decode(parts[2], who), parts)


@builtin("io", "jwt", "decode")
def _io_jwt_decode(token: Any):
    import json

    header_b, payload_b, sig_b, _parts = _jwt_parts(token, "io.jwt.decode")
    try:
        header = json.loads(header_b)
        payload = json.loads(payload_b)
    except json.JSONDecodeError as e:
        raise BuiltinError(f"io.jwt.decode: {e}")
    return (_freeze(header), _freeze(payload), sig_b.hex())


def _jwt_verify_hs(token: Any, secret: Any, alg: str, digestmod) -> bool:
    import hashlib  # noqa: F401  (digestmod resolved by caller)
    import hmac
    import json

    header_b, _payload_b, sig_b, parts = _jwt_parts(token, f"io.jwt.verify_{alg.lower()}")
    _need(isinstance(secret, str), f"io.jwt.verify_{alg.lower()}: secret not a string")
    try:
        header = json.loads(header_b)
    except json.JSONDecodeError:
        return False
    if header.get("alg") != alg:
        return False
    signing_input = (parts[0] + "." + parts[1]).encode()
    want = hmac.new(secret.encode(), signing_input, digestmod).digest()
    return hmac.compare_digest(want, sig_b)


@builtin("io", "jwt", "verify_hs256")
def _io_jwt_verify_hs256(token: Any, secret: Any):
    import hashlib

    return _jwt_verify_hs(token, secret, "HS256", hashlib.sha256)


@builtin("io", "jwt", "verify_hs384")
def _io_jwt_verify_hs384(token: Any, secret: Any):
    import hashlib

    return _jwt_verify_hs(token, secret, "HS384", hashlib.sha384)


@builtin("io", "jwt", "verify_hs512")
def _io_jwt_verify_hs512(token: Any, secret: Any):
    import hashlib

    return _jwt_verify_hs(token, secret, "HS512", hashlib.sha512)


# ---- JWT asymmetric family + X.509 (the installed `cryptography`
# package — the same library certs/rotator.py uses for serving certs).
# Semantics pinned to the reference's vendored OPA topdown
# (opa/topdown/tokens.go, opa/topdown/crypto.go). ------------------------


def _b64u_decode(s: str, who: str) -> bytes:
    import base64

    try:
        return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))
    except Exception as e:
        raise BuiltinError(f"{who}: bad base64url: {e}")


def _b64u_encode(b: bytes) -> str:
    import base64

    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def _b64u_uint(s: str, who: str) -> int:
    return int.from_bytes(_b64u_decode(s, who), "big")


def _jwk_field(jwk: dict, field: str, who: str) -> str:
    v = jwk.get(field)
    _need(isinstance(v, str), f"{who}: JWK missing field {field!r}")
    return v


def _jwk_public_key(jwk: dict, who: str):
    """JWK -> cryptography public key (RSA / EC), or raw bytes for oct."""
    from cryptography.hazmat.primitives.asymmetric import ec, rsa

    kty = jwk.get("kty")
    if kty == "RSA":
        n = _b64u_uint(_jwk_field(jwk, "n", who), who)
        e = _b64u_uint(_jwk_field(jwk, "e", who), who)
        return rsa.RSAPublicNumbers(e, n).public_key()
    if kty == "EC":
        curve = _ec_curves().get(jwk.get("crv"))
        _need(curve is not None, f"{who}: unsupported EC curve {jwk.get('crv')}")
        x = _b64u_uint(_jwk_field(jwk, "x", who), who)
        y = _b64u_uint(_jwk_field(jwk, "y", who), who)
        return ec.EllipticCurvePublicNumbers(x, y, curve()).public_key()
    if kty == "oct":
        return _b64u_decode(_jwk_field(jwk, "k", who), who)
    raise BuiltinError(f"{who}: unsupported JWK kty {kty!r}")


def _jwk_private_key(jwk: dict, who: str):
    """JWK -> cryptography private key (RSA / EC), or raw bytes for oct."""
    from cryptography.hazmat.primitives.asymmetric import ec, rsa

    kty = jwk.get("kty")
    if kty == "RSA":
        n = _b64u_uint(_jwk_field(jwk, "n", who), who)
        e = _b64u_uint(_jwk_field(jwk, "e", who), who)
        d = _b64u_uint(_jwk_field(jwk, "d", who), who)
        if "p" in jwk and "q" in jwk:
            p = _b64u_uint(_jwk_field(jwk, "p", who), who)
            q = _b64u_uint(_jwk_field(jwk, "q", who), who)
        else:
            p, q = rsa.rsa_recover_prime_factors(n, e, d)
        dmp1 = _b64u_uint(jwk["dp"], who) if "dp" in jwk else rsa.rsa_crt_dmp1(d, p)
        dmq1 = _b64u_uint(jwk["dq"], who) if "dq" in jwk else rsa.rsa_crt_dmq1(d, q)
        iqmp = _b64u_uint(jwk["qi"], who) if "qi" in jwk else rsa.rsa_crt_iqmp(p, q)
        pub = rsa.RSAPublicNumbers(e, n)
        return rsa.RSAPrivateNumbers(p, q, d, dmp1, dmq1, iqmp, pub).private_key()
    if kty == "EC":
        curve = _ec_curves().get(jwk.get("crv"))
        _need(curve is not None, f"{who}: unsupported EC curve {jwk.get('crv')}")
        return ec.derive_private_key(
            _b64u_uint(_jwk_field(jwk, "d", who), who), curve())
    if kty == "oct":
        return _b64u_decode(_jwk_field(jwk, "k", who), who)
    raise BuiltinError(f"{who}: unsupported JWK kty {kty!r}")


import functools as _functools


@_functools.lru_cache(maxsize=1)
def _ec_curves() -> dict:
    from cryptography.hazmat.primitives.asymmetric import ec

    return {"P-256": ec.SECP256R1, "P-384": ec.SECP384R1, "P-521": ec.SECP521R1}


def _verification_keys(cert: Any, who: str) -> list:
    """tokens.go getKeysFromCertOrJWK: the `cert` argument is a PEM
    certificate, a PEM public key, or a JWK/JWKS JSON string.  Returns a
    list of candidate keys (public keys, or bytes for oct JWKs)."""
    import json

    _need(isinstance(cert, str), f"{who}: key material not a string")
    if "-----BEGIN CERTIFICATE" in cert:
        from cryptography import x509

        try:
            certs = x509.load_pem_x509_certificates(cert.encode())
        except Exception as e:
            raise BuiltinError(f"{who}: bad certificate: {e}")
        return [c.public_key() for c in certs]
    if "-----BEGIN" in cert:
        from cryptography.hazmat.primitives import serialization

        try:
            return [serialization.load_pem_public_key(cert.encode())]
        except Exception as e:
            raise BuiltinError(f"{who}: bad public key PEM: {e}")
    try:
        doc = json.loads(cert)
    except json.JSONDecodeError as e:
        raise BuiltinError(f"{who}: key is neither PEM nor JWK JSON: {e}")
    _need(isinstance(doc, dict), f"{who}: JWK document must be an object")
    jwks = doc.get("keys") if "keys" in doc else [doc]
    _need(isinstance(jwks, list) and jwks, f"{who}: empty JWKS")
    return [_jwk_public_key(j, who) for j in jwks]


def _hash_for(alg: str):
    from cryptography.hazmat.primitives import hashes

    return {"256": hashes.SHA256(), "384": hashes.SHA384(),
            "512": hashes.SHA512()}[alg[-3:]]


def _verify_one(key, alg: str, signing_input: bytes, sig: bytes) -> bool:
    """Verify one candidate key against a JWS signature; False on mismatch
    or a key type that cannot carry this algorithm."""
    import hashlib
    import hmac as hmac_mod

    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric import ec, padding, rsa, utils

    chash = _hash_for(alg)
    fam = alg[:2]
    try:
        if fam == "HS":
            if not isinstance(key, (bytes, bytearray)):
                return False
            digest = getattr(hashlib, chash.name.replace("-", ""))
            want = hmac_mod.new(bytes(key), signing_input, digest).digest()
            return hmac_mod.compare_digest(want, sig)
        if fam == "RS":
            if not isinstance(key, rsa.RSAPublicKey):
                return False
            key.verify(sig, signing_input, padding.PKCS1v15(), chash)
            return True
        if fam == "PS":
            if not isinstance(key, rsa.RSAPublicKey):
                return False
            # AUTO salt detection: Go's rsa.VerifyPSS (the reference path)
            # accepts any salt length, not just digest_size
            key.verify(
                sig, signing_input,
                padding.PSS(mgf=padding.MGF1(chash),
                            salt_length=padding.PSS.AUTO),
                chash,
            )
            return True
        if fam == "ES":
            if not isinstance(key, ec.EllipticCurvePublicKey):
                return False
            # JWS ECDSA signatures are raw R||S (RFC 7518 section 3.4)
            half = len(sig) // 2
            if half == 0 or len(sig) % 2:
                return False
            der = utils.encode_dss_signature(
                int.from_bytes(sig[:half], "big"),
                int.from_bytes(sig[half:], "big"),
            )
            key.verify(der, signing_input, ec.ECDSA(chash))
            return True
    except InvalidSignature:
        return False
    except Exception:
        return False
    return False


def _jwt_verify_asym(token: Any, cert: Any, alg: str) -> bool:
    import json

    who = f"io.jwt.verify_{alg.lower()}"
    header_b, _payload_b, sig_b, parts = _jwt_parts(token, who)
    keys = _verification_keys(cert, who)
    try:
        header = json.loads(header_b)
    except json.JSONDecodeError:
        return False
    if header.get("alg") != alg:
        return False
    signing_input = (parts[0] + "." + parts[1]).encode()
    return any(_verify_one(k, alg, signing_input, sig_b) for k in keys)


def _register_jwt_verifiers():
    for _alg in ("RS256", "RS384", "RS512", "PS256", "PS384", "PS512",
                 "ES256", "ES384", "ES512"):
        def _v(token: Any, cert: Any, _alg=_alg):
            return _jwt_verify_asym(token, cert, _alg)

        _v.__name__ = f"_io_jwt_verify_{_alg.lower()}"
        builtin("io", "jwt", f"verify_{_alg.lower()}", arity=2)(_v)


_register_jwt_verifiers()

_JWS_ALGS = ("HS256", "HS384", "HS512", "RS256", "RS384", "RS512",
             "PS256", "PS384", "PS512", "ES256", "ES384", "ES512")


@builtin("io", "jwt", "decode_verify")
def _io_jwt_decode_verify(token: Any, constraints: Any):
    """tokens.go builtinJWTDecodeVerify: returns [valid, header, payload]
    — [false, {}, {}] whenever signature or claim checks fail."""
    import json

    who = "io.jwt.decode_verify"
    _need(isinstance(constraints, FrozenDict), f"{who}: constraints must be an object")
    cons = _thaw(constraints)
    unknown = set(cons) - {"cert", "secret", "alg", "iss", "aud", "time"}
    _need(not unknown, f"{who}: unknown constraint keys {sorted(unknown)}")
    _need("cert" in cons or "secret" in cons,
          f"{who}: no verification key supplied (cert or secret)")

    invalid = (False, FrozenDict({}), FrozenDict({}))
    header_b, payload_b, sig_b, parts = _jwt_parts(token, who)
    try:
        header = json.loads(header_b)
        payload = json.loads(payload_b)
    except json.JSONDecodeError:
        return invalid
    if not isinstance(header, dict) or not isinstance(payload, dict):
        return invalid
    if "crit" in header:  # no crit extensions are understood here (or in OPA)
        return invalid
    alg = header.get("alg")
    if alg not in _JWS_ALGS:
        return invalid
    if "alg" in cons and cons["alg"] != alg:
        return invalid

    if alg.startswith("HS"):
        secret = cons.get("secret")
        if not isinstance(secret, str):
            return invalid
        keys = [secret.encode()]
    else:
        if "cert" not in cons:
            return invalid
        keys = _verification_keys(cons["cert"], who)
    signing_input = (parts[0] + "." + parts[1]).encode()
    if not any(_verify_one(k, alg, signing_input, sig_b) for k in keys):
        return invalid

    # claim checks (tokens.go _verify: exp/nbf against time, iss, aud)
    now_ns = cons.get("time", _time_now_ns())
    if not is_number(now_ns):
        raise BuiltinError(f"{who}: time constraint must be a number")
    now_s = float(now_ns) / 1e9
    if "iss" in cons and payload.get("iss") != cons["iss"]:
        return invalid
    aud = payload.get("aud")
    if aud is not None:
        want = cons.get("aud")
        if want is None:
            return invalid
        auds = aud if isinstance(aud, list) else [aud]
        if want not in auds:
            return invalid
    elif "aud" in cons:
        return invalid
    exp = payload.get("exp")
    if exp is not None:
        if not is_number(exp) or now_s >= float(exp):
            return invalid
    nbf = payload.get("nbf")
    if nbf is not None:
        if not is_number(nbf) or now_s < float(nbf):
            return invalid
    return (True, _freeze(header), _freeze(payload))


def _jws_sign(header_json: bytes, payload_bytes: bytes, key, alg: str,
              who: str) -> str:
    import hashlib
    import hmac as hmac_mod

    from cryptography.hazmat.primitives.asymmetric import ec, padding, rsa, utils

    _need(alg in _JWS_ALGS, f"{who}: unsupported alg {alg!r}")
    chash = _hash_for(alg)
    signing_input = (_b64u_encode(header_json) + "." +
                     _b64u_encode(payload_bytes)).encode()
    fam = alg[:2]
    if fam == "HS":
        _need(isinstance(key, (bytes, bytearray)),
              f"{who}: {alg} needs an oct JWK")
        digest = getattr(hashlib, chash.name.replace("-", ""))
        sig = hmac_mod.new(bytes(key), signing_input, digest).digest()
    elif fam in ("RS", "PS"):
        _need(isinstance(key, rsa.RSAPrivateKey),
              f"{who}: {alg} needs an RSA private JWK")
        pad = (padding.PKCS1v15() if fam == "RS" else
               padding.PSS(mgf=padding.MGF1(chash),
                           salt_length=chash.digest_size))
        sig = key.sign(signing_input, pad, chash)
    else:  # ES
        _need(isinstance(key, ec.EllipticCurvePrivateKey),
              f"{who}: {alg} needs an EC private JWK")
        der = key.sign(signing_input, ec.ECDSA(chash))
        r, s = utils.decode_dss_signature(der)
        nbytes = (key.curve.key_size + 7) // 8
        sig = r.to_bytes(nbytes, "big") + s.to_bytes(nbytes, "big")
    return signing_input.decode() + "." + _b64u_encode(sig)


@builtin("io", "jwt", "encode_sign")
def _io_jwt_encode_sign(headers: Any, payload: Any, key: Any):
    import json

    who = "io.jwt.encode_sign"
    _need(isinstance(headers, FrozenDict), f"{who}: headers must be an object")
    _need(isinstance(payload, FrozenDict), f"{who}: payload must be an object")
    _need(isinstance(key, FrozenDict), f"{who}: key must be a JWK object")
    hdr = _thaw(headers)
    alg = hdr.get("alg")
    _need(isinstance(alg, str), f"{who}: headers missing alg")
    priv = _jwk_private_key(_thaw(key), who)
    hdr_json = json.dumps(hdr, separators=(",", ":"), sort_keys=False).encode()
    pl_json = json.dumps(_thaw(payload), separators=(",", ":")).encode()
    return _jws_sign(hdr_json, pl_json, priv, alg, who)


@builtin("io", "jwt", "encode_sign_raw")
def _io_jwt_encode_sign_raw(headers: Any, payload: Any, key: Any):
    """Same as encode_sign but every argument is a JSON *string*
    (tokens.go builtinJWTEncodeSignRaw)."""
    import json

    who = "io.jwt.encode_sign_raw"
    for x in (headers, payload, key):
        _need(isinstance(x, str), f"{who}: arguments must be JSON strings")
    try:
        hdr = json.loads(headers)
        jwk = json.loads(key)
    except json.JSONDecodeError as e:
        raise BuiltinError(f"{who}: {e}")
    _need(isinstance(hdr, dict), f"{who}: headers must encode an object")
    _need(isinstance(jwk, dict), f"{who}: key must encode a JWK object")
    alg = hdr.get("alg")
    _need(isinstance(alg, str), f"{who}: headers missing alg")
    priv = _jwk_private_key(jwk, who)
    return _jws_sign(headers.encode(), payload.encode(), priv, alg, who)


# Go crypto/x509 enum values (x509.go), so policies written against the
# reference's field encoding keep working.
_GO_SIG_ALGS = {
    "md5WithRSAEncryption": 2, "sha1WithRSAEncryption": 3,
    "sha256WithRSAEncryption": 4, "sha384WithRSAEncryption": 5,
    "sha512WithRSAEncryption": 6, "dsaWithSHA1": 7, "dsaWithSHA256": 8,
    "ecdsaWithSHA1": 9, "ecdsaWithSHA256": 10, "ecdsaWithSHA384": 11,
    "ecdsaWithSHA512": 12, "rsassaPss": 13, "ed25519": 16,
}
_GO_KEY_USAGE_BITS = (
    "digital_signature", "content_commitment", "key_encipherment",
    "data_encipherment", "key_agreement", "key_cert_sign", "crl_sign",
    "encipher_only", "decipher_only",
)


def _go_name(name) -> dict:
    """pkix.Name JSON shape (crypto/x509/pkix) for Subject/Issuer."""
    from cryptography.x509.oid import NameOID

    def vals(oid):
        return [a.value for a in name.get_attributes_for_oid(oid)]

    cn = vals(NameOID.COMMON_NAME)
    serial = vals(NameOID.SERIAL_NUMBER)
    return {
        "Country": vals(NameOID.COUNTRY_NAME),
        "Organization": vals(NameOID.ORGANIZATION_NAME),
        "OrganizationalUnit": vals(NameOID.ORGANIZATIONAL_UNIT_NAME),
        "Locality": vals(NameOID.LOCALITY_NAME),
        "Province": vals(NameOID.STATE_OR_PROVINCE_NAME),
        "StreetAddress": vals(NameOID.STREET_ADDRESS),
        "PostalCode": vals(NameOID.POSTAL_CODE),
        "SerialNumber": serial[0] if serial else "",
        "CommonName": cn[0] if cn else "",
    }


def _go_time(dt) -> str:
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def _x509_input_certs(s: str, who: str):
    """PEM chain, or base64(DER concatenation) (crypto.go
    getX509CertsFromString)."""
    import base64

    from cryptography import x509

    try:
        if "-----BEGIN" in s:
            return x509.load_pem_x509_certificates(s.encode())
        der = base64.b64decode(s)
        certs = []
        while der:
            # outer SEQUENCE header gives this certificate's extent
            # (the DER parser rejects trailing data, so slice first)
            _need(der[0] == 0x30, f"{who}: not a DER SEQUENCE")
            if der[1] & 0x80:
                nlen = der[1] & 0x7F
                body = int.from_bytes(der[2:2 + nlen], "big")
                end = 2 + nlen + body
            else:
                end = 2 + der[1]
            certs.append(x509.load_der_x509_certificate(der[:end]))
            der = der[end:]
        return certs
    except Exception as e:
        raise BuiltinError(f"{who}: {e}")


def _cert_to_go(c) -> dict:
    """Go x509.Certificate JSON field subset (names + encodings match
    encoding/json over the Go struct; uncommon fields are omitted —
    documented in docs/rego.md)."""
    import base64

    from cryptography import x509
    from cryptography.hazmat.primitives.asymmetric import ec, rsa
    from cryptography.hazmat.primitives.serialization import Encoding

    out: dict = {
        "Version": 3 if c.version.name == "v3" else 1,
        "SerialNumber": c.serial_number,
        "Issuer": _go_name(c.issuer),
        "Subject": _go_name(c.subject),
        "NotBefore": _go_time(c.not_valid_before_utc),
        "NotAfter": _go_time(c.not_valid_after_utc),
        "SignatureAlgorithm": _GO_SIG_ALGS.get(
            c.signature_algorithm_oid._name, 0),
        "Signature": base64.b64encode(c.signature).decode(),
        "Raw": base64.b64encode(c.public_bytes(Encoding.DER)).decode(),
        "KeyUsage": 0,
        "IsCA": False,
        "BasicConstraintsValid": False,
        "DNSNames": [],
        "EmailAddresses": [],
        "IPAddresses": [],
        "URIs": [],
    }
    pub = c.public_key()
    if isinstance(pub, rsa.RSAPublicKey):
        nums = pub.public_numbers()
        out["PublicKeyAlgorithm"] = 1  # x509.RSA
        out["PublicKey"] = {"N": nums.n, "E": nums.e}
    elif isinstance(pub, ec.EllipticCurvePublicKey):
        nums = pub.public_numbers()
        out["PublicKeyAlgorithm"] = 3  # x509.ECDSA
        out["PublicKey"] = {"Curve": pub.curve.name, "X": nums.x, "Y": nums.y}
    else:
        out["PublicKeyAlgorithm"] = 0
    try:
        bc = c.extensions.get_extension_for_class(x509.BasicConstraints)
        out["IsCA"] = bool(bc.value.ca)
        out["BasicConstraintsValid"] = True
    except x509.ExtensionNotFound:
        pass
    try:
        ku = c.extensions.get_extension_for_class(x509.KeyUsage).value
        bits = 0
        for i, attr in enumerate(_GO_KEY_USAGE_BITS):
            try:
                if getattr(ku, attr):
                    bits |= 1 << i
            except ValueError:  # encipher/decipher_only w/o key_agreement
                pass
        out["KeyUsage"] = bits
    except x509.ExtensionNotFound:
        pass
    try:
        san = c.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        out["DNSNames"] = san.get_values_for_type(x509.DNSName)
        out["EmailAddresses"] = san.get_values_for_type(x509.RFC822Name)
        out["IPAddresses"] = [str(ip) for ip in
                              san.get_values_for_type(x509.IPAddress)]
        out["URIs"] = san.get_values_for_type(x509.UniformResourceIdentifier)
    except x509.ExtensionNotFound:
        pass
    return out


@builtin("crypto", "x509", "parse_certificates")
def _crypto_x509_parse_certificates(certs: Any):
    who = "crypto.x509.parse_certificates"
    _need(isinstance(certs, str), f"{who}: not a string")
    return _freeze([_cert_to_go(c) for c in _x509_input_certs(certs, who)])


@builtin("crypto", "x509", "parse_certificate_request")
def _crypto_x509_parse_certificate_request(csr: Any):
    import base64

    from cryptography import x509
    from cryptography.hazmat.primitives.serialization import Encoding

    who = "crypto.x509.parse_certificate_request"
    _need(isinstance(csr, str), f"{who}: not a string")
    try:
        if "-----BEGIN" in csr:
            req = x509.load_pem_x509_csr(csr.encode())
        else:
            req = x509.load_der_x509_csr(base64.b64decode(csr))
    except Exception as e:
        raise BuiltinError(f"{who}: {e}")
    out = {
        "Subject": _go_name(req.subject),
        "SignatureAlgorithm": _GO_SIG_ALGS.get(
            req.signature_algorithm_oid._name, 0),
        "Signature": base64.b64encode(req.signature).decode(),
        "Raw": base64.b64encode(req.public_bytes(Encoding.DER)).decode(),
        "Version": 0,  # Go: CSR version is always 0 (v1)
        "DNSNames": [],
        "EmailAddresses": [],
        "IPAddresses": [],
        "URIs": [],
    }
    try:
        san = req.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        out["DNSNames"] = san.get_values_for_type(x509.DNSName)
        out["EmailAddresses"] = san.get_values_for_type(x509.RFC822Name)
        out["IPAddresses"] = [str(ip) for ip in
                              san.get_values_for_type(x509.IPAddress)]
        out["URIs"] = san.get_values_for_type(x509.UniformResourceIdentifier)
    except x509.ExtensionNotFound:
        pass
    return _freeze(out)


@builtin("rego", "parse_module")
def _rego_parse_module(filename: Any, src: Any):
    """Reflective parse via this engine's own parser.  Emits the subset of
    OPA's ast.Module JSON shape policies actually navigate (package path +
    rule heads); full term-level AST is a documented divergence
    (docs/rego.md)."""
    who = "rego.parse_module"
    _need(isinstance(filename, str) and isinstance(src, str),
          f"{who}: arguments must be strings")
    from ..rego.parser import parse_module as _parse

    try:
        mod = _parse(src)  # filename is error-context only in OPA; unused
    except Exception as e:
        raise BuiltinError(f"{who}: {e}")
    pkg_path = [{"type": "var", "value": "data"}] + [
        {"type": "string", "value": p} for p in mod.package
    ]
    rules = []
    for r in mod.rules:
        rules.append({
            "head": {
                "name": r.name,
                "args": [{"type": "var", "value": getattr(a, "name", "_")}
                         for a in (r.args or [])],
            },
            "default": bool(getattr(r, "is_default", False)),
        })
    return _freeze({"package": {"path": pkg_path}, "rules": rules})


@builtin("regex", "globs_match")
def _regex_globs_match(g1: Any, g2: Any):
    """Non-empty intersection of two regex-style globs.

    Reference: vendor/.../opa/topdown/regex.go:119 (builtinGlobsMatch).
    Implemented per the documented semantics via a product-NFA emptiness
    check (engine/globintersect.py); see docs/rego.md for the two
    documented divergences from the vendored greedy library.
    """
    from .globintersect import GlobError, GlobLimitError, globs_intersect

    _need(isinstance(g1, str), "regex.globs_match: not a string")
    _need(isinstance(g2, str), "regex.globs_match: not a string")
    if g1 == "" and g2 == "":
        # the vendored library answers true for two empty globs (their
        # only common string is empty, so the documented "non-empty"
        # semantics say false); GK_BUG_COMPAT restores the library answer
        from .compat import bug_compat_enabled

        if bug_compat_enabled():
            return True
    try:
        return globs_intersect(g1, g2)
    except GlobLimitError as e:
        # fail CLOSED, like net.cidr_expand's cap: a pathological glob
        # must not silence a violation rule via undefined
        raise BuiltinLimitError(f"regex.globs_match: {e}")
    except GlobError as e:
        raise BuiltinError(f"regex.globs_match: {e}")


def _unsupported_builtin(name: str, why: str, arity: int):
    def stub(*_args):
        raise BuiltinError(f"{name}: {why}")

    stub._rego_arity = arity  # true OPA arity, so call-form checks stay sound
    return stub


for _name, _why, _arity in [
    ("http.send", "outbound HTTP is disabled in this runtime", 1),
]:
    REGISTRY[tuple(_name.split("."))] = _unsupported_builtin(_name, _why, _arity)


# ---- misc -----------------------------------------------------------------


@builtin("trace")
def _trace(note: Any):
    _need(isinstance(note, str), "trace: not a string")
    return True  # notes surface through the evaluator's tracer, not here


@builtin("opa", "runtime")
def _opa_runtime():
    from .. import version

    return FrozenDict({"version": getattr(version, "VERSION", "dev"), "env": FrozenDict({}), "config": FrozenDict({})})


@builtin("uuid", "rfc4122")
def _uuid_rfc4122(k: Any):
    """Stable within one query per key (OPA caches per-query); marked
    memo-unsafe by the compile analysis like time.now_ns."""
    import uuid

    epoch = getattr(_NOW_TLS, "epoch", 0)
    cache = getattr(_NOW_TLS, "uuid_cache", None)
    if cache is None or getattr(_NOW_TLS, "uuid_epoch", None) != epoch:
        cache = {}
        _NOW_TLS.uuid_cache = cache
        _NOW_TLS.uuid_epoch = epoch
    if k not in cache:
        cache[k] = str(uuid.uuid4())
    return cache[k]


@builtin("walk")
def _walk_stub(_x: Any):
    # `walk` is relational; the interpreter special-cases it (interp.
    # _eval_walk) and never dispatches here.  Registered for arity metadata.
    raise BuiltinError("walk: must be used as walk(x, [path, value])")
