"""Per-request deadline budgets and overload refusal.

The webhook server stamps each admission request with an absolute
monotonic deadline derived from its budget; everything downstream on the
same thread (micro-batcher enqueue, driver fallback ladders) reads it
through this module and refuses to start work it can no longer finish.
An exhausted budget surfaces as `DeadlineExceeded`, which the validation
handler converts into an explicit fail-open or fail-closed admission
decision — never a socket timeout.

End-to-end propagation (ISSUE 12, docs/failure-modes.md): the budget is
``min()`` over every bound the request carries — the configured
``--admission-deadline-budget-ms``, the AdmissionReview's own
``request.timeoutSeconds`` (the webhook configuration's timeout, when
the caller stamps it onto the request — an opportunistic source, never
required), and the **remaining** wire budget a fleet front door
forwards in the ``X-GK-Deadline-Ms`` header (:data:`DEADLINE_HEADER`).  A replica behind
the door therefore re-enters ``push`` with what is actually left of the
caller's patience, not a fresh budget; :func:`effective_budget_s` is the
shared min() so the door and the webhook cannot drift.

`OverloadShed` is the sibling refusal: not "too late" but "too full" —
raised by bounded queues (micro-batcher ``max_pending``, the front
door's per-backend inflight bound) when accepting the request would
push service time past every deadline anyway.  Both are converted to
the same explicit fail-open/closed decision.

The deadline rides a ContextVar: each webhook handler thread carries its
own, and code with no deadline set (audit sweeps, tests, background
threads) sees None everywhere and pays nothing.
"""

from __future__ import annotations

import contextvars
import math
import time
from contextlib import contextmanager
from typing import Optional

#: the wire header carrying the REMAINING budget, in milliseconds, across
#: the front-door hop (and any future proxy hop: the contract is
#: transport-agnostic — the event-edge wire protocol carries the same
#: remaining-budget value in its request frames, fleet/wireproto.py)
DEADLINE_HEADER = "X-GK-Deadline-Ms"


class DeadlineExceeded(Exception):
    """The request's deadline budget is exhausted."""


class OverloadShed(RuntimeError):
    """The request was refused by a bounded queue under overload — an
    explicit, immediate backpressure decision (docs/failure-modes.md
    shed order), never a slow timeout."""


_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "gk_deadline", default=None
)


def push(budget_s: float):
    """Set the current context's deadline to now + budget_s; returns a
    token for `pop`."""
    return _ctx.set(time.monotonic() + budget_s)


def pop(token):
    _ctx.reset(token)


def current() -> Optional[float]:
    """The absolute monotonic deadline, or None when no budget is set."""
    return _ctx.get()


def remaining() -> Optional[float]:
    """Seconds left (may be negative), or None when no budget is set."""
    dl = _ctx.get()
    return None if dl is None else dl - time.monotonic()


def remaining_ms() -> Optional[float]:
    """Milliseconds left (may be negative), or None when no budget is
    set — the value a proxy hop forwards in DEADLINE_HEADER."""
    r = remaining()
    return None if r is None else r * 1e3


def effective_budget_s(*candidates: Optional[float]) -> Optional[float]:
    """min() over the present budget bounds, in seconds.  None entries
    are 'no bound from this source'; all-None means no deadline at all.
    A zero or negative candidate is preserved (not clamped): it means
    the budget is ALREADY exhausted, and the caller must refuse the work
    explicitly rather than run it with a fabricated allowance."""
    present = [c for c in candidates if c is not None]
    return min(present) if present else None


def parse_header_ms(value) -> Optional[float]:
    """DEADLINE_HEADER value -> seconds, defensively: a malformed header
    from an unknown proxy must not 500 the request — it simply carries
    no bound."""
    if value is None:
        return None
    try:
        s = float(value) / 1e3
    except (TypeError, ValueError):
        return None
    # NaN/inf would poison every downstream comparison and socket
    # timeout (NaN compares False against everything, so an expired
    # check never fires and settimeout(nan) raises mid-proxy)
    return s if math.isfinite(s) else None


def parse_timeout_seconds(req: dict) -> Optional[float]:
    """``request.timeoutSeconds`` from an AdmissionReview request dict
    — the webhook configuration's timeout, when the apiserver (or the
    harness driving this webhook) stamps it onto the request.  Absent
    or non-numeric -> None: this source is opportunistic, and the
    configured ``--admission-deadline-budget-ms`` / forwarded wire
    budget still apply without it.  Bools are excluded (True is an int
    in Python, and `timeoutSeconds: true` is corruption, not a
    1-second budget)."""
    if not isinstance(req, dict):
        return None
    v = req.get("timeoutSeconds")
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    v = float(v)
    # json.loads happily produces NaN/Infinity; neither is a budget
    return v if math.isfinite(v) else None


def expired() -> bool:
    dl = _ctx.get()
    return dl is not None and time.monotonic() > dl


@contextmanager
def budget(budget_s: float):
    """Scope a deadline budget around a block (tests, embedders)."""
    token = push(budget_s)
    try:
        yield
    finally:
        pop(token)
