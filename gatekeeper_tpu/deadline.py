"""Per-request deadline budgets.

The webhook server stamps each admission request with an absolute
monotonic deadline derived from a configured budget; everything
downstream on the same thread (micro-batcher enqueue, driver fallback
ladders) reads it through this module and refuses to start work it can
no longer finish.  An exhausted budget surfaces as `DeadlineExceeded`,
which the validation handler converts into an explicit fail-open or
fail-closed admission decision — never a socket timeout.

The deadline rides a ContextVar: each webhook handler thread carries its
own, and code with no deadline set (audit sweeps, tests, background
threads) sees None everywhere and pays nothing.
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Optional

class DeadlineExceeded(Exception):
    """The request's deadline budget is exhausted."""


_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "gk_deadline", default=None
)


def push(budget_s: float):
    """Set the current context's deadline to now + budget_s; returns a
    token for `pop`."""
    return _ctx.set(time.monotonic() + budget_s)


def pop(token):
    _ctx.reset(token)


def current() -> Optional[float]:
    """The absolute monotonic deadline, or None when no budget is set."""
    return _ctx.get()


def remaining() -> Optional[float]:
    """Seconds left (may be negative), or None when no budget is set."""
    dl = _ctx.get()
    return None if dl is None else dl - time.monotonic()


def expired() -> bool:
    dl = _ctx.get()
    return dl is not None and time.monotonic() > dl


@contextmanager
def budget(budget_s: float):
    """Scope a deadline budget around a block (tests, embedders)."""
    token = push(budget_s)
    try:
        yield
    finally:
        pop(token)
