"""The validation handler — /v1/admit semantics (reference
pkg/webhook/policy.go:142-223).

Order of checks, matching the reference Handle:
  1. gatekeeper's own service account bypass (policy.go:147-149)
  2. DELETE uses OldObject; absent OldObject is a 500 (policy.go:151-166)
  3. gatekeeper resources get dry-run validation: templates through the
     CRD-synthesis compile, constraints against their template CRD
     (policy.go:168-179, 310-360) — user errors are 422, internal 500
  4. namespaces excluded for the webhook process are allowed through
     (policy.go:192-195)
  5. review: trace config lookup, Namespace-kind namespace coercion,
     Namespace augmentation from the cluster (policy.go:363-400)
  6. deny messages only from enforcementAction==deny; dryrun logs/events
     only (policy.go:209-222, 225-291)
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from .. import logging as gklog
from ..deadline import (
    DeadlineExceeded,
    OverloadShed,
    pop as deadline_pop,
    push as deadline_push,
    remaining as deadline_remaining,
)
from ..obs import decisionlog as obsdlog
from ..obs import slo as obsslo
from ..obs import trace as obstrace
from ..apis.config import CONFIG_NAME, GVK as CONFIG_GVK, parse_config
from ..kube.inmem import InMemoryKube, NotFound
from ..process.excluder import WEBHOOK, Excluder
from ..target.target import AugmentedReview
from ..util import (
    DENY as ACTION_DENY,
    DRYRUN as ACTION_DRYRUN,
    EnforcementActionError,
    get_namespace,
    validate_enforcement_action,
)

SERVICE_ACCOUNT_NAME = "gatekeeper-admin"

TEMPLATE_GROUP = "templates.gatekeeper.sh"
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"

# requestResponse values for the request_count metric (policy.go:134-140)
RESPONSE_ALLOW = "allow"
RESPONSE_DENY = "deny"
RESPONSE_ERROR = "error"
RESPONSE_UNKNOWN = "unknown"

# fixed messages/annotations for the explicit failure decisions so the
# AdmissionReview JSON is exact and testable (tests/test_webhook.py)
DEADLINE_MESSAGE = "admission deadline budget exhausted"
DEADLINE_CODE = 504
SHED_MESSAGE = "admission request shed under overload"
SHED_CODE = 429
FAIL_OPEN_ANNOTATION = "admission.gatekeeper.sh/fail-open"
FAIL_OPEN_DEADLINE = "deadline-exhausted"
FAIL_OPEN_INTERNAL = "internal-error"
FAIL_OPEN_SHED = "overload-shed"

log = gklog.get("webhook")


class NamespaceNotSynced(LookupError):
    """The review's namespace is not in the API store yet — an expected
    operational condition (informer lag), not an engine defect."""


@dataclass
class AdmissionResponse:
    allowed: bool
    message: str = ""
    code: int = 200
    # auditAnnotations: the fail-open path allows the request but stamps
    # WHY into the audit log (admissionreview v1 auditAnnotations field),
    # so a degraded webhook leaves a forensic trail instead of silently
    # admitting
    annotations: Optional[dict] = field(default=None)

    def to_dict(self, uid: str = "") -> dict:
        out = {"uid": uid, "allowed": self.allowed}
        if self.message or not self.allowed:
            out["status"] = {"message": self.message, "code": self.code}
        if self.annotations:
            out["auditAnnotations"] = dict(self.annotations)
        return out


def _allowed(msg: str = "") -> AdmissionResponse:
    return AdmissionResponse(True, msg)


def _denied(msg: str, code: int) -> AdmissionResponse:
    return AdmissionResponse(False, msg, code)


class ValidationHandler:
    def __init__(
        self,
        client,                       # gatekeeper_tpu.client.Client
        kube: Optional[InMemoryKube] = None,
        excluder: Optional[Excluder] = None,
        reporter=None,
        gk_namespace: str = "gatekeeper-system",
        log_denies: bool = False,
        emit_admission_events: bool = False,
        disable_enforcementaction_validation: bool = False,
        event_recorder: Optional[Callable[[dict], None]] = None,
        injected_config: Optional[dict] = None,
        fail_open: bool = False,
    ):
        self.client = client
        self.kube = kube
        self.excluder = excluder or Excluder()
        self.reporter = reporter
        self.gk_namespace = gk_namespace
        self.log_denies = log_denies
        self.emit_admission_events = emit_admission_events
        self.disable_enforcementaction_validation = (
            disable_enforcementaction_validation
        )
        self.event_recorder = event_recorder
        self.injected_config = injected_config
        # failure policy for internal errors and deadline exhaustion:
        # fail_open=True allows the request with an audit annotation
        # (availability over enforcement); the default denies (fail
        # closed).  Either way the decision is EXPLICIT — the caller gets
        # a well-formed AdmissionReview, never a hung socket.
        self.fail_open = fail_open
        self.service_account = (
            f"system:serviceaccount:{get_namespace()}:{SERVICE_ACCOUNT_NAME}"
        )

    # ---- entry -------------------------------------------------------------

    def handle(self, req: dict) -> AdmissionResponse:
        t0 = time.monotonic()
        # decision-log provenance (obs/decisionlog.py): the remaining
        # deadline budget at entry rides every record, and each return
        # site below lands one admission record — pre-review refusals
        # included — so a denied AdmissionReview survives the trace
        # ring's rotation
        budget_s = deadline_remaining()

        def _record(resp, hint=None, results=None):
            obsdlog.record_admission(
                req, resp, time.monotonic() - t0, budget_s=budget_s,
                results=results, hint=hint,
            )
            return resp

        if self._is_gk_service_account(req):
            return _record(_allowed("Gatekeeper does not self-manage"))

        is_delete = req.get("operation") == "DELETE"
        if is_delete:
            if req.get("oldObject") is None:
                return _record(_denied(
                    "For admission webhooks registered for DELETE operations, "
                    "please use Kubernetes v1.15.0+.",
                    500,
                ), hint=obsdlog.CLASS_ERROR)
            req = dict(req)
            req["object"] = req["oldObject"]

        # dry-run validation only gates writes; deleting a gatekeeper
        # resource must never require it to still compile/validate (an
        # orphaned constraint would otherwise be undeletable)
        if not is_delete:
            user_err, err = self._validate_gatekeeper_resources(req)
            if err is not None:
                return _record(_denied(err, 422 if user_err else 500))

        status = RESPONSE_UNKNOWN
        resp: Optional[AdmissionResponse] = None
        hint: Optional[str] = None
        results = None
        try:
            ns = req.get("namespace") or ""
            if self.excluder.is_namespace_excluded(WEBHOOK, ns):
                status = RESPONSE_ALLOW
                resp = _allowed(
                    "Namespace is set to be ignored by Gatekeeper config"
                )
                return resp
            try:
                results = self._review(req)
            except NamespaceNotSynced as e:
                # expected operational condition (namespace not yet synced,
                # policy.go:379-385): same 500 verdict, but logged without
                # the per-request traceback formatting — at admission rates
                # that costs ~0.7ms/request and is trivially attacker-paced
                log.warning("error executing query: %s", e)
                status = RESPONSE_ERROR
                hint = obsdlog.CLASS_ERROR
                resp = _denied(str(e), 500)
                return resp
            except DeadlineExceeded:
                # budget exhausted: explicit, policy-selected decision —
                # the apiserver gets a well-formed AdmissionReview inside
                # its own timeout instead of a hung socket
                log.warning("admission deadline budget exhausted")
                status = RESPONSE_ERROR
                hint = obsdlog.CLASS_EXPIRED
                resp = self._failure_response(
                    DEADLINE_MESSAGE, DEADLINE_CODE, FAIL_OPEN_DEADLINE
                )
                return resp
            except OverloadShed:
                # bounded-queue refusal (docs/failure-modes.md shed
                # order): the same explicit fail-open/closed decision,
                # answered FAST — the whole point of shedding is that
                # the refusal costs microseconds, not a queue wait
                log.warning("admission request shed under overload")
                status = RESPONSE_ERROR
                hint = obsdlog.CLASS_SHED
                resp = self._failure_response(
                    SHED_MESSAGE, SHED_CODE, FAIL_OPEN_SHED
                )
                return resp
            except Exception as e:  # error executing query -> 500
                log.exception("error executing query")
                status = RESPONSE_ERROR
                hint = obsdlog.CLASS_ERROR
                resp = self._failure_response(
                    str(e), 500, FAIL_OPEN_INTERNAL
                )
                return resp
            msgs = self._get_deny_messages(results, req)
            if msgs:
                status = RESPONSE_DENY
                resp = _denied("\n".join(msgs), 403)
                return resp
            status = RESPONSE_ALLOW
            resp = _allowed()
            return resp
        finally:
            obstrace.set_attrs(admission_status=status)
            duration_s = time.monotonic() - t0
            if resp is not None:
                # provenance record: class hint from the branch taken,
                # matched constraint set when a review completed
                obsdlog.record_admission(
                    req, resp, duration_s, budget_s=budget_s,
                    results=results, hint=hint,
                )
            # SLO event stream (obs/slo.py): the same outcome + duration
            # the request metric records, so burn rates and dashboards
            # agree by construction
            obsslo.observe_admission(status, duration_s)
            if self.reporter is not None:
                self.reporter.report_request(status, duration_s)

    def handle_many(self, items: List[tuple]) -> List[AdmissionResponse]:
        """Chunk admission for the wire listener (ISSUE 19): evaluate N
        parsed requests with ONE batcher enqueue instead of N.

        ``items`` is a list of ``(req, deadline, span)`` — deadline an
        absolute ``time.monotonic()`` instant or None, span the
        request's ``admission`` root span or None.  Returns responses
        aligned with ``items``.

        Semantics are handle()'s, request for request — same check
        order, same exception taxonomy, same decision-log records, same
        SLO stream — with the review leg routed through the client's
        submit_many/wait chunk API when it has one (the MicroBatcher),
        so the whole chunk costs one producer-lock round.  Traced
        requests and clients without submit_many fall back to the
        per-request review path."""
        n = len(items)
        out: List[Optional[AdmissionResponse]] = [None] * n
        meta = [None] * n        # (req, t0, budget_s, deadline, span)
        to_review: List[tuple] = []   # (idx, AugmentedReview, trace, dump)
        for idx, (req, deadline, span) in enumerate(items):
            t0 = time.monotonic()
            budget_s = (
                None if deadline is None else max(0.0, deadline - t0)
            )
            # --- handle()'s pre-try section: decision-log records only,
            # no SLO event (preserved asymmetry) ---
            if self._is_gk_service_account(req):
                resp = _allowed("Gatekeeper does not self-manage")
                obsdlog.record_admission(
                    req, resp, time.monotonic() - t0, budget_s=budget_s)
                out[idx] = resp
                continue
            is_delete = req.get("operation") == "DELETE"
            if is_delete:
                if req.get("oldObject") is None:
                    resp = _denied(
                        "For admission webhooks registered for DELETE "
                        "operations, please use Kubernetes v1.15.0+.",
                        500,
                    )
                    obsdlog.record_admission(
                        req, resp, time.monotonic() - t0,
                        budget_s=budget_s, hint=obsdlog.CLASS_ERROR)
                    out[idx] = resp
                    continue
                req = dict(req)
                req["object"] = req["oldObject"]
            if not is_delete:
                user_err, err = self._validate_gatekeeper_resources(req)
                if err is not None:
                    resp = _denied(err, 422 if user_err else 500)
                    obsdlog.record_admission(
                        req, resp, time.monotonic() - t0,
                        budget_s=budget_s)
                    out[idx] = resp
                    continue
            meta[idx] = (req, t0, budget_s, deadline, span)
            ns = req.get("namespace") or ""
            if self.excluder.is_namespace_excluded(WEBHOOK, ns):
                resp = _allowed(
                    "Namespace is set to be ignored by Gatekeeper config"
                )
                out[idx] = self._finalize_one(
                    req, resp, t0, budget_s, RESPONSE_ALLOW, None, None,
                    span)
                continue
            try:
                trace, dump = self._tracing_level(req)
                review = self._augmented_review(req)
            except NamespaceNotSynced as e:
                log.warning("error executing query: %s", e)
                out[idx] = self._finalize_one(
                    req, _denied(str(e), 500), t0, budget_s,
                    RESPONSE_ERROR, obsdlog.CLASS_ERROR, None, span)
                continue
            except Exception as e:
                log.exception("error executing query")
                out[idx] = self._finalize_one(
                    req, self._failure_response(str(e), 500,
                                                FAIL_OPEN_INTERNAL),
                    t0, budget_s, RESPONSE_ERROR, obsdlog.CLASS_ERROR,
                    None, span)
                continue
            to_review.append((idx, review, trace, dump))

        submit = getattr(self.client, "submit_many", None)
        waiter = getattr(self.client, "wait", None)
        batchable: List[tuple] = []
        for idx, review, trace, dump in to_review:
            if submit is None or waiter is None or trace:
                # traced requests want their own trace output (and a
                # client without the chunk API has no batch lane):
                # evaluate solo, exactly like _review
                req, t0, budget_s, deadline, span = meta[idx]
                out[idx] = self._review_one_direct(
                    req, review, trace, dump, t0, budget_s, deadline,
                    span)
            else:
                batchable.append((idx, review))
        if batchable:
            pendings = submit([
                (review, meta[idx][3], meta[idx][4])
                for idx, review in batchable
            ])
            for (idx, review), p in zip(batchable, pendings):
                req, t0, budget_s, deadline, span = meta[idx]
                results = None
                try:
                    resp_obj = waiter(p)
                    results = resp_obj.results()
                except Exception as e:
                    out[idx] = self._finalize_failure(
                        req, e, t0, budget_s, span)
                    continue
                out[idx] = self._finalize_verdict(
                    req, results, t0, budget_s, span)
        return out  # type: ignore[return-value]

    def _review_one_direct(self, req, review, trace, dump, t0, budget_s,
                           deadline, span) -> AdmissionResponse:
        """handle()'s review leg for one chunk member without the batch
        lane (traced request, or a client with no submit_many)."""
        results = None
        token = None
        try:
            if deadline is not None:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise DeadlineExceeded(
                        "admission deadline budget exhausted before "
                        "evaluation"
                    )
                # the wire lane has no ambient deadline (do_POST pushes
                # one on the HTTP edge): bound the batcher wait by the
                # caller's REMAINING budget or a traced request parks
                # its wire worker past the caller's deadline
                token = deadline_push(rem)
            resp_obj = self.client.review(review, tracing=trace)
            if trace:
                log.info(resp_obj.trace_dump())
            if dump:
                log.info(self.client.dump())
            results = resp_obj.results()
        except Exception as e:
            return self._finalize_failure(req, e, t0, budget_s, span)
        finally:
            if token is not None:
                deadline_pop(token)
        return self._finalize_verdict(req, results, t0, budget_s, span)

    def _finalize_failure(self, req, e, t0, budget_s,
                          span) -> AdmissionResponse:
        """handle()'s except-chain, verbatim, for the chunk path."""
        if isinstance(e, NamespaceNotSynced):
            log.warning("error executing query: %s", e)
            return self._finalize_one(
                req, _denied(str(e), 500), t0, budget_s,
                RESPONSE_ERROR, obsdlog.CLASS_ERROR, None, span)
        if isinstance(e, DeadlineExceeded):
            log.warning("admission deadline budget exhausted")
            return self._finalize_one(
                req, self._failure_response(
                    DEADLINE_MESSAGE, DEADLINE_CODE, FAIL_OPEN_DEADLINE),
                t0, budget_s, RESPONSE_ERROR, obsdlog.CLASS_EXPIRED,
                None, span)
        if isinstance(e, OverloadShed):
            log.warning("admission request shed under overload")
            return self._finalize_one(
                req, self._failure_response(
                    SHED_MESSAGE, SHED_CODE, FAIL_OPEN_SHED),
                t0, budget_s, RESPONSE_ERROR, obsdlog.CLASS_SHED,
                None, span)
        log.exception("error executing query")
        return self._finalize_one(
            req, self._failure_response(str(e), 500, FAIL_OPEN_INTERNAL),
            t0, budget_s, RESPONSE_ERROR, obsdlog.CLASS_ERROR, None, span)

    def _finalize_verdict(self, req, results, t0, budget_s,
                          span) -> AdmissionResponse:
        msgs = self._get_deny_messages(results, req)
        if msgs:
            return self._finalize_one(
                req, _denied("\n".join(msgs), 403), t0, budget_s,
                RESPONSE_DENY, None, results, span)
        return self._finalize_one(
            req, _allowed(), t0, budget_s, RESPONSE_ALLOW, None, results,
            span)

    def _finalize_one(self, req, resp, t0, budget_s, status, hint,
                      results, span) -> AdmissionResponse:
        """handle()'s finally block for one chunk member: status attr on
        the request's OWN span (the chunk path has no per-request
        CURRENT), then the identical decision-log + SLO + reporter
        triple."""
        duration_s = time.monotonic() - t0
        if span is not None:
            span.set_attrs(admission_status=status)
        obsdlog.record_admission(
            req, resp, duration_s, budget_s=budget_s, results=results,
            hint=hint,
        )
        obsslo.observe_admission(status, duration_s)
        if self.reporter is not None:
            self.reporter.report_request(status, duration_s)
        return resp

    # ---- pieces ------------------------------------------------------------

    def _failure_response(self, msg: str, code: int,
                          reason: str) -> AdmissionResponse:
        """The explicit degraded-path decision: deny (fail closed,
        default) or allow with an audit annotation recording why
        (fail open).  docs/failure-modes.md describes the ladder."""
        if self.fail_open:
            return AdmissionResponse(
                True, msg, 200,
                annotations={FAIL_OPEN_ANNOTATION: reason},
            )
        return _denied(msg, code)

    def _is_gk_service_account(self, req: dict) -> bool:
        user = (req.get("userInfo") or {}).get("username", "")
        return user == self.service_account

    def _validate_gatekeeper_resources(self, req: dict):
        """-> (user_error, error_message|None)  (policy.go:310-360)."""
        kind = req.get("kind") or {}
        group, k = kind.get("group", ""), kind.get("kind", "")
        obj = req.get("object")
        if group == TEMPLATE_GROUP and k == "ConstraintTemplate":
            try:
                self.client.create_crd(obj)
            except Exception as e:
                return True, str(e)
            return False, None
        if group == CONSTRAINT_GROUP:
            try:
                self.client.validate_constraint(obj)
            except Exception as e:
                return True, str(e)
            action = ((obj or {}).get("spec") or {}).get("enforcementAction")
            if isinstance(action, str) and action:
                if not self.disable_enforcementaction_validation:
                    try:
                        validate_enforcement_action(action)
                    except EnforcementActionError as e:
                        return False, str(e)
            return False, None
        return False, None

    def _get_config(self) -> dict:
        if self.injected_config is not None:
            return self.injected_config
        if self.kube is None:
            return {}
        try:
            return self.kube.get(CONFIG_GVK, CONFIG_NAME, self.gk_namespace)
        except NotFound:
            return {}

    def _tracing_level(self, req: dict):
        """(trace, dump) from Config.spec.validation.traces
        (policy.go:402-423)."""
        cfg = parse_config(self._get_config())
        user = (req.get("userInfo") or {}).get("username", "")
        kind = req.get("kind") or {}
        gvk = (kind.get("group", ""), kind.get("version", ""), kind.get("kind", ""))
        trace = dump = False
        for t in cfg.traces:
            if t.user != user:
                continue
            if t.kind == gvk:
                trace = True
                if t.dump.lower() == "all":
                    dump = True
        return trace, dump

    def _augmented_review(self, req: dict) -> AugmentedReview:
        req = dict(req)
        kind = req.get("kind") or {}
        # server-side-apply namespace coercion for Namespace objects
        # (policy.go:365-369, issue #792)
        if kind.get("kind") == "Namespace" and kind.get("group", "") == "":
            req["namespace"] = ""
        ns_obj = None
        ns = req.get("namespace") or ""
        if ns and self.kube is not None:
            # cached client then direct API reader (policy.go:372-385);
            # with one API abstraction both reads collapse into this get
            try:
                ns_obj = self.kube.get(("", "v1", "Namespace"), ns)
            except NotFound:
                raise NamespaceNotSynced(f"namespace {ns} not found")
        return AugmentedReview(admission_request=req, namespace=ns_obj)

    def _review(self, req: dict) -> List:
        trace, dump = self._tracing_level(req)
        review = self._augmented_review(req)
        resp = self.client.review(review, tracing=trace)
        if trace:
            log.info(resp.trace_dump())
        if dump:
            log.info(self.client.dump())
        return resp.results()

    def _get_deny_messages(self, results: List, req: dict) -> List[str]:
        msgs: List[str] = []
        resource_name = req.get("name") or ""
        if not resource_name and isinstance(req.get("object"), dict):
            resource_name = (
                (req["object"].get("metadata") or {}).get("name") or ""
            )
        kind = req.get("kind") or {}
        for r in results:
            cname = (r.constraint.get("metadata") or {}).get("name", "")
            if r.enforcement_action in (ACTION_DENY, ACTION_DRYRUN):
                kv = {
                    gklog.PROCESS: "admission",
                    gklog.EVENT_TYPE: "violation",
                    gklog.CONSTRAINT_NAME: cname,
                    gklog.CONSTRAINT_GROUP: CONSTRAINT_GROUP,
                    gklog.CONSTRAINT_API_VERSION: "v1beta1",
                    gklog.CONSTRAINT_KIND: r.constraint.get("kind", ""),
                    gklog.CONSTRAINT_ACTION: r.enforcement_action,
                    gklog.RESOURCE_GROUP: kind.get("group", ""),
                    gklog.RESOURCE_API_VERSION: kind.get("version", ""),
                    gklog.RESOURCE_KIND: kind.get("kind", ""),
                    gklog.RESOURCE_NAMESPACE: req.get("namespace", ""),
                    gklog.RESOURCE_NAME: resource_name,
                    gklog.REQUEST_USERNAME: (req.get("userInfo") or {}).get(
                        "username", ""
                    ),
                }
                if self.log_denies:
                    gklog.log_event(log, "denied admission", **kv)
                if self.emit_admission_events and self.event_recorder:
                    dryrun = r.enforcement_action == ACTION_DRYRUN
                    event_msg = (
                        "Dryrun violation"
                        if dryrun
                        else 'Admission webhook "validation.gatekeeper.sh" denied request'
                    )
                    self.event_recorder(
                        {
                            "reason": "DryrunViolation" if dryrun else "FailedAdmission",
                            "type": "Warning",
                            "message": (
                                f"{event_msg}, "
                                f"Resource Namespace: {req.get('namespace', '')}, "
                                f"Constraint: {cname}, Message: {r.msg}"
                            ),
                            "annotations": kv,
                            "namespace": self.gk_namespace,
                        }
                    )
            # only deny prompts a deny admission response (policy.go:286-288)
            if r.enforcement_action == ACTION_DENY:
                msgs.append(f"[denied by {cname}] {r.msg}")
        return msgs
