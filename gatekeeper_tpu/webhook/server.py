"""Webhook HTTPS front end with TPU micro-batching.

The reference serves /v1/admit and /v1/admitlabel from controller-runtime's
webhook server (pkg/webhook/webhook.go:36-43, main.go:145).  Here the server
is a threaded HTTP(S) listener whose admission path goes through a
`MicroBatcher`: concurrent requests inside a short window coalesce into ONE
batched device dispatch (TpuDriver.review_batch), which is how p99 stays low
while the TPU runs at batch efficiency (SURVEY.md §7 stage 5).
"""

from __future__ import annotations

import json
import ssl
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from .. import deadline as _deadline
from .. import faults
from .. import logging as gklog
from ..metrics.catalog import (
    WEBHOOK_QUEUE_M,
    record_batch_size,
    record_batcher_state,
    record_shed,
    record_stage,
)
from ..obs import trace as obstrace
from ..util import join_thread
from ..obs.debug import get_router
from .namespacelabel import NamespaceLabelHandler
from .policy import AdmissionResponse, ValidationHandler

log = gklog.get("webhook.server")

# paths that never produce an access log line (scrape/probe traffic —
# the /metrics convention extended to the debug surface)
QUIET_PATHS = ("/healthz", "/readyz", "/statusz", "/metrics")
DEBUG_PREFIX = "/debug/"


class BatcherStopped(RuntimeError):
    """Raised to requests enqueued on (or pending across) a stopped
    MicroBatcher — they must fail fast, not wait on an event forever."""


def _low_value(obj) -> bool:
    """Shed-priority classification (docs/failure-modes.md shed order):
    dry-run admissions are advisory — under overload they are refused
    before any enforced admission is.  Accepts both the handler's
    AugmentedReview and a bare request dict (tests, embedders)."""
    req = getattr(obj, "admission_request", None)
    if req is None and isinstance(obj, dict):
        req = obj
    return bool(isinstance(req, dict) and req.get("dryRun"))


_SPAN_CURRENT = object()  # _Pending sentinel: adopt the caller's span


class _Pending:
    __slots__ = (
        "obj", "event", "result", "error", "deadline", "low_value",
        "span", "queue_span",
    )

    def __init__(self, obj, deadline: Optional[float] = None,
                 span=_SPAN_CURRENT):
        self.obj = obj
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None
        self.deadline = deadline  # absolute monotonic, or None
        self.low_value = _low_value(obj)
        # explicit cross-thread context passing: the request's active span
        # (linked by the batch span) and its open queue-wait span (ended
        # by the batch thread when the batch is drained).  The wire
        # listener's chunk path has no per-request thread, so it passes
        # each request's span explicitly instead of relying on CURRENT.
        if span is _SPAN_CURRENT:
            span = obstrace.current_span()
        self.span = span
        self.queue_span = (
            obstrace.detached_span(
                "webhook.queue_wait", parent=self.span,
                stage=obstrace.QUEUE_WAIT,
            )
            if self.span is not None else None
        )


class MicroBatcher:
    """Client-compatible wrapper that coalesces concurrent review() calls.

    Continuous batching: when the system is idle, a request dispatches
    immediately (zero added latency — the sparse-traffic p99 must not pay
    the window).  During a burst — detected as arrivals landing hot on the
    heels of the previous dispatch — the thread holds the window open for
    up to `window_s` so concurrent arrivals share one review_batch; and
    while a batch is evaluating, new arrivals accumulate naturally behind
    it, which is the real batching mechanism under sustained load.

    LOAD-ADAPTIVE (docs/fleet.md): with a routing calibration on the
    driver (TpuDriver.calibrate_routing — rtt/cells-per-ms, the
    BENCH_r04/r05 `routing_calibration` model), the batcher continuously
    adapts to the offered load it observes:

    - it tracks a decayed arrival rate λ (reviews/s);
    - the TARGET batch size is the batching equilibrium B = λ·T(B),
      where T(B) is the model-predicted service time of a B-review
      batch on its cheapest tier — low load fixes the target at 1
      (immediate flush, the inline fast path keeps the p99 floor), high
      load grows batches toward the throughput-optimal tier;
    - the FLUSH DEADLINE is the time it takes λ to deliver the target
      (capped by ``max_deadline_s``), so a lull never strands a partial
      batch;
    - λ is pushed to the driver (set_offered_load) each dispatch, which
      makes the interp/np/device route choice load-aware instead of
      size-only.

    Without a calibration the adaptive controller stays dormant and the
    original recent-concurrency window heuristic applies unchanged.
    """

    # adaptation cadence/shape knobs (class-level so tests can tune)
    RATE_BUCKET_S = 0.25     # arrival-rate sampling bucket
    RATE_ALPHA = 0.5         # EWMA blend per bucket
    IDLE_RESET_S = 2.0       # no arrivals this long -> rate resets to 0
    # dispatch headroom reserved when the adaptive window is clamped to
    # a queued member's admission-deadline budget
    DEADLINE_CLAMP_MARGIN_S = 0.002
    # bounded backpressure (ISSUE 12, docs/failure-modes.md): the pending
    # queue never grows past this — past the bound, the lowest-value work
    # (dry-run admissions) sheds first, then new arrivals shed outright.
    # 0 = unbounded (the pre-overload-plane behavior, tests only).
    MAX_PENDING = 1024

    def __init__(self, client, window_s: float = 0.002, max_batch: int = 256,
                 adaptive: bool = True, max_deadline_s: float = 0.025,
                 max_pending: Optional[int] = None):
        self._client = client
        self.window_s = window_s
        self.max_batch = max_batch
        self.adaptive = adaptive
        self.max_deadline_s = max_deadline_s
        self.max_pending = (
            self.MAX_PENDING if max_pending is None else int(max_pending)
        )
        self.sheds = 0  # queue-bound refusals (brownout signal + /statusz)
        self._pending: List[_Pending] = []
        # queued dry-run count (maintained under the cv): the at-bound
        # eviction scan short-circuits to O(1) when no dry-run is
        # queued — the common case under an all-enforced storm, which
        # is exactly when the enqueue path is hottest
        self._pending_dryruns = 0
        self._cv = threading.Condition()
        self._inline = threading.Lock()  # at most one idle fast-path eval
        self._busy = False  # a batch is evaluating (pending already drained)
        self._stop = False
        # arrival-rate tracking (its own tiny lock: the inline fast path
        # must not contend on _cv just to count itself)
        self._rate_lock = threading.Lock()
        self._arrivals = 0
        self._rate_t0 = time.monotonic()
        self._load_rps = 0.0
        # current adaptation state (read by tests, /debug spans, metrics)
        self._target_batch = 1
        self._deadline_s = 0.0
        self._thread = threading.Thread(
            target=self._run, name="microbatcher", daemon=True
        )
        self._thread.start()

    # anything that isn't review() passes straight through to the client
    def __getattr__(self, name):
        return getattr(self._client, name)

    # ---- load-adaptive controller ---------------------------------------

    def _note_arrival(self):
        with self._rate_lock:
            self._arrivals += 1

    def offered_load_rps(self) -> float:
        """Decayed arrival rate (reviews/s); rolls the sampling bucket as
        a side effect.  An empty bucket decays the EWMA toward zero, so
        a burst minutes ago never taxes today's lone request."""
        now = time.monotonic()
        with self._rate_lock:
            dt = now - self._rate_t0
            if dt >= self.RATE_BUCKET_S:
                inst = self._arrivals / dt
                if dt >= self.IDLE_RESET_S:
                    # the bucket only rolls when _adapt runs, so a long
                    # gap means the batcher sat idle: adopt the gap's
                    # observed (near-zero) rate outright — one EWMA
                    # blend would leave half of a minutes-old burst
                    # taxing today's lone request with a deadline
                    self._load_rps = inst
                else:
                    self._load_rps = (
                        inst if self._load_rps == 0.0
                        else (1.0 - self.RATE_ALPHA) * self._load_rps
                        + self.RATE_ALPHA * inst
                    )
                if self._load_rps < 1e-3:
                    self._load_rps = 0.0
                self._arrivals = 0
                self._rate_t0 = now
            return self._load_rps

    def _service_model(self):
        """(predict, set_load) from the wrapped client's driver — None
        pair when there is no calibrated TpuDriver underneath (tests,
        interp deployments): the adaptive controller then stays dormant
        and the static recent-concurrency heuristic applies."""
        drv = getattr(self._client, "driver", None)
        target = drv if drv is not None else self._client
        return (
            getattr(target, "predicted_batch_ms", None),
            getattr(target, "set_offered_load", None),
        )

    def _adapt(self):
        """(target_batch, deadline_s) for the next accumulation window.

        Target is the batching equilibrium B = λ·T(B) under the driver's
        calibrated service model T (fixed-point iterated, clamped to
        [1, max_batch]): while one batch evaluates, λ·T(B) new arrivals
        accumulate behind it, so dispatching exactly that many keeps the
        queue stationary.  The deadline is the time λ needs to deliver
        the target (capped), so a lull flushes a partial batch instead
        of stranding it.  Low load collapses to (1, 0) — immediate
        dispatch, the inline fast path keeps the sparse-traffic p99.
        Pushes λ to the driver so routing is load-aware, and exports the
        webhook_batch_* gauges."""
        lam = self.offered_load_rps()
        target, deadline = 1, 0.0
        predict, set_load = self._service_model()
        if self.adaptive and lam > 0.0 and predict is not None:
            try:
                if set_load is not None:
                    set_load(lam)
                lam_pms = lam / 1e3
                b = 1.0
                t_ms = None
                for _ in range(4):  # fixed point; converges in 2-3 steps
                    t_ms = predict(max(int(b), 1))
                    if t_ms is None:
                        break
                    nb = min(max(lam_pms * t_ms, 1.0),
                             float(self.max_batch))
                    if abs(nb - b) < 0.5:
                        b = nb
                        break
                    b = nb
                if t_ms is not None:
                    target = max(int(round(b)), 1)
                    if target > 1:
                        deadline = min(target / lam, self.max_deadline_s)
            except Exception:  # the model must never stall dispatch
                target, deadline = 1, 0.0
        self._target_batch, self._deadline_s = target, deadline
        record_batcher_state(target, deadline * 1e3, lam)
        return target, deadline

    def review(self, obj, tracing: bool = False):
        if faults.ENABLED:
            faults.fire(faults.WEBHOOK_ENQUEUE)
        self._note_arrival()
        if tracing:
            # traced requests are rare and want their own trace output;
            # bypass the batch
            return self._client.review(obj, tracing=True)
        dl = _deadline.current()
        if dl is not None and time.monotonic() > dl:
            # refuse to enqueue work that can no longer finish in budget
            raise _deadline.DeadlineExceeded(
                "admission deadline budget exhausted before evaluation"
            )
        # idle fast path: with nothing else in flight, evaluate on the
        # caller's thread — two scheduler handoffs per request otherwise
        # put milliseconds of wakeup jitter into the sparse-traffic p99.
        # The lock bounds inline evaluation to one caller; arrivals during
        # an in-flight batch (_busy) queue instead, so they join the next
        # coalesced dispatch rather than blocking solo on the driver lock.
        # Deadline-carrying requests always queue: an inline evaluation on
        # the caller's thread cannot be interrupted, so a wedged backend
        # would hold the request past any budget — the queued path's
        # event wait is what bounds time-to-answer (docs/failure-modes.md).
        if (
            dl is None
            and not self._stop  # stopped batcher: fall through and reject
            and not self._pending
            and not self._busy
            and self._inline.acquire(blocking=False)
        ):
            try:
                if not self._pending and not self._busy and not self._stop:
                    return self._client.review(obj)
            finally:
                self._inline.release()
        p = _Pending(obj, deadline=dl)
        # bounded backpressure (docs/failure-modes.md shed order): the
        # decision is made under the cv, but refusals are DELIVERED (and
        # counted) outside it — Event.set on an evicted waiter and the
        # registry record must not run under the producer lock
        evicted: Optional[_Pending] = None
        shed_self = False
        with self._cv:
            if self._stop:
                # enqueues after stop() must fail fast, never wait on an
                # event no batch loop will ever set
                raise BatcherStopped("webhook batcher is stopped")
            if self.max_pending and len(self._pending) >= self.max_pending:
                if p.low_value:
                    # a dry-run arrival at the bound sheds itself: it is
                    # the lowest-value work in sight
                    shed_self = True
                elif self._pending_dryruns > 0:
                    # an enforced admission preempts the oldest QUEUED
                    # dry-run (the counter makes the no-dry-run case
                    # O(1) — no scan under the cv at peak load)
                    for i, q in enumerate(self._pending):
                        if q.low_value:
                            evicted = self._pending.pop(i)
                            self._pending_dryruns -= 1
                            break
                    if evicted is None:
                        shed_self = True
                else:
                    # nothing to preempt — the bound is the bound
                    shed_self = True
            if not shed_self:
                self._pending.append(p)
                if p.low_value:
                    self._pending_dryruns += 1
                self._cv.notify()
        if evicted is not None:
            with self._rate_lock:  # += races concurrent shedders
                self.sheds += 1
            if evicted.queue_span is not None:
                evicted.queue_span.end()
            evicted.error = _deadline.OverloadShed(
                "dry-run admission preempted by enforced work at the "
                "pending bound"
            )
            evicted.event.set()
            record_shed("queue_full_dryrun")
        if shed_self:
            with self._rate_lock:  # += races concurrent shedders
                self.sheds += 1
            if p.queue_span is not None:
                # the span opened at _Pending construction must close
                # even though the request never queued — shed traces
                # otherwise lose their (zero-length) queue_wait stage
                p.queue_span.end()
            record_shed(
                "queue_full_dryrun" if p.low_value else "queue_full"
            )
            raise _deadline.OverloadShed(
                "micro-batcher pending queue is at its bound "
                f"({self.max_pending})"
            )
        if dl is None:
            p.event.wait()
        elif not p.event.wait(timeout=max(0.0, dl - time.monotonic())):
            raise _deadline.DeadlineExceeded(
                "admission deadline budget exhausted"
            )
        if p.error is not None:
            raise p.error
        return p.result

    def submit_many(self, items):
        """Chunk enqueue (ISSUE 19): admit a whole decoded wire chunk
        under ONE cv acquisition — the point of the batched door↔replica
        protocol is that N pipelined requests cost one producer-lock
        round and one notify, not N.

        ``items`` is an iterable of ``(obj, deadline, span)`` — deadline
        an absolute monotonic instant or None, span the request's root
        span or None (the chunk path has no per-request thread, so
        CURRENT would be wrong).  Returns the list of `_Pending`s, every
        one of which WILL complete: refusals — stopped batcher, expired
        budget, queue bound — are delivered as ``p.error`` instead of
        raised, so the caller finalizes all requests of a chunk through
        the same :meth:`wait` tail.  Shed accounting (self.sheds,
        record_shed, dry-run-first eviction) matches review() exactly:
        the overload taxonomy must not care which transport carried the
        request."""
        pendings: List[_Pending] = []
        for obj, dl, span in items:
            if faults.ENABLED:
                faults.fire(faults.WEBHOOK_ENQUEUE)
            pendings.append(_Pending(obj, deadline=dl, span=span))
        with self._rate_lock:
            self._arrivals += len(pendings)
        now = time.monotonic()
        stopped = False
        queued_any = False
        evictions: List[_Pending] = []
        refused: List[_Pending] = []   # queue-bound sheds
        expired: List[_Pending] = []   # dead-on-arrival budgets
        with self._cv:
            if self._stop:
                stopped = True
            else:
                for p in pendings:
                    if p.deadline is not None and now > p.deadline:
                        expired.append(p)
                        continue
                    evicted: Optional[_Pending] = None
                    if (self.max_pending
                            and len(self._pending) >= self.max_pending):
                        if p.low_value:
                            refused.append(p)
                            continue
                        if self._pending_dryruns > 0:
                            for i, q in enumerate(self._pending):
                                if q.low_value:
                                    evicted = self._pending.pop(i)
                                    self._pending_dryruns -= 1
                                    break
                        if evicted is None:
                            refused.append(p)
                            continue
                    self._pending.append(p)
                    if p.low_value:
                        self._pending_dryruns += 1
                    queued_any = True
                    if evicted is not None:
                        evictions.append(evicted)
            if queued_any:
                self._cv.notify()
        # deliveries happen OUTSIDE the cv, exactly as in review():
        # Event.set and registry records must not run under the producer
        # lock
        if stopped:
            for p in pendings:
                if p.queue_span is not None:
                    p.queue_span.end()
                p.error = BatcherStopped("webhook batcher is stopped")
                p.event.set()
            return pendings
        for ev in evictions:
            with self._rate_lock:
                self.sheds += 1
            if ev.queue_span is not None:
                ev.queue_span.end()
            ev.error = _deadline.OverloadShed(
                "dry-run admission preempted by enforced work at the "
                "pending bound"
            )
            ev.event.set()
            record_shed("queue_full_dryrun")
        for p in refused:
            with self._rate_lock:
                self.sheds += 1
            if p.queue_span is not None:
                p.queue_span.end()
            record_shed("queue_full_dryrun" if p.low_value else "queue_full")
            p.error = _deadline.OverloadShed(
                "micro-batcher pending queue is at its bound "
                f"({self.max_pending})"
            )
            p.event.set()
        for p in expired:
            if p.queue_span is not None:
                p.queue_span.end()
            p.error = _deadline.DeadlineExceeded(
                "admission deadline budget exhausted before evaluation"
            )
            p.event.set()
        return pendings

    def wait(self, p: "_Pending"):
        """Block until a submit_many pending completes — the same tail
        as review(): a deadline-bounded event wait, then the error (if
        any) raised on the waiter's thread."""
        if p.deadline is None:
            p.event.wait()
        elif not p.event.wait(timeout=max(0.0, p.deadline - time.monotonic())):
            raise _deadline.DeadlineExceeded(
                "admission deadline budget exhausted"
            )
        if p.error is not None:
            raise p.error
        return p.result

    def _run(self):
        import time as _time

        last_batch_size = 0
        last_dispatch_end = 0.0
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait(timeout=0.1)
                if self._stop and not self._pending:
                    return
            # adapt OUTSIDE the cv: the service model takes the driver
            # lock (predicted_batch_ms -> _n_constraints_total), and a
            # long driver hold (audit sweep, snapshot capture) must not
            # stall every enqueue behind the cv — producers only need
            # the cv to append and notify
            target, deadline = self._adapt()
            with self._cv:
                # load-adaptive accumulation (docs/fleet.md): with a
                # calibrated service model and observed load, hold the
                # window until the equilibrium target batch arrives or
                # the adaptive deadline lapses (each arrival notifies the
                # cv, so a filled target dispatches immediately)
                goal = min(target, self.max_batch)
                if target > 1 and len(self._pending) < goal:
                    t_end = _time.monotonic() + deadline
                    while (
                        not self._stop and len(self._pending) < goal
                    ):
                        # a deadline-budgeted member must never be held
                        # past its own budget by the adaptive window:
                        # clamp to the earliest pending deadline (minus
                        # a dispatch margin), recomputed each pass since
                        # new arrivals may carry tighter budgets
                        cut = t_end
                        for p in self._pending:
                            if p.deadline is not None:
                                cut = min(
                                    cut,
                                    p.deadline
                                    - self.DEADLINE_CLAMP_MARGIN_S,
                                )
                        remaining = cut - _time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(timeout=remaining)
                else:
                    # static heuristic (no calibration / low load): open
                    # the window only under observed, RECENT concurrency
                    # (several already waiting, or the previous batch
                    # coalesced moments ago) — a sequential client
                    # issuing one request at a time must never pay the
                    # window, or the sparse-traffic p99 absorbs it
                    # wholesale; and a burst minutes ago must not tax
                    # today's lone request
                    recent = (
                        _time.monotonic() - last_dispatch_end
                        < 5 * self.window_s
                    )
                    concurrent = len(self._pending) > 1 or (
                        last_batch_size > 1 and recent
                    )
                    if concurrent and len(self._pending) < self.max_batch:
                        self._cv.wait(timeout=self.window_s)
                batch = self._pending[: self.max_batch]
                self._pending = self._pending[self.max_batch:]
                if self._pending_dryruns:
                    self._pending_dryruns -= sum(
                        1 for q in batch if q.low_value
                    )
                last_batch_size = len(batch)
                self._busy = True
            # the batch is drained: queue-wait ends here for every member
            # (deadline-refused ones included — their wait was real)
            for p in batch:
                if p.queue_span is not None:
                    p.queue_span.end()
                    record_stage(
                        WEBHOOK_QUEUE_M,
                        p.queue_span.stop - p.queue_span.start,
                    )
            # refuse past-deadline work before paying a dispatch for it:
            # the waiter has already (or will imminently) time out, and
            # evaluating its review is pure wasted device time
            now = _time.monotonic()
            live = []
            for p in batch:
                if p.deadline is not None and now > p.deadline:
                    p.error = _deadline.DeadlineExceeded(
                        "admission deadline budget exhausted in queue"
                    )
                    p.event.set()
                else:
                    live.append(p)
            batch = live
            # one batch span serving N request spans: linked to each, and
            # every span of the batch trace (this one + the driver's stage
            # spans) mirrors into each request trace, so request traces
            # stay self-contained (obs/trace.py batch_span)
            bsp = None
            btoken = None
            if batch:
                record_batch_size(len(batch))
                req_spans = [p.span for p in batch if p.span is not None]
                if req_spans:  # un-traced batches skip span work entirely
                    # adaptation state on the dispatch span, mirrored
                    # into every member's trace: /debug/traces shows WHY
                    # a given request waited (target it accumulated
                    # toward, deadline, the load that set them)
                    bsp = obstrace.batch_span(
                        "webhook.batch", req_spans, batch_size=len(batch),
                        batch_target=self._target_batch,
                        batch_deadline_ms=round(self._deadline_s * 1e3, 3),
                        offered_load_rps=round(self._load_rps, 1),
                    )
                    # activate (not a bare CURRENT.set): the sampling
                    # profiler's stage correlation reads the cross-
                    # thread registry, and this loop is exactly the
                    # dispatch thread it needs to see (obs/profiler.py)
                    btoken = obstrace.activate(bsp)
            try:
                if batch:
                    responses = self._client.review_batch(
                        [p.obj for p in batch]
                    )
                    if bsp is not None:
                        obstrace.deactivate(btoken)
                        btoken = None
                        bsp.end()
                        bsp = None
                    for p, resp in zip(batch, responses):
                        p.result = resp
                        p.event.set()
            except Exception:
                # batched failure: fall back to per-request evaluation so one
                # poisoned review can't fail the whole window — but check
                # each request's remaining budget first; a request whose
                # deadline lapsed during the failed dispatch gets an
                # explicit deadline error, not another evaluation.
                # The batch span ends FIRST: fallback evaluations run under
                # each request's OWN span, not the batch span — otherwise
                # every fallback's stage spans would mirror into all N
                # request traces (and keep appending after their waiters
                # were released)
                if bsp is not None:
                    obstrace.deactivate(btoken)
                    btoken = None
                    bsp.end()
                    bsp = None
                for p in batch:
                    if (
                        p.deadline is not None
                        and _time.monotonic() > p.deadline
                    ):
                        p.error = _deadline.DeadlineExceeded(
                            "admission deadline budget exhausted during "
                            "per-request fallback"
                        )
                        p.event.set()
                        continue
                    try:
                        if p.span is not None:
                            with obstrace.use_span(p.span):
                                p.result = self._client.review(p.obj)
                        else:
                            p.result = self._client.review(p.obj)
                    except Exception as e:
                        p.error = e
                    p.event.set()
            finally:
                if btoken is not None:
                    obstrace.deactivate(btoken)
                if bsp is not None:
                    bsp.end()  # idempotent on the success path
                self._busy = False
                last_dispatch_end = _time.monotonic()

    def drain(self, deadline_s: float) -> dict:
        """Flush the queue for a graceful shutdown (docs/fleet.md drain
        protocol): wait until every already-enqueued request has been
        dispatched AND answered (or refused by its own admission budget —
        each queued member's deadline still bounds it individually), up
        to `deadline_s`.  The batcher keeps running — new arrivals during
        the drain are NOT rejected here; stopping intake is the server's
        job (WebhookServer.drain), sequenced by the supervisor before
        this flush.  Returns {"pending_start", "drained", "overran",
        "drain_ms"}; never blocks past the deadline."""
        t0 = time.monotonic()
        deadline = t0 + max(0.0, deadline_s)
        with self._cv:
            pending_start = len(self._pending)
        while time.monotonic() < deadline:
            with self._cv:
                if not self._pending and not self._busy:
                    break
                # each arrival/dispatch notifies the cv; cap the wait so
                # a missed notify cannot overrun the budget
                self._cv.wait(
                    timeout=min(0.005, max(0.0,
                                           deadline - time.monotonic()))
                )
        with self._cv:
            leftover = len(self._pending) or (1 if self._busy else 0)
        dur = time.monotonic() - t0
        return {
            "pending_start": pending_start,
            "drained": leftover == 0,
            "overran": leftover > 0,
            "drain_ms": round(dur * 1e3, 3),
        }

    def stop(self):
        # clear the driver's load hint: a stopped batcher must not pin
        # throughput routing for whoever evaluates next (tests, restarts)
        try:
            _predict, set_load = self._service_model()
            if set_load is not None:
                set_load(None)
        except Exception:
            log.debug("clearing driver load hint failed on batcher stop",
                      exc_info=True)
        # drain under the cv lock: a request appended concurrently either
        # lands before the drain (gets BatcherStopped here) or after _stop
        # is set (review() rejects it) — no pending can be left waiting on
        # an event forever (the shutdown race this replaces)
        with self._cv:
            self._stop = True
            drained, self._pending = self._pending, []
            self._pending_dryruns = 0
            for p in drained:
                p.error = BatcherStopped(
                    "webhook batcher stopped before evaluation"
                )
                p.event.set()
            self._cv.notify_all()
        join_thread(self._thread, 2.0, "webhook micro-batcher loop")


class WebhookServer:
    """HTTP(S) listener for /v1/admit + /v1/admitlabel + health endpoints."""

    def __init__(
        self,
        validation_handler: ValidationHandler,
        label_handler: Optional[NamespaceLabelHandler] = None,
        port: int = 8443,
        certfile: Optional[str] = None,
        keyfile: Optional[str] = None,
        readiness_check=None,  # callable -> bool (tracker.satisfied)
        deadline_budget_s: Optional[float] = None,
        health_status: Optional[Callable[[], dict]] = None,
    ):
        self.validation_handler = validation_handler
        self.label_handler = label_handler or NamespaceLabelHandler()
        self.port = port
        self.certfile = certfile
        self.keyfile = keyfile
        self.readiness_check = readiness_check
        # per-request deadline budget: every admission request entering
        # this server carries monotonic_now + budget as its deadline; the
        # batching client and driver fallbacks refuse work past it, and
        # the handler converts exhaustion into an explicit fail-open or
        # fail-closed decision (never a socket timeout)
        self.deadline_budget_s = deadline_budget_s
        # degradation visibility: a callable returning a status dict
        # (e.g. {"tpu_breaker": driver.breaker_status()}) surfaced on
        # /healthz (degraded marker) and /statusz (full JSON)
        self.health_status = health_status
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ssl_context: Optional[ssl.SSLContext] = None
        self._stopping = False
        # graceful drain (docs/fleet.md): a draining server answers 503 to
        # NEW admission requests (the front door/LB has already stopped
        # routing here; stragglers must fail over, not land new work) while
        # in-flight evaluation finishes under its own deadline budgets.
        # Health endpoints keep answering; /readyz reports not-ready.
        self._draining = False

    def _status_snapshot(self) -> Optional[dict]:
        if self.health_status is None:
            return None
        try:
            return self.health_status()
        except Exception:
            log.exception("health status callable failed")
            return None

    def reload_certs(self, certfile: str, keyfile: str):
        """Hot-swap the serving cert: new handshakes pick up the reloaded
        chain (cert rotation must not require a listener restart)."""
        self.certfile, self.keyfile = certfile, keyfile
        if self._ssl_context is not None:
            self._ssl_context.load_cert_chain(certfile, keyfile)

    def start(self):
        # idempotent: a double start must REPLACE the previous listener
        # and GC sweeper, not leak them — the old sweeper thread otherwise
        # outlives the server forever, and the old socket still holds the
        # port the new bind needs.  shutdown() only when serve_forever is
        # actually running: on a server whose loop never started (a prior
        # start() died mid-body) it would wait forever on the
        # __is_shut_down event that only serve_forever sets.
        if self._server is not None:
            if self._thread is not None and self._thread.is_alive():
                self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None
        if getattr(self, "_gc_stop", None) is not None:
            self._gc_stop.set()
            self._gc_stop = None
        self._stopping = False  # a stopped server may be restarted
        self._draining = False
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # keep-alive: without HTTP/1.1 every admission request pays a
            # fresh TLS handshake (the apiserver reuses connections);
            # responses always carry Content-Length below, as 1.1 requires
            protocol_version = "HTTP/1.1"
            # headers and body flush as separate TCP segments; with Nagle
            # on, the body write stalls ~40ms behind the peer's delayed ACK
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                # access logging at DEBUG only, and never for probe/scrape
                # paths (/healthz-style and the /debug/* surface): a
                # misconfigured prober polling /debug/traces must not spam
                # stderr at admission rates
                path = (getattr(self, "path", "") or "").split("?", 1)[0]
                if path in QUIET_PATHS or path.startswith(DEBUG_PREFIX):
                    return
                if log.isEnabledFor(10):  # logging.DEBUG
                    log.debug("%s - %s", self.address_string(), fmt % args)

            def _send_json(self, code: int, payload: dict):
                self._send_bytes(code, "application/json",
                                 json.dumps(payload).encode())

            def _send_text(self, code: int, text: str):
                self._send_bytes(code, "text/plain", text.encode())

            def _send_bytes(self, code: int, ctype: str, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if self.close_connection:
                    # advertise the close decided by framing/shutdown so
                    # keep-alive clients don't reuse a dying connection
                    self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # a GET may legally carry a body too
                if self._read_body() is None:
                    return
                if self._stopped():
                    return
                # healthz/readyz (reference main.go:193-196)
                if self.path == "/healthz":
                    body = "ok"
                    st = outer._status_snapshot()
                    if st and any(
                        isinstance(v, dict)
                        and v.get("state") not in (None, "closed")
                        for v in st.values()
                    ):
                        # degraded-but-serving is still healthy: the
                        # interpreter tier answers while the breaker is
                        # open, so the pod must NOT be restarted — the
                        # marker makes the state visible to probes/humans
                        body = "ok (degraded)"
                    self._send_text(200, body)
                elif self.path == "/statusz":
                    # machine-readable degradation ladder state (breaker
                    # state machine, trip counts, time degraded)
                    self._send_json(200, outer._status_snapshot() or {})
                elif self.path == "/readyz":
                    if outer._draining:
                        # draining is an orderly not-ready: LB health
                        # checks pull the backend while /healthz stays ok
                        self._send_text(503, "draining")
                        return
                    ready = (
                        outer.readiness_check() if outer.readiness_check else True
                    )
                    self._send_text(200 if ready else 500,
                                    "ok" if ready else "not ready")
                elif self.path.split("?", 1)[0].startswith(DEBUG_PREFIX):
                    self._debug_get()
                else:
                    self._send_text(404, "not found")

            def _debug_get(self):
                """Debug introspection surface, served by the shared
                DebugRouter (obs/debug.py) — the same routes (and the
                same hardened query parsing) the metrics exporter
                serves, so docs/tracing.md describes one contract:
                /debug/traces?min_ms=&limit=  recent completed traces
                /debug/stacks                 live thread-stack dump
                /debug/costs?top=             per-template cost ledger
                /debug/slo                    SLO burn-rate status"""
                from urllib.parse import urlsplit

                parts = urlsplit(self.path)
                self._send_bytes(
                    *get_router().handle(parts.path, parts.query)
                )

            # Admission payloads are small; a body this large is abuse or
            # corruption, never a legitimate AdmissionReview.
            MAX_BODY = 32 * 1024 * 1024

            def _read_body(self) -> Optional[bytes]:
                """Always consume the request body: under HTTP/1.1
                keep-alive, unread body bytes would be parsed as the NEXT
                request line, poisoning the persistent connection.

                Returns None when the body could not be framed — in that
                case an error response has already been sent and the
                caller must bail out (the Go reference's net/http parses
                chunked transparently; evaluating an unframeable body as
                b"" would be a fail-open admission decision)."""
                te = self.headers.get("Transfer-Encoding")
                if te:
                    if te.strip().lower() == "chunked":
                        return self._read_chunked()
                    self.close_connection = True
                    self._send_text(411, "Length Required")
                    return None
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    self.close_connection = True
                    self._send_text(400, "bad Content-Length")
                    return None
                if length > self.MAX_BODY:
                    self.close_connection = True
                    self._send_text(413, "body too large")
                    return None
                return self.rfile.read(length) if length > 0 else b""

            def _read_chunked(self) -> Optional[bytes]:
                """RFC 7230 §4.1 chunked decoding (net/http does this
                inside the transport; here it is explicit)."""
                chunks: list = []
                total = 0
                try:
                    while True:
                        line = self.rfile.readline(65536)
                        if not line.endswith(b"\n"):
                            raise ValueError("chunk size line overflow")
                        size = int(line.strip().split(b";", 1)[0], 16)
                        if size < 0:
                            raise ValueError("negative chunk size")
                        if size == 0:
                            # consume trailers up to the blank line,
                            # bounded like the body (an endless trailer
                            # stream must not pin the handler thread)
                            budget = 65536
                            while True:
                                trailer = self.rfile.readline(65536)
                                if trailer in (b"\r\n", b"\n", b""):
                                    break
                                budget -= len(trailer)
                                if budget < 0:
                                    raise ValueError("trailers too large")
                            return b"".join(chunks)
                        total += size
                        if total > self.MAX_BODY:
                            raise ValueError("chunked body too large")
                        data = self.rfile.read(size)
                        if len(data) != size:
                            raise ValueError("truncated chunk")
                        chunks.append(data)
                        crlf = self.rfile.read(2)
                        if crlf not in (b"\r\n",):
                            raise ValueError("missing chunk terminator")
                except (ValueError, OSError):
                    # malformed framing: the connection cannot be reused
                    # and the request must NOT be evaluated as empty
                    self.close_connection = True
                    self._send_text(400, "malformed chunked body")
                    return None

            def _stopped(self) -> bool:
                """After stop(), established keep-alive connections must
                not keep receiving admission decisions from a server the
                process considers down (HTTP/1.0 closed per response, so
                this was free before keep-alive)."""
                if outer._stopping:
                    self.close_connection = True
                    self._send_text(503, "shutting down")
                    return True
                return False

            def do_POST(self):
                body = self._read_body()
                if body is None:
                    return
                if self._stopped():
                    return
                if outer._draining:
                    # explicit refusal, never a fabricated verdict: the
                    # caller (front door / apiserver) fails over to a
                    # live replica or applies its failurePolicy
                    self.close_connection = True
                    self._send_text(503, "draining")
                    return
                if self.path not in ("/v1/admit", "/v1/admitlabel"):
                    self._send_text(404, "not found")
                    return
                try:
                    review = json.loads(body or b"{}")
                    req = review.get("request") or {}
                    if not isinstance(req, dict):
                        # {"request": "bogus"} is a malformed envelope,
                        # not an empty request — it must get the same
                        # explicit 500 AdmissionReview, and everything
                        # below (budget parse, uid extraction) assumes
                        # a dict
                        raise TypeError(
                            "AdmissionReview request must be an "
                            f"object, got {type(req).__name__}"
                        )
                except Exception as e:  # malformed envelope
                    log.exception("bad admission request")
                    resp = AdmissionResponse(False, str(e), 500)
                    self._send_json(
                        200,
                        {
                            "apiVersion": "admission.k8s.io/v1beta1",
                            "kind": "AdmissionReview",
                            "response": resp.to_dict(uid=""),
                        },
                    )
                    return
                # end-to-end deadline (ISSUE 12): the budget is min()
                # over every bound the request carries — the configured
                # --admission-deadline-budget-ms, the AdmissionReview's
                # own request.timeoutSeconds (the webhook config's
                # timeout, when the caller stamps it — opportunistic,
                # never required), and the REMAINING wire budget a
                # fleet front door forwarded in X-GK-Deadline-Ms.  A replica behind the door re-enters
                # the budget with what is left of the caller's patience,
                # never a fresh allowance; an already-expired budget is
                # refused at the first downstream stage (batcher
                # enqueue), surfacing the explicit fail-open/closed
                # decision within microseconds.
                budget = _deadline.effective_budget_s(
                    outer.deadline_budget_s,
                    _deadline.parse_timeout_seconds(req),
                    _deadline.parse_header_ms(
                        self.headers.get(_deadline.DEADLINE_HEADER)
                    ),
                )
                token = None
                if budget is not None:
                    token = _deadline.push(budget)
                try:
                    # W3C trace context: adopt the apiserver's trace id so
                    # the deny log line and /debug/traces entry correlate
                    # with the upstream request
                    with obstrace.root_span(
                        "admission",
                        traceparent=self.headers.get("traceparent"),
                        path=self.path,
                        uid=str(req.get("uid", "")),
                    ) as rsp:
                        if self.path == "/v1/admit":
                            resp = outer.validation_handler.handle(req)
                        else:
                            resp = outer.label_handler.handle(req)
                        rsp.set_attrs(allowed=resp.allowed, code=resp.code)
                except Exception as e:  # handler defect
                    log.exception("bad admission request")
                    resp = AdmissionResponse(False, str(e), 500)
                finally:
                    if token is not None:
                        _deadline.pop(token)
                self._send_json(
                    200,
                    {
                        "apiVersion": "admission.k8s.io/v1beta1",
                        "kind": "AdmissionReview",
                        "response": resp.to_dict(uid=req.get("uid", "")),
                    },
                )

        self._server = ThreadingHTTPServer(("0.0.0.0", self.port), Handler)
        self.port = self._server.server_address[1]
        if self.certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.certfile, self.keyfile)
            self._ssl_context = ctx
            self._server.socket = ctx.wrap_socket(
                self._server.socket, server_side=True
            )
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="webhook", daemon=True
        )
        self._thread.start()
        # p99 tactic: move everything allocated so far (compiled policies,
        # packed tensors, module graph) out of the cyclic GC's generations —
        # a gen-2 collection scanning a 100k-object inventory otherwise
        # injects multi-ms pauses into the admission path — then take the
        # collector OFF the admission path entirely: automatic collections
        # triggered mid-request inject ms-scale pauses exactly at p99.
        # Refcounting still frees the (acyclic) request traffic; a
        # background sweeper collects the rare cycles every few seconds.
        import gc

        gc.collect()
        gc.freeze()
        gc.disable()
        stop_evt = threading.Event()
        self._gc_stop = stop_evt

        def _sweep():
            # closes over the Event only: capturing self would pin a
            # dropped server forever and re-reading self._gc_stop races
            # stop()'s None reset
            while not stop_evt.wait(5.0):
                gc.collect()

        threading.Thread(target=_sweep, name="webhook-gc", daemon=True).start()

    def drain(self, draining: bool = True):
        """Enter (or leave) draining: new admission POSTs answer 503 and
        /readyz reports not-ready, while /healthz and the debug surface
        keep serving.  The supervisor's graceful-drain sequence is
        eject-from-front-door -> server.drain() -> batcher.drain(budget)
        -> stop() (docs/fleet.md)."""
        self._draining = bool(draining)

    def stop(self):
        if getattr(self, "_gc_stop", None) is not None:
            self._gc_stop.set()
            self._gc_stop = None
            import gc

            gc.enable()
            # unfreeze too: repeated start/stop cycles (tests, embedders)
            # would otherwise grow the permanent generation monotonically
            # and any cycles frozen on a later start() would leak forever
            gc.unfreeze()
        # established keep-alive connections keep their handler threads
        # alive past shutdown(); the flag makes them 503 + close instead
        # of serving admission decisions from a stopped server
        self._stopping = True
        if self._server:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
