"""Webhook HTTPS front end with TPU micro-batching.

The reference serves /v1/admit and /v1/admitlabel from controller-runtime's
webhook server (pkg/webhook/webhook.go:36-43, main.go:145).  Here the server
is a threaded HTTP(S) listener whose admission path goes through a
`MicroBatcher`: concurrent requests inside a short window coalesce into ONE
batched device dispatch (TpuDriver.review_batch), which is how p99 stays low
while the TPU runs at batch efficiency (SURVEY.md §7 stage 5).
"""

from __future__ import annotations

import json
import ssl
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from .. import deadline as _deadline
from .. import faults
from .. import logging as gklog
from ..metrics.catalog import (
    WEBHOOK_QUEUE_M,
    record_batch_size,
    record_stage,
)
from ..obs import trace as obstrace
from ..obs.debug import get_router
from .namespacelabel import NamespaceLabelHandler
from .policy import AdmissionResponse, ValidationHandler

log = gklog.get("webhook.server")

# paths that never produce an access log line (scrape/probe traffic —
# the /metrics convention extended to the debug surface)
QUIET_PATHS = ("/healthz", "/readyz", "/statusz", "/metrics")
DEBUG_PREFIX = "/debug/"


class BatcherStopped(RuntimeError):
    """Raised to requests enqueued on (or pending across) a stopped
    MicroBatcher — they must fail fast, not wait on an event forever."""


class _Pending:
    __slots__ = (
        "obj", "event", "result", "error", "deadline", "span", "queue_span",
    )

    def __init__(self, obj, deadline: Optional[float] = None):
        self.obj = obj
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None
        self.deadline = deadline  # absolute monotonic, or None
        # explicit cross-thread context passing: the request's active span
        # (linked by the batch span) and its open queue-wait span (ended
        # by the batch thread when the batch is drained)
        self.span = obstrace.current_span()
        self.queue_span = (
            obstrace.detached_span(
                "webhook.queue_wait", parent=self.span,
                stage=obstrace.QUEUE_WAIT,
            )
            if self.span is not None else None
        )


class MicroBatcher:
    """Client-compatible wrapper that coalesces concurrent review() calls.

    Continuous batching: when the system is idle, a request dispatches
    immediately (zero added latency — the sparse-traffic p99 must not pay
    the window).  During a burst — detected as arrivals landing hot on the
    heels of the previous dispatch — the thread holds the window open for
    up to `window_s` so concurrent arrivals share one review_batch; and
    while a batch is evaluating, new arrivals accumulate naturally behind
    it, which is the real batching mechanism under sustained load.
    """

    def __init__(self, client, window_s: float = 0.002, max_batch: int = 256):
        self._client = client
        self.window_s = window_s
        self.max_batch = max_batch
        self._pending: List[_Pending] = []
        self._cv = threading.Condition()
        self._inline = threading.Lock()  # at most one idle fast-path eval
        self._busy = False  # a batch is evaluating (pending already drained)
        self._stop = False
        self._thread = threading.Thread(
            target=self._run, name="microbatcher", daemon=True
        )
        self._thread.start()

    # anything that isn't review() passes straight through to the client
    def __getattr__(self, name):
        return getattr(self._client, name)

    def review(self, obj, tracing: bool = False):
        if faults.ENABLED:
            faults.fire(faults.WEBHOOK_ENQUEUE)
        if tracing:
            # traced requests are rare and want their own trace output;
            # bypass the batch
            return self._client.review(obj, tracing=True)
        dl = _deadline.current()
        if dl is not None and time.monotonic() > dl:
            # refuse to enqueue work that can no longer finish in budget
            raise _deadline.DeadlineExceeded(
                "admission deadline budget exhausted before evaluation"
            )
        # idle fast path: with nothing else in flight, evaluate on the
        # caller's thread — two scheduler handoffs per request otherwise
        # put milliseconds of wakeup jitter into the sparse-traffic p99.
        # The lock bounds inline evaluation to one caller; arrivals during
        # an in-flight batch (_busy) queue instead, so they join the next
        # coalesced dispatch rather than blocking solo on the driver lock.
        # Deadline-carrying requests always queue: an inline evaluation on
        # the caller's thread cannot be interrupted, so a wedged backend
        # would hold the request past any budget — the queued path's
        # event wait is what bounds time-to-answer (docs/failure-modes.md).
        if (
            dl is None
            and not self._stop  # stopped batcher: fall through and reject
            and not self._pending
            and not self._busy
            and self._inline.acquire(blocking=False)
        ):
            try:
                if not self._pending and not self._busy and not self._stop:
                    return self._client.review(obj)
            finally:
                self._inline.release()
        p = _Pending(obj, deadline=dl)
        with self._cv:
            if self._stop:
                # enqueues after stop() must fail fast, never wait on an
                # event no batch loop will ever set
                raise BatcherStopped("webhook batcher is stopped")
            self._pending.append(p)
            self._cv.notify()
        if dl is None:
            p.event.wait()
        elif not p.event.wait(timeout=max(0.0, dl - time.monotonic())):
            raise _deadline.DeadlineExceeded(
                "admission deadline budget exhausted"
            )
        if p.error is not None:
            raise p.error
        return p.result

    def _run(self):
        import time as _time

        last_batch_size = 0
        last_dispatch_end = 0.0
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait(timeout=0.1)
                if self._stop and not self._pending:
                    return
                # open the accumulation window only under observed, RECENT
                # concurrency (several already waiting, or the previous
                # batch coalesced moments ago) — a sequential client
                # issuing one request at a time must never pay the window,
                # or the sparse-traffic p99 absorbs it wholesale; and a
                # burst minutes ago must not tax today's lone request
                recent = (
                    _time.monotonic() - last_dispatch_end < 5 * self.window_s
                )
                concurrent = len(self._pending) > 1 or (
                    last_batch_size > 1 and recent
                )
                if concurrent and len(self._pending) < self.max_batch:
                    self._cv.wait(timeout=self.window_s)
                batch = self._pending[: self.max_batch]
                self._pending = self._pending[self.max_batch:]
                last_batch_size = len(batch)
                self._busy = True
            # the batch is drained: queue-wait ends here for every member
            # (deadline-refused ones included — their wait was real)
            for p in batch:
                if p.queue_span is not None:
                    p.queue_span.end()
                    record_stage(
                        WEBHOOK_QUEUE_M,
                        p.queue_span.stop - p.queue_span.start,
                    )
            # refuse past-deadline work before paying a dispatch for it:
            # the waiter has already (or will imminently) time out, and
            # evaluating its review is pure wasted device time
            now = _time.monotonic()
            live = []
            for p in batch:
                if p.deadline is not None and now > p.deadline:
                    p.error = _deadline.DeadlineExceeded(
                        "admission deadline budget exhausted in queue"
                    )
                    p.event.set()
                else:
                    live.append(p)
            batch = live
            # one batch span serving N request spans: linked to each, and
            # every span of the batch trace (this one + the driver's stage
            # spans) mirrors into each request trace, so request traces
            # stay self-contained (obs/trace.py batch_span)
            bsp = None
            btoken = None
            if batch:
                record_batch_size(len(batch))
                req_spans = [p.span for p in batch if p.span is not None]
                if req_spans:  # un-traced batches skip span work entirely
                    bsp = obstrace.batch_span(
                        "webhook.batch", req_spans, batch_size=len(batch),
                    )
                    btoken = obstrace.CURRENT.set(bsp)
            try:
                if batch:
                    responses = self._client.review_batch(
                        [p.obj for p in batch]
                    )
                    if bsp is not None:
                        obstrace.CURRENT.reset(btoken)
                        btoken = None
                        bsp.end()
                        bsp = None
                    for p, resp in zip(batch, responses):
                        p.result = resp
                        p.event.set()
            except Exception:
                # batched failure: fall back to per-request evaluation so one
                # poisoned review can't fail the whole window — but check
                # each request's remaining budget first; a request whose
                # deadline lapsed during the failed dispatch gets an
                # explicit deadline error, not another evaluation.
                # The batch span ends FIRST: fallback evaluations run under
                # each request's OWN span, not the batch span — otherwise
                # every fallback's stage spans would mirror into all N
                # request traces (and keep appending after their waiters
                # were released)
                if bsp is not None:
                    obstrace.CURRENT.reset(btoken)
                    btoken = None
                    bsp.end()
                    bsp = None
                for p in batch:
                    if (
                        p.deadline is not None
                        and _time.monotonic() > p.deadline
                    ):
                        p.error = _deadline.DeadlineExceeded(
                            "admission deadline budget exhausted during "
                            "per-request fallback"
                        )
                        p.event.set()
                        continue
                    try:
                        if p.span is not None:
                            with obstrace.use_span(p.span):
                                p.result = self._client.review(p.obj)
                        else:
                            p.result = self._client.review(p.obj)
                    except Exception as e:
                        p.error = e
                    p.event.set()
            finally:
                if btoken is not None:
                    obstrace.CURRENT.reset(btoken)
                if bsp is not None:
                    bsp.end()  # idempotent on the success path
                self._busy = False
                last_dispatch_end = _time.monotonic()

    def stop(self):
        # drain under the cv lock: a request appended concurrently either
        # lands before the drain (gets BatcherStopped here) or after _stop
        # is set (review() rejects it) — no pending can be left waiting on
        # an event forever (the shutdown race this replaces)
        with self._cv:
            self._stop = True
            drained, self._pending = self._pending, []
            for p in drained:
                p.error = BatcherStopped(
                    "webhook batcher stopped before evaluation"
                )
                p.event.set()
            self._cv.notify_all()
        self._thread.join(timeout=2.0)


class WebhookServer:
    """HTTP(S) listener for /v1/admit + /v1/admitlabel + health endpoints."""

    def __init__(
        self,
        validation_handler: ValidationHandler,
        label_handler: Optional[NamespaceLabelHandler] = None,
        port: int = 8443,
        certfile: Optional[str] = None,
        keyfile: Optional[str] = None,
        readiness_check=None,  # callable -> bool (tracker.satisfied)
        deadline_budget_s: Optional[float] = None,
        health_status: Optional[Callable[[], dict]] = None,
    ):
        self.validation_handler = validation_handler
        self.label_handler = label_handler or NamespaceLabelHandler()
        self.port = port
        self.certfile = certfile
        self.keyfile = keyfile
        self.readiness_check = readiness_check
        # per-request deadline budget: every admission request entering
        # this server carries monotonic_now + budget as its deadline; the
        # batching client and driver fallbacks refuse work past it, and
        # the handler converts exhaustion into an explicit fail-open or
        # fail-closed decision (never a socket timeout)
        self.deadline_budget_s = deadline_budget_s
        # degradation visibility: a callable returning a status dict
        # (e.g. {"tpu_breaker": driver.breaker_status()}) surfaced on
        # /healthz (degraded marker) and /statusz (full JSON)
        self.health_status = health_status
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ssl_context: Optional[ssl.SSLContext] = None
        self._stopping = False

    def _status_snapshot(self) -> Optional[dict]:
        if self.health_status is None:
            return None
        try:
            return self.health_status()
        except Exception:
            log.exception("health status callable failed")
            return None

    def reload_certs(self, certfile: str, keyfile: str):
        """Hot-swap the serving cert: new handshakes pick up the reloaded
        chain (cert rotation must not require a listener restart)."""
        self.certfile, self.keyfile = certfile, keyfile
        if self._ssl_context is not None:
            self._ssl_context.load_cert_chain(certfile, keyfile)

    def start(self):
        # idempotent: a double start must REPLACE the previous listener
        # and GC sweeper, not leak them — the old sweeper thread otherwise
        # outlives the server forever, and the old socket still holds the
        # port the new bind needs.  shutdown() only when serve_forever is
        # actually running: on a server whose loop never started (a prior
        # start() died mid-body) it would wait forever on the
        # __is_shut_down event that only serve_forever sets.
        if self._server is not None:
            if self._thread is not None and self._thread.is_alive():
                self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None
        if getattr(self, "_gc_stop", None) is not None:
            self._gc_stop.set()
            self._gc_stop = None
        self._stopping = False  # a stopped server may be restarted
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # keep-alive: without HTTP/1.1 every admission request pays a
            # fresh TLS handshake (the apiserver reuses connections);
            # responses always carry Content-Length below, as 1.1 requires
            protocol_version = "HTTP/1.1"
            # headers and body flush as separate TCP segments; with Nagle
            # on, the body write stalls ~40ms behind the peer's delayed ACK
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):
                # access logging at DEBUG only, and never for probe/scrape
                # paths (/healthz-style and the /debug/* surface): a
                # misconfigured prober polling /debug/traces must not spam
                # stderr at admission rates
                path = (getattr(self, "path", "") or "").split("?", 1)[0]
                if path in QUIET_PATHS or path.startswith(DEBUG_PREFIX):
                    return
                if log.isEnabledFor(10):  # logging.DEBUG
                    log.debug("%s - %s", self.address_string(), fmt % args)

            def _send_json(self, code: int, payload: dict):
                self._send_bytes(code, "application/json",
                                 json.dumps(payload).encode())

            def _send_text(self, code: int, text: str):
                self._send_bytes(code, "text/plain", text.encode())

            def _send_bytes(self, code: int, ctype: str, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if self.close_connection:
                    # advertise the close decided by framing/shutdown so
                    # keep-alive clients don't reuse a dying connection
                    self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # a GET may legally carry a body too
                if self._read_body() is None:
                    return
                if self._stopped():
                    return
                # healthz/readyz (reference main.go:193-196)
                if self.path == "/healthz":
                    body = "ok"
                    st = outer._status_snapshot()
                    if st and any(
                        isinstance(v, dict)
                        and v.get("state") not in (None, "closed")
                        for v in st.values()
                    ):
                        # degraded-but-serving is still healthy: the
                        # interpreter tier answers while the breaker is
                        # open, so the pod must NOT be restarted — the
                        # marker makes the state visible to probes/humans
                        body = "ok (degraded)"
                    self._send_text(200, body)
                elif self.path == "/statusz":
                    # machine-readable degradation ladder state (breaker
                    # state machine, trip counts, time degraded)
                    self._send_json(200, outer._status_snapshot() or {})
                elif self.path == "/readyz":
                    ready = (
                        outer.readiness_check() if outer.readiness_check else True
                    )
                    self._send_text(200 if ready else 500,
                                    "ok" if ready else "not ready")
                elif self.path.split("?", 1)[0].startswith(DEBUG_PREFIX):
                    self._debug_get()
                else:
                    self._send_text(404, "not found")

            def _debug_get(self):
                """Debug introspection surface, served by the shared
                DebugRouter (obs/debug.py) — the same routes (and the
                same hardened query parsing) the metrics exporter
                serves, so docs/tracing.md describes one contract:
                /debug/traces?min_ms=&limit=  recent completed traces
                /debug/stacks                 live thread-stack dump
                /debug/costs?top=             per-template cost ledger
                /debug/slo                    SLO burn-rate status"""
                from urllib.parse import urlsplit

                parts = urlsplit(self.path)
                self._send_bytes(
                    *get_router().handle(parts.path, parts.query)
                )

            # Admission payloads are small; a body this large is abuse or
            # corruption, never a legitimate AdmissionReview.
            MAX_BODY = 32 * 1024 * 1024

            def _read_body(self) -> Optional[bytes]:
                """Always consume the request body: under HTTP/1.1
                keep-alive, unread body bytes would be parsed as the NEXT
                request line, poisoning the persistent connection.

                Returns None when the body could not be framed — in that
                case an error response has already been sent and the
                caller must bail out (the Go reference's net/http parses
                chunked transparently; evaluating an unframeable body as
                b"" would be a fail-open admission decision)."""
                te = self.headers.get("Transfer-Encoding")
                if te:
                    if te.strip().lower() == "chunked":
                        return self._read_chunked()
                    self.close_connection = True
                    self._send_text(411, "Length Required")
                    return None
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except (TypeError, ValueError):
                    self.close_connection = True
                    self._send_text(400, "bad Content-Length")
                    return None
                if length > self.MAX_BODY:
                    self.close_connection = True
                    self._send_text(413, "body too large")
                    return None
                return self.rfile.read(length) if length > 0 else b""

            def _read_chunked(self) -> Optional[bytes]:
                """RFC 7230 §4.1 chunked decoding (net/http does this
                inside the transport; here it is explicit)."""
                chunks: list = []
                total = 0
                try:
                    while True:
                        line = self.rfile.readline(65536)
                        if not line.endswith(b"\n"):
                            raise ValueError("chunk size line overflow")
                        size = int(line.strip().split(b";", 1)[0], 16)
                        if size < 0:
                            raise ValueError("negative chunk size")
                        if size == 0:
                            # consume trailers up to the blank line,
                            # bounded like the body (an endless trailer
                            # stream must not pin the handler thread)
                            budget = 65536
                            while True:
                                trailer = self.rfile.readline(65536)
                                if trailer in (b"\r\n", b"\n", b""):
                                    break
                                budget -= len(trailer)
                                if budget < 0:
                                    raise ValueError("trailers too large")
                            return b"".join(chunks)
                        total += size
                        if total > self.MAX_BODY:
                            raise ValueError("chunked body too large")
                        data = self.rfile.read(size)
                        if len(data) != size:
                            raise ValueError("truncated chunk")
                        chunks.append(data)
                        crlf = self.rfile.read(2)
                        if crlf not in (b"\r\n",):
                            raise ValueError("missing chunk terminator")
                except (ValueError, OSError):
                    # malformed framing: the connection cannot be reused
                    # and the request must NOT be evaluated as empty
                    self.close_connection = True
                    self._send_text(400, "malformed chunked body")
                    return None

            def _stopped(self) -> bool:
                """After stop(), established keep-alive connections must
                not keep receiving admission decisions from a server the
                process considers down (HTTP/1.0 closed per response, so
                this was free before keep-alive)."""
                if outer._stopping:
                    self.close_connection = True
                    self._send_text(503, "shutting down")
                    return True
                return False

            def do_POST(self):
                body = self._read_body()
                if body is None:
                    return
                if self._stopped():
                    return
                if self.path not in ("/v1/admit", "/v1/admitlabel"):
                    self._send_text(404, "not found")
                    return
                token = None
                if outer.deadline_budget_s:
                    token = _deadline.push(outer.deadline_budget_s)
                try:
                    review = json.loads(body or b"{}")
                    req = review.get("request") or {}
                    # W3C trace context: adopt the apiserver's trace id so
                    # the deny log line and /debug/traces entry correlate
                    # with the upstream request
                    with obstrace.root_span(
                        "admission",
                        traceparent=self.headers.get("traceparent"),
                        path=self.path,
                        uid=str(req.get("uid", "")),
                    ) as rsp:
                        if self.path == "/v1/admit":
                            resp = outer.validation_handler.handle(req)
                        else:
                            resp = outer.label_handler.handle(req)
                        rsp.set_attrs(allowed=resp.allowed, code=resp.code)
                except Exception as e:  # malformed envelope
                    log.exception("bad admission request")
                    resp = AdmissionResponse(False, str(e), 500)
                    req = {}
                finally:
                    if token is not None:
                        _deadline.pop(token)
                self._send_json(
                    200,
                    {
                        "apiVersion": "admission.k8s.io/v1beta1",
                        "kind": "AdmissionReview",
                        "response": resp.to_dict(uid=req.get("uid", "")),
                    },
                )

        self._server = ThreadingHTTPServer(("0.0.0.0", self.port), Handler)
        self.port = self._server.server_address[1]
        if self.certfile:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.certfile, self.keyfile)
            self._ssl_context = ctx
            self._server.socket = ctx.wrap_socket(
                self._server.socket, server_side=True
            )
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="webhook", daemon=True
        )
        self._thread.start()
        # p99 tactic: move everything allocated so far (compiled policies,
        # packed tensors, module graph) out of the cyclic GC's generations —
        # a gen-2 collection scanning a 100k-object inventory otherwise
        # injects multi-ms pauses into the admission path — then take the
        # collector OFF the admission path entirely: automatic collections
        # triggered mid-request inject ms-scale pauses exactly at p99.
        # Refcounting still frees the (acyclic) request traffic; a
        # background sweeper collects the rare cycles every few seconds.
        import gc

        gc.collect()
        gc.freeze()
        gc.disable()
        stop_evt = threading.Event()
        self._gc_stop = stop_evt

        def _sweep():
            # closes over the Event only: capturing self would pin a
            # dropped server forever and re-reading self._gc_stop races
            # stop()'s None reset
            while not stop_evt.wait(5.0):
                gc.collect()

        threading.Thread(target=_sweep, name="webhook-gc", daemon=True).start()

    def stop(self):
        if getattr(self, "_gc_stop", None) is not None:
            self._gc_stop.set()
            self._gc_stop = None
            import gc

            gc.enable()
            # unfreeze too: repeated start/stop cycles (tests, embedders)
            # would otherwise grow the permanent generation monotonically
            # and any cycles frozen on a later start() would leak forever
            gc.unfreeze()
        # established keep-alive connections keep their handler threads
        # alive past shutdown(); the flag makes them 503 + close instead
        # of serving admission decisions from a stopped server
        self._stopping = True
        if self._server:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
