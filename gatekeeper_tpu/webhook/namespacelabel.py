"""/v1/admitlabel — the namespace ignore-label guard (reference
pkg/webhook/namespacelabel.go:27-29,69-95).

Only namespaces on the exempt list may carry the
admission.gatekeeper.sh/ignore label; everything else that sets it is
denied.  This webhook is registered failurePolicy=Fail (unlike the policy
webhook, which fails open) because it protects the bypass mechanism itself.
"""

from __future__ import annotations

from typing import Iterable, Optional, Set

from .policy import AdmissionResponse, _allowed, _denied

IGNORE_LABEL = "admission.gatekeeper.sh/ignore"


class NamespaceLabelHandler:
    def __init__(self, exempt_namespaces: Optional[Iterable[str]] = None):
        self.exempt: Set[str] = set(exempt_namespaces or ())

    def add_exempt(self, namespace: str):
        self.exempt.add(namespace)

    def handle(self, req: dict) -> AdmissionResponse:
        if req.get("operation") == "DELETE":
            return _allowed("Delete is always allowed")
        kind = req.get("kind") or {}
        if kind.get("group", "") != "" or kind.get("kind") != "Namespace":
            return _allowed("Not a namespace")
        obj = req.get("object")
        if not isinstance(obj, dict):
            return _denied("while deserializing resource", 500)
        name = (obj.get("metadata") or {}).get("name", "")
        if name in self.exempt:
            return _allowed(
                f"Namespace {name} is allowed to set {IGNORE_LABEL}"
            )
        labels = (obj.get("metadata") or {}).get("labels") or {}
        for label in labels:
            if label == IGNORE_LABEL:
                return AdmissionResponse(
                    False,
                    f"Only exempt namespace can have the {IGNORE_LABEL} label",
                    403,
                )
        return _allowed(f"Namespace is not setting the {IGNORE_LABEL} label")
