"""Admission webhook serving layer (reference pkg/webhook/).

`policy` re-provides the validation handler semantics of policy.go;
`namespacelabel` guards the admission.gatekeeper.sh/ignore label;
`server` is the HTTPS front end with TPU micro-batching.
"""

from .policy import (
    AdmissionResponse,
    ValidationHandler,
    SERVICE_ACCOUNT_NAME,
)
from .namespacelabel import IGNORE_LABEL, NamespaceLabelHandler
from .server import BatcherStopped, MicroBatcher, WebhookServer

__all__ = [
    "AdmissionResponse",
    "BatcherStopped",
    "IGNORE_LABEL",
    "MicroBatcher",
    "NamespaceLabelHandler",
    "SERVICE_ACCOUNT_NAME",
    "ValidationHandler",
    "WebhookServer",
]
