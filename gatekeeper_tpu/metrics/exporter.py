"""Prometheus text exposition + standalone metrics HTTP server.

The reference exports views through a Prometheus exporter serving on its
own HTTP listener at --prometheus-port 8888 (pkg/metrics/exporter.go:14-15,
prometheus_exporter.go).  Same here: render the registry in the Prometheus
text format and serve it from a background thread.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .views import (
    AGG_COUNT,
    AGG_DISTRIBUTION,
    AGG_LAST_VALUE,
    AGG_SUM,
    DistributionData,
    Registry,
    global_registry,
)

NAMESPACE = "gatekeeper"  # metric name prefix, as the reference's exporter


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(keys, values) -> str:
    pairs = [f'{k}="{_escape(v)}"' for k, v in zip(keys, values) if v != ""]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(registry: Optional[Registry] = None) -> str:
    registry = registry or global_registry()
    lines = []
    for view, rows in sorted(registry.snapshot(), key=lambda s: s[0].name):
        full = f"{NAMESPACE}_{view.name}"
        kind = {
            AGG_COUNT: "counter",
            AGG_SUM: "counter",
            AGG_LAST_VALUE: "gauge",
            AGG_DISTRIBUTION: "histogram",
        }[view.aggregation]
        lines.append(f"# HELP {full} {view.description}")
        lines.append(f"# TYPE {full} {kind}")
        for tag_values in sorted(rows):
            val = rows[tag_values]
            label_str = _labels(view.tag_keys, tag_values)
            if isinstance(val, DistributionData):
                cumulative = 0
                for bound, n in zip(view.buckets, val.bucket_counts):
                    cumulative += n
                    le = _labels(
                        view.tag_keys + ("le",),
                        tag_values + (_fmt(bound),),
                    )
                    lines.append(f"{full}_bucket{le} {cumulative}")
                le = _labels(view.tag_keys + ("le",), tag_values + ("+Inf",))
                lines.append(f"{full}_bucket{le} {val.count}")
                lines.append(f"{full}_sum{label_str} {_fmt(val.sum)}")
                lines.append(f"{full}_count{label_str} {val.count}")
            else:
                lines.append(f"{full}{label_str} {_fmt(float(val))}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    registry: Registry = None

    def do_GET(self):
        if self.path not in ("/metrics", "/"):
            self.send_response(404)
            self.end_headers()
            return
        body = render_prometheus(self.registry).encode()
        self.send_response(200)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet
        pass


class MetricsExporter:
    """Background Prometheus endpoint (reference runner in exporter.go:40-57)."""

    def __init__(
        self,
        port: int = 8888,
        registry: Optional[Registry] = None,
        host: str = "0.0.0.0",
    ):
        self.port = port
        self.host = host
        self.registry = registry or global_registry()
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        handler = type("Handler", (_Handler,), {"registry": self.registry})
        self._server = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="metrics", daemon=True
        )
        self._thread.start()

    def stop(self):
        if self._server:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
