"""Prometheus/OpenMetrics exposition + standalone metrics HTTP server.

The reference exports views through a Prometheus exporter serving on its
own HTTP listener at --prometheus-port 8888 (pkg/metrics/exporter.go:14-15,
prometheus_exporter.go).  Same here, with two ISSUE 5 extensions:

- **Content negotiation.**  An ``Accept`` header containing
  ``application/openmetrics-text`` selects the OpenMetrics rendering:
  counter families drop/regain the ``_total`` sample suffix per the spec,
  histogram bucket lines carry trace exemplars
  (``# {trace_id="..."} value ts`` — the link from a hot bucket to its
  /debug/traces entry), and the body terminates with ``# EOF``.  The
  classic text format (the default) is byte-identical to what it always
  was: no exemplars, no terminator.
- **Debug surface.**  ``/debug/*`` routes through the shared DebugRouter
  (obs/debug.py), so audit-only deployments — which run no webhook
  listener — still serve /debug/traces, /debug/costs and /debug/slo.

``collect_hooks`` run before each scrape renders (guarded): the cost
ledger and SLO engine refresh their gauges there, so scraped values are
current without any background refresher thread.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from .views import (
    AGG_COUNT,
    AGG_DISTRIBUTION,
    AGG_LAST_VALUE,
    AGG_SUM,
    DistributionData,
    Registry,
    global_registry,
)

NAMESPACE = "gatekeeper"  # metric name prefix, as the reference's exporter

CONTENT_TYPE_TEXT = "text/plain; version=0.0.4; charset=utf-8"
CONTENT_TYPE_OPENMETRICS = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(keys, values) -> str:
    pairs = [f'{k}="{_escape(v)}"' for k, v in zip(keys, values) if v != ""]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _kind(aggregation: str) -> str:
    return {
        AGG_COUNT: "counter",
        AGG_SUM: "counter",
        AGG_LAST_VALUE: "gauge",
        AGG_DISTRIBUTION: "histogram",
    }[aggregation]


def render_prometheus(registry: Optional[Registry] = None) -> str:
    registry = registry or global_registry()
    lines = []
    for view, rows in sorted(registry.snapshot(), key=lambda s: s[0].name):
        full = f"{NAMESPACE}_{view.name}"
        kind = _kind(view.aggregation)
        lines.append(f"# HELP {full} {view.description}")
        lines.append(f"# TYPE {full} {kind}")
        for tag_values in sorted(rows):
            val = rows[tag_values]
            label_str = _labels(view.tag_keys, tag_values)
            if isinstance(val, DistributionData):
                cumulative = 0
                for bound, n in zip(view.buckets, val.bucket_counts):
                    cumulative += n
                    le = _labels(
                        view.tag_keys + ("le",),
                        tag_values + (_fmt(bound),),
                    )
                    lines.append(f"{full}_bucket{le} {cumulative}")
                le = _labels(view.tag_keys + ("le",), tag_values + ("+Inf",))
                lines.append(f"{full}_bucket{le} {val.count}")
                lines.append(f"{full}_sum{label_str} {_fmt(val.sum)}")
                lines.append(f"{full}_count{label_str} {val.count}")
            else:
                lines.append(f"{full}{label_str} {_fmt(float(val))}")
    return "\n".join(lines) + "\n"


def _exemplar_suffix(ex) -> str:
    """OpenMetrics exemplar: `` # {labels} value timestamp``."""
    return (
        f' # {{trace_id="{_escape(ex.trace_id)}"}} '
        f"{_fmt(ex.value)} {ex.ts:.3f}"
    )


def render_openmetrics(registry: Optional[Registry] = None) -> str:
    """OpenMetrics 1.0 text rendering: counter families named without the
    ``_total`` suffix (samples carry it), per-bucket exemplars on
    histograms, ``# EOF`` terminator."""
    registry = registry or global_registry()
    lines = []
    for view, rows in sorted(registry.snapshot(), key=lambda s: s[0].name):
        kind = _kind(view.aggregation)
        family = f"{NAMESPACE}_{view.name}"
        if kind == "counter" and family.endswith("_total"):
            family = family[: -len("_total")]
        lines.append(f"# HELP {family} {view.description}")
        lines.append(f"# TYPE {family} {kind}")
        for tag_values in sorted(rows):
            val = rows[tag_values]
            label_str = _labels(view.tag_keys, tag_values)
            if isinstance(val, DistributionData):
                cumulative = 0
                for i, (bound, n) in enumerate(
                    zip(view.buckets, val.bucket_counts)
                ):
                    cumulative += n
                    le = _labels(
                        view.tag_keys + ("le",),
                        tag_values + (_fmt(bound),),
                    )
                    ex = val.exemplars.get(i)
                    suffix = _exemplar_suffix(ex) if ex else ""
                    lines.append(
                        f"{family}_bucket{le} {cumulative}{suffix}"
                    )
                le = _labels(view.tag_keys + ("le",), tag_values + ("+Inf",))
                ex = val.exemplars.get(len(view.buckets))
                suffix = _exemplar_suffix(ex) if ex else ""
                lines.append(f"{family}_bucket{le} {val.count}{suffix}")
                lines.append(f"{family}_sum{label_str} {_fmt(val.sum)}")
                lines.append(f"{family}_count{label_str} {val.count}")
            elif kind == "counter":
                lines.append(
                    f"{family}_total{label_str} {_fmt(float(val))}"
                )
            else:
                lines.append(f"{family}{label_str} {_fmt(float(val))}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def negotiate(accept_header: Optional[str]):
    """-> (render_fn, content_type) from an Accept header value."""
    if accept_header and "application/openmetrics-text" in accept_header:
        return render_openmetrics, CONTENT_TYPE_OPENMETRICS
    return render_prometheus, CONTENT_TYPE_TEXT


class _Handler(BaseHTTPRequestHandler):
    registry: Registry = None
    collect_hooks: List[Callable[[Registry], None]] = ()

    def _send(self, code: int, ctype: str, body: bytes):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        path, _, query = self.path.partition("?")
        if path.startswith("/debug/"):
            from ..obs.debug import get_router

            self._send(*get_router().handle(path, query))
            return
        if path not in ("/metrics", "/"):
            self._send(404, "text/plain", b"not found")
            return
        for hook in self.collect_hooks:
            try:
                hook(self.registry)
            except Exception:  # a hook defect must never break the scrape
                from .catalog import record_dropped

                record_dropped(
                    "collect_hook:"
                    + getattr(hook, "__name__", repr(hook))
                )
        render, ctype = negotiate(self.headers.get("Accept"))
        self._send(200, ctype, render(self.registry).encode())

    def log_message(self, *args):  # quiet
        pass


class MetricsExporter:
    """Background Prometheus endpoint (reference runner in exporter.go:40-57)."""

    def __init__(
        self,
        port: int = 8888,
        registry: Optional[Registry] = None,
        host: str = "0.0.0.0",
        collect_hooks: Optional[List[Callable[[Registry], None]]] = None,
    ):
        self.port = port
        self.host = host
        self.registry = registry or global_registry()
        self.collect_hooks = list(collect_hooks or ())
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def add_collect_hook(self, hook: Callable[[Registry], None]):
        self.collect_hooks.append(hook)

    def start(self):
        # idempotent: a double start must REPLACE the previous listener,
        # not leak it — the old socket otherwise still holds the port the
        # new bind needs (parity with WebhookServer.start()); shutdown()
        # only when serve_forever is actually running
        if self._server is not None:
            if self._thread is not None and self._thread.is_alive():
                self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None
        handler = type(
            "Handler", (_Handler,),
            {"registry": self.registry, "collect_hooks": self.collect_hooks},
        )
        try:
            self._server = ThreadingHTTPServer((self.host, self.port), handler)
        except OSError as e:
            # port-in-use (or bad bind address) must surface as a clear,
            # actionable startup error, not a bare traceback — the
            # operator's fix is a flag change, not a code change
            raise RuntimeError(
                f"metrics exporter cannot bind {self.host}:{self.port}: {e} "
                "(is another process — or a previous exporter — holding "
                "--prometheus-port?)"
            ) from e
        self.port = self._server.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="metrics", daemon=True
        )
        self._thread.start()

    def stop(self):
        if self._server:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None
