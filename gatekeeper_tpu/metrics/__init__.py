"""Metrics: OpenCensus-style views with a Prometheus exporter.

The reference records measurements against registered views (tagged
aggregations) and exports them via a Prometheus exporter on its own HTTP
server (reference pkg/metrics/exporter.go:14-15, prometheus_exporter.go).
This package re-provides that shape: `Measure` + `View` + `record()` over a
process-global `Registry`, rendered in the Prometheus text exposition format
by `gatekeeper_tpu.metrics.exporter`.
"""

from .views import (
    AGG_COUNT,
    AGG_DISTRIBUTION,
    AGG_LAST_VALUE,
    AGG_SUM,
    Measure,
    Registry,
    View,
    global_registry,
    record,
)
from .catalog import Reporters, register_catalog
from .exporter import MetricsExporter, render_prometheus

STATUS_ACTIVE = "active"
STATUS_ERROR = "error"
ALL_STATUSES = (STATUS_ACTIVE, STATUS_ERROR)

__all__ = [
    "AGG_COUNT",
    "AGG_DISTRIBUTION",
    "AGG_LAST_VALUE",
    "AGG_SUM",
    "ALL_STATUSES",
    "Measure",
    "MetricsExporter",
    "Registry",
    "Reporters",
    "STATUS_ACTIVE",
    "STATUS_ERROR",
    "View",
    "global_registry",
    "record",
    "register_catalog",
    "render_prometheus",
]
