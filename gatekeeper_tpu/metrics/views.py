"""Measure/View/Registry — the aggregation model behind the metric catalog.

Mirrors the semantics the reference gets from OpenCensus (views over
measures with tag keys; reference pkg/metrics/record.go): a view names one
aggregation of one measure, partitioned by tag values.  Supported
aggregations are the ones the catalog actually uses: count, sum,
last-value, and bucketed distribution.
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

# wall-clock anchor for exemplar timestamps: captured once at import so
# the hot-path record() never touches the wall clock (the obs/trace.py
# pattern; tools/check_observability.py enforces it)
_WALL_ANCHOR = time.time()  # wall-clock: ok (import-time anchor)
_PERF_ANCHOR = time.perf_counter()

AGG_COUNT = "count"
AGG_SUM = "sum"
AGG_LAST_VALUE = "last_value"
AGG_DISTRIBUTION = "distribution"


@dataclass(frozen=True)
class Measure:
    name: str
    description: str = ""
    unit: str = "1"


@dataclass
class View:
    name: str
    measure: Measure
    aggregation: str
    description: str = ""
    tag_keys: Tuple[str, ...] = ()
    buckets: Tuple[float, ...] = ()  # AGG_DISTRIBUTION only

    def __post_init__(self):
        if not self.description:
            self.description = self.measure.description
        if self.aggregation == AGG_DISTRIBUTION and not self.buckets:
            raise ValueError(f"view {self.name}: distribution requires buckets")


@dataclass(frozen=True)
class Exemplar:
    """One trace-linked sample on a distribution bucket (ISSUE 5): the
    OpenMetrics exemplar triple linking a hot histogram bucket to the
    /debug/traces entry that produced it."""

    value: float
    trace_id: str
    ts: float  # epoch seconds (anchor-derived, never a hot-path time.time)


@dataclass
class DistributionData:
    bucket_counts: List[int]
    count: int = 0
    sum: float = 0.0
    min: float = float("inf")
    max: float = float("-inf")
    # bucket index -> latest exemplar; bounded by construction at one
    # exemplar per bucket (len(buckets)+1 entries at most)
    exemplars: Dict[int, Exemplar] = field(default_factory=dict)


@dataclass
class _ViewState:
    view: View
    # tag-value tuple (aligned with view.tag_keys) -> aggregated value
    rows: Dict[Tuple[str, ...], object] = field(default_factory=dict)


class Registry:
    """Thread-safe collection of registered views and their rows."""

    def __init__(self):
        self._views: Dict[str, _ViewState] = {}
        # measure name -> view states: record() is on the admission hot
        # path (stage histograms record per request), so the per-record
        # cost must be O(views of this measure), not O(all views)
        self._by_measure: Dict[str, List[_ViewState]] = {}
        self._lock = threading.Lock()

    def register(self, *views: View) -> None:
        with self._lock:
            for v in views:
                existing = self._views.get(v.name)
                if existing is not None:
                    # idempotent re-registration of an equal view keeps the
                    # accumulated rows; a conflicting definition is an error
                    if existing.view != v:
                        raise ValueError(f"view {v.name} already registered")
                    continue
                state = _ViewState(view=v)
                self._views[v.name] = state
                self._by_measure.setdefault(v.measure.name, []).append(state)

    def record(
        self,
        measure: Measure,
        value: float,
        tags: Optional[Dict[str, str]] = None,
        count: int = 1,
        exemplar_trace_id: Optional[str] = None,
    ) -> None:
        """Record one measurement against every view of this measure.
        ``count`` batches AGG_COUNT increments (N cache hits recorded in
        one lock hold); the other aggregations treat the call as a single
        sample regardless.  ``exemplar_trace_id`` (when the caller has an
        active trace) attaches a bounded per-bucket exemplar to every
        distribution view of the measure."""
        tags = tags or {}
        with self._lock:
            for state in self._by_measure.get(measure.name, ()):
                key = tuple(tags.get(k, "") for k in state.view.tag_keys)
                self._apply(state, key, value, count, exemplar_trace_id)

    def record_many(
        self,
        measure: Measure,
        samples,
        exemplar_trace_id: Optional[str] = None,
    ) -> None:
        """Record N ``(value, tags)`` measurements of one measure under
        a SINGLE lock hold — the event-loop edge flushes a request's
        six wire-stage observes in one call instead of six lock
        round-trips on the reactor thread."""
        with self._lock:
            for state in self._by_measure.get(measure.name, ()):
                keys = state.view.tag_keys
                for value, tags in samples:
                    key = tuple(tags.get(k, "") for k in keys)
                    self._apply(state, key, value, 1, exemplar_trace_id)

    def observer(self, measure: Measure, tag_key: str):
        """Prebound recorder for a single-tag measure on a reactor hot
        path: returns ``obs(pairs, exemplar_trace_id=None)`` with pairs
        ``[(tag_value, value)]`` — one lock hold for the whole batch,
        and the per-tag-value row key tuples memoized instead of
        rebuilt per sample.  Row objects are still fetched per call so
        :meth:`clear` keeps working.  Views registered after a tag
        value is first seen are not picked up for that value — build
        observers after catalog registration (the catalog does)."""
        memo: Dict[str, list] = {}

        def keyed(tv: str) -> list:
            rows = [
                (st, tuple(tv if k == tag_key else ""
                           for k in st.view.tag_keys))
                for st in self._by_measure.get(measure.name, ())
            ]
            memo[tv] = rows
            return rows

        bisect_left = bisect.bisect_left

        def obs(pairs, exemplar_trace_id: Optional[str] = None) -> None:
            with self._lock:
                for tv, value in pairs:
                    for st, key in (memo.get(tv) or keyed(tv)):
                        v = st.view
                        if v.aggregation != AGG_DISTRIBUTION:
                            self._apply(st, key, value, 1,
                                        exemplar_trace_id)
                            continue
                        # inlined _apply distribution branch: the stage
                        # histogram flush is the reactor's hottest
                        # metric path, worth skipping a frame per sample
                        dist = st.rows.get(key)
                        if dist is None:
                            dist = DistributionData(
                                bucket_counts=[0] * (len(v.buckets) + 1)
                            )
                            st.rows[key] = dist
                        idx = bisect_left(v.buckets, value)
                        dist.bucket_counts[idx] += 1
                        dist.count += 1
                        dist.sum += value
                        if value < dist.min:
                            dist.min = value
                        if value > dist.max:
                            dist.max = value
                        if exemplar_trace_id:
                            dist.exemplars[idx] = Exemplar(
                                value=float(value),
                                trace_id=exemplar_trace_id,
                                ts=_WALL_ANCHOR
                                + (time.perf_counter() - _PERF_ANCHOR),
                            )

        return obs

    def _apply(self, state, key, value, count, exemplar_trace_id) -> None:
        """One measurement into one view's row (caller holds _lock)."""
        v = state.view
        if v.aggregation == AGG_COUNT:
            state.rows[key] = int(state.rows.get(key, 0)) + count
        elif v.aggregation == AGG_SUM:
            state.rows[key] = float(state.rows.get(key, 0.0)) + value
        elif v.aggregation == AGG_LAST_VALUE:
            state.rows[key] = float(value)
        elif v.aggregation == AGG_DISTRIBUTION:
            dist = state.rows.get(key)
            if dist is None:
                dist = DistributionData(
                    bucket_counts=[0] * (len(v.buckets) + 1)
                )
                state.rows[key] = dist
            # first bound >= value, i.e. the "value <= bound" bucket;
            # bisect beats the linear scan on the wide stage histograms
            idx = bisect.bisect_left(v.buckets, value)
            dist.bucket_counts[idx] += 1
            dist.count += 1
            dist.sum += value
            dist.min = min(dist.min, value)
            dist.max = max(dist.max, value)
            if exemplar_trace_id:
                dist.exemplars[idx] = Exemplar(
                    value=float(value),
                    trace_id=exemplar_trace_id,
                    ts=_WALL_ANCHOR
                    + (time.perf_counter() - _PERF_ANCHOR),
                )

    def snapshot(self) -> List[Tuple[View, Dict[Tuple[str, ...], object]]]:
        import copy

        with self._lock:
            return [
                (s.view, copy.deepcopy(s.rows)) for s in self._views.values()
            ]

    def view_rows(self, name: str) -> Dict[Tuple[str, ...], object]:
        """Test/introspection helper: rows of one view by name."""
        import copy

        with self._lock:
            s = self._views.get(name)
            return copy.deepcopy(s.rows) if s else {}

    def clear(self) -> None:
        with self._lock:
            for s in self._views.values():
                s.rows.clear()


_global = Registry()


def global_registry() -> Registry:
    return _global


def record(measure: Measure, value: float, tags: Optional[Dict[str, str]] = None):
    """The analogue of metrics.Record (reference pkg/metrics/record.go)."""
    _global.record(measure, value, tags)
