"""The metric catalog (reference docs/Metrics.md) and the reporter facade.

Every metric the reference documents, with the same names, tags, and bucket
boundaries, defined against this framework's view registry:

  constraints                                pkg/controller/constraint/stats_reporter.go:13-36
  constraint_templates                       pkg/controller/constrainttemplate/stats_reporter.go:15-33
  constraint_template_ingestion_count        .../stats_reporter.go:36-41
  constraint_template_ingestion_duration_seconds  .../stats_reporter.go:43-48
  request_count / request_duration_seconds   pkg/webhook/stats_reporter.go:13-25,71-88
  violations                                 pkg/audit/stats_reporter.go:15-41
  audit_duration_seconds / audit_last_run_time    pkg/audit/stats_reporter.go:42-53
  sync / sync_duration_seconds / sync_last_run_time  pkg/controller/sync/stats_reporter.go:14-46
  watch_manager_watched_gvk / watch_manager_intended_watch_gvk  pkg/watch/stats_reporter.go:13-33
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from .views import (
    AGG_COUNT,
    AGG_DISTRIBUTION,
    AGG_LAST_VALUE,
    Measure,
    Registry,
    View,
    global_registry,
)

# ---- measures ---------------------------------------------------------------

CONSTRAINTS_M = Measure("constraints", "Current number of known constraints")
CT_M = Measure(
    "constraint_templates", "Number of observed constraint templates"
)
INGEST_DURATION_M = Measure(
    "constraint_template_ingestion_duration_seconds",
    "How long it took to ingest a constraint template in seconds",
    unit="s",
)
REQUEST_DURATION_M = Measure(
    "request_duration_seconds", "The response time in seconds", unit="s"
)
VIOLATIONS_M = Measure(
    "violations", "Total number of violations per constraint"
)
AUDIT_DURATION_M = Measure(
    "audit_duration_seconds", "Latency of audit operation in seconds", unit="s"
)
AUDIT_LAST_RUN_M = Measure(
    "audit_last_run_time", "Timestamp of last audit run time", unit="s"
)
SYNC_M = Measure(
    "sync", "Total number of resources of each kind being cached"
)
SYNC_DURATION_M = Measure(
    "sync_duration_seconds", "Latency of sync operation in seconds", unit="s"
)
SYNC_LAST_RUN_M = Measure(
    "sync_last_run_time", "Timestamp of last sync operation", unit="s"
)
WATCHED_GVK_M = Measure(
    "watch_manager_watched_gvk", "Total number of watched GroupVersionKinds"
)
INTENDED_GVK_M = Measure(
    "watch_manager_intended_watch_gvk",
    "Total number of GroupVersionKinds with a registered watch intent",
)
# ---- robustness additions (fault plane / breaker / audit health) -----------
AUDIT_STATUS_M = Measure(
    "audit_last_run_status",
    "Whether the most recent audit run succeeded (1) or failed (0)",
)
AUDIT_FAILS_M = Measure(
    "audit_consecutive_failures",
    "Consecutive audit runs that have failed since the last success",
)
BREAKER_STATE_M = Measure(
    "tpu_breaker_state",
    "TPU circuit breaker state (0 closed, 1 half-open, 2 open)",
)
BREAKER_TRIPS_M = Measure(
    "tpu_breaker_trips",
    "Cumulative TPU circuit breaker trips (closed -> open transitions)",
)
BREAKER_DEGRADED_M = Measure(
    "tpu_breaker_degraded_seconds",
    "Cumulative seconds spent with the TPU breaker not closed "
    "(evaluation served by the interpreter tier)",
    unit="s",
)
# ---- observability additions (per-stage hot-path telemetry, ISSUE 2) --------
WEBHOOK_QUEUE_M = Measure(
    "webhook_batch_queue_seconds",
    "Time an admission review waited in the micro-batch queue before its "
    "batch dispatched",
    unit="s",
)
BATCH_SIZE_M = Measure(
    "webhook_batch_size",
    "Admission reviews coalesced into one batched evaluation",
)
PACK_M = Measure(
    "tpu_pack_seconds",
    "Host-side tensor packing time per evaluation (reviews + columns)",
    unit="s",
)
COMPILE_M = Measure(
    "tpu_compile_seconds",
    "XLA trace+compile time per fused-executable build (cache misses only)",
    unit="s",
)
DISPATCH_M = Measure(
    "tpu_dispatch_seconds",
    "Device dispatch + result fetch time per evaluation",
    unit="s",
)
CACHE_M = Measure(
    "cache_requests",
    "Evaluation-cache lookups by cache (request_memo, aotcache, xlacache) "
    "and outcome (hit, miss)",
)
# ---- compiled violation rendering (ISSUE 4) ---------------------------------
RENDER_CELLS_M = Measure(
    "render_cells",
    "Violation-candidate cells rendered, by plan tier: static (bind-time "
    "constant message), slots (compiled field-gather message), interp "
    "(interpreter fallback)",
)
# ---- snapshot / warm-resume subsystem (ISSUE 3) -----------------------------
SNAPSHOT_WRITE_M = Measure(
    "snapshot_write_seconds",
    "Wall time to capture + persist one state snapshot (capture under the "
    "driver lock plus serialization and the atomic rename)",
    unit="s",
)
SNAPSHOT_LOAD_M = Measure(
    "snapshot_load_seconds",
    "Wall time of a startup snapshot restore: validation, array load and "
    "the kube delta resync",
    unit="s",
)
SNAPSHOT_BYTES_M = Measure(
    "snapshot_bytes",
    "On-disk size of the most recently written snapshot directory",
    unit="By",
)
SNAPSHOT_RESTORE_M = Measure(
    "snapshot_restore_outcome",
    "Startup snapshot restore attempts by outcome (restored, fallback, "
    "none, disabled), plus one 'quarantined' sample per snapshot a "
    "restore moved aside into .quarantine/ after failed validation",
)
# ---- cost attribution + SLO engine (ISSUE 5) --------------------------------
# The cost_* gauges are refreshed from the cost ledger's decaying window
# by the exporter's pre-scrape hook (obs/costs.py collect); their
# `template` label is top-K-capped with an `other` rollup — the
# cardinality contract tools/check_observability.py lints.
COST_DEVICE_MS_M = Measure(
    "cost_device_ms",
    "Device (dispatch) milliseconds attributed to a template over the "
    "cost-ledger window, apportioned by evaluated cells",
    unit="ms",
)
COST_RENDER_MS_M = Measure(
    "cost_render_ms",
    "Host render milliseconds attributed to a template over the "
    "cost-ledger window, apportioned by rendered cells",
    unit="ms",
)
COST_CELLS_M = Measure(
    "cost_cells",
    "Cells evaluated for a template over the cost-ledger window",
)
COST_RENDER_CELLS_M = Measure(
    "cost_render_cells",
    "Violation-candidate cells rendered for a template over the "
    "cost-ledger window, by render-plan tier",
)
COST_VIOLATIONS_M = Measure(
    "cost_violations",
    "Violations rendered for a template over the cost-ledger window",
)
COST_MEMO_HIT_RATIO_M = Measure(
    "cost_memo_hit_ratio",
    "Review-memo hit ratio for a template's rendered cells over the "
    "cost-ledger window",
)
# ---- sharded mesh audit (ISSUE 6) -------------------------------------------
# Per-shard stage telemetry for the double-buffered host-pack / device-
# commit pipeline (parallel/mesh.py pipelined_shard_commit): one sample
# per shard per full placement, labelled by path (review/audit).
AUDIT_SHARD_ROWS_M = Measure(
    "audit_shard_rows",
    "Rows committed to one mesh shard's contiguous slab per full "
    "placement (the per-device share of the sharded [C, R] sweep)",
)
AUDIT_SHARD_PACK_M = Measure(
    "audit_shard_pack_seconds",
    "Host-side slab slice/pad time per shard in the double-buffered "
    "placement pipeline (overlaps the previous shard's transfer)",
    unit="s",
)
AUDIT_SHARD_DISPATCH_M = Measure(
    "audit_shard_dispatch_seconds",
    "Per-shard device commit (async transfer issue) time in the "
    "double-buffered placement pipeline",
    unit="s",
)
# ---- fleet serving + load-adaptive micro-batcher (ISSUE 7) ------------------
# All four series carry the replica_id label (util.replica_id(); empty on
# single-process deployments) so a scraped fleet's telemetry separates
# per replica without relying on scrape-time instance labels.
REPLICA_UP_M = Measure(
    "replica_up",
    "1 for a started gatekeeper process, labelled by its fleet "
    "replica_id (empty outside a fleet)",
)
BATCH_TARGET_M = Measure(
    "webhook_batch_target_size",
    "The micro-batcher's current load-adapted target batch size "
    "(1 = immediate dispatch at the latency floor)",
)
BATCH_DEADLINE_M = Measure(
    "webhook_batch_deadline_ms",
    "The micro-batcher's current load-adapted flush deadline: how long "
    "the accumulation window stays open under observed concurrency",
    unit="ms",
)
OFFERED_LOAD_M = Measure(
    "webhook_offered_load_rps",
    "Offered admission load the micro-batcher currently observes "
    "(decayed arrival rate, requests/second)",
)
SLO_BURN_M = Measure(
    "slo_burn_rate",
    "Error-budget burn rate per SLO objective and trailing window "
    "(1.0 = budget consumed exactly at the sustainable rate)",
)
SLO_BUDGET_M = Measure(
    "slo_error_budget_remaining",
    "Fraction of the 6h error budget remaining per SLO objective",
)
AUDIT_AGE_M = Measure(
    "audit_last_run_age_s",
    "Seconds since the last successful audit sweep finished (since "
    "process start when none has completed)",
    unit="s",
)
# ---- self-healing fleet (ISSUE 8) -------------------------------------------
REPLICA_RESTARTS_M = Measure(
    "fleet_replica_restarts",
    "Supervisor-initiated replica restarts by replica_id and reason "
    "(crash, wedge, rolling)",
)
REPLICA_STATE_M = Measure(
    "fleet_replica_state",
    "Supervised replica state (0 running, 1 restarting, 2 quarantined, "
    "3 draining, 4 stopped), per replica_id",
)
MESH_STALL_M = Measure(
    "mesh_dispatch_stalls",
    "Mesh-collective dispatches abandoned by the dispatch watchdog "
    "(each trips the breaker and re-shards the sweep narrower)",
)
MESH_WIDTH_M = Measure(
    "mesh_sweep_width",
    "Row-sharding width currently serving device audit sweeps "
    "(1 = the single-device path; drops when a dispatch stall degrades "
    "the mesh)",
)
# ---- fleet observability plane (ISSUE 11) -----------------------------------
# Wire-path stage telemetry recorded by the front door (the serving edge
# the FLEET_r06 176 reviews/s number traverses), scrape-health gauges
# recorded by the metrics federator, and the sampling profiler's own
# accounting.  Stage names are the frontdoor.WIRE_STAGES stable set
# (docs/tracing.md); tools/check_observability.py cross-checks them.
FRONTDOOR_STAGE_M = Measure(
    "frontdoor_stage_seconds",
    "Time one admission request spent in one front-door wire-path stage "
    "(accept, read_body, route_choose, proxy_connect, replica_wait, "
    "write_back) — the stages are disjoint and sum to the wire latency",
    unit="s",
)
FRONTDOOR_REQS_M = Measure(
    "frontdoor_requests",
    "Requests through the fleet front door by outcome (ok, "
    "backend_error, no_backend, bad_request) and serving backend "
    "replica id (empty when no backend answered)",
)
FLEET_SCRAPE_OK_M = Measure(
    "fleet_scrape_ok",
    "1 when the federator's most recent scrape of this replica's "
    "exporter succeeded, 0 when the federated view is serving its "
    "stale-marked last-known-good series",
)
FLEET_SCRAPE_AGE_M = Measure(
    "fleet_scrape_age_seconds",
    "Seconds since the federator last scraped this replica "
    "successfully (grows while the replica is wedged or down)",
    unit="s",
)
FLEET_SCRAPED_M = Measure(
    "fleet_replicas_scraped",
    "Replica exporters scraped successfully on the federator's most "
    "recent pass (the fleet rollup's freshness denominator)",
)
FLEET_ADMISSIONS_M = Measure(
    "fleet_admission_requests",
    "Fleet rollup: sum of request_count samples across every scraped "
    "replica exporter (stale-marked series included)",
)
# ---- overload robustness plane (ISSUE 12) -----------------------------------
# Bounded-backpressure accounting: every request refused by a bound
# (micro-batcher max_pending, front-door inflight cap, expired deadline,
# spent retry budget) counts here by reason — the shed rate is also a
# brownout-ladder input (obs/brownout.py).
SHED_M = Measure(
    "shed",
    "Admission requests refused by the overload plane, by reason "
    "(queue_full, queue_full_dryrun, door_inflight, deadline_expired) "
    "— every shed is an explicit fail-open/closed decision, never a "
    "timeout (denied retries count separately in "
    "frontdoor_retries_denied_total)",
)
BROWNOUT_M = Measure(
    "brownout_level",
    "Current brownout-ladder level (0 normal; 1 audit/snapshot "
    "deferral; 2 + reduced trace sampling and profiler rate; 3 + router "
    "pinned to the cheapest sustainable tier)",
)
RETRY_TOKENS_M = Measure(
    "frontdoor_retry_tokens",
    "Tokens currently in the front door's retry budget bucket; retries "
    "are denied at zero so they cannot amplify a brownout into a storm",
)
RETRY_DENIED_M = Measure(
    "frontdoor_retries_denied",
    "Front-door retries denied because the retry budget bucket was "
    "empty (the request fails over to the explicit 502 path instead)",
)
# ---- engine observability plane (ISSUE 13) ----------------------------------
# Route-explainability counter fed per BATCH decision by the driver's
# route ledger (obs/routeledger.py); compile/device telemetry gauges fed
# by obs/compilestats.py from the aot/async/xla compile paths and the
# driver's device-placement chokepoints.
ROUTE_DECISIONS_M = Measure(
    "route_decisions",
    "Evaluation routing decisions by chosen tier (device, np, interp) "
    "and deciding reason (latency, load_aware, saturated, brownout_pin, "
    "breaker_open, compile_pending, device_failed, forced_device, "
    "uncalibrated_prior) — one per evaluated batch, never per review",
)
JOIN_PLANS_M = Measure(
    "join_plans",
    "Active cross-resource join plans (referential policies classified "
    "into vectorized join/aggregate kernels, ops/joinkernel.py)",
)
JOIN_AFFECTED_M = Measure(
    "join_delta_affected_rows",
    "Reader rows co-dispatched by a delta sweep because a churned row "
    "changed their join key group's aggregate — the key-group locality "
    "cost beyond raw churn",
)
JOIN_DIVERGENCE_M = Measure(
    "join_plan_divergence",
    "Cells an exact join plan flagged whose interpreter-oracle render "
    "was empty (interned-key/aggregate divergence; raises under "
    "GK_JOIN_ASSERT=1)",
)
COMPILE_LAG_M = Measure(
    "compile_epoch_lag",
    "Constraint-side mutation epochs the async background compiler is "
    "behind the live epoch (0 = the compiled executable is current; the "
    "backlog the audit wait loop otherwise infers blind)",
)
DEVICE_BYTES_M = Measure(
    "device_bytes",
    "Device-resident bytes by component: the packed [C,R] audit arrays "
    "(audit_pack / audit_pack_mesh with per-shard slab share) and the "
    "replicated constraint side, recorded at each placement",
    unit="By",
)
XLA_COUNTERS_M = Measure(
    "xlacache_counters_available",
    "1 when jax's persistent-compilation-cache monitoring events exist "
    "on this build (cache_requests_total{cache=xlacache} is live), 0 "
    "when they are absent and that instrumentation is silently missing",
)
# ---- decision log (ISSUE 15) ------------------------------------------------
# Durable verdict provenance (obs/decisionlog.py): record/drop accounting
# for the non-blocking decision recorder — a dropped record is an audit
# gap and must be visible, never silent (the telemetry-drop contract).
DECISION_RECORDS_M = Measure(
    "decision_log_records",
    "Decision records accepted by the recorder, by decision class "
    "(allow, deny, shed, expired, error) or 'audit_transition' — "
    "sampled-out records count in decision_log_dropped_total instead",
)
DECISION_DROPPED_M = Measure(
    "decision_log_dropped",
    "Decision records not written, by reason (sampled_out: head "
    "sampling; queue_full: bounded-queue shed; write_error: disk "
    "failure; transition_overflow: per-sweep transition cap) — every "
    "drop is counted, never silent",
)
DECISION_SEGMENTS_M = Measure(
    "decision_log_segments",
    "Completed decision-log segments made visible by the writer's "
    "atomic rename (rotation by size/time; bounded retention prunes "
    "this replica's own oldest segments)",
)
DECISION_BYTES_M = Measure(
    "decision_log_bytes",
    "Bytes of decision records committed into completed segments",
    unit="By",
)
PROFILER_SAMPLES_M = Measure(
    "profiler_samples",
    "Thread-stack samples collected by the always-on sampling profiler "
    "(obs/profiler.py; one sample = one thread's stack at one tick)",
)
PROFILER_OVERFLOW_M = Measure(
    "profiler_overflow",
    "Profiler samples dropped because the unique-stack table hit its "
    "memory bound (max_stacks); the profile is still valid, its tail "
    "is just truncated",
)
# ---- reactor observability plane (ISSUE 20) ---------------------------------
# Runtime health of the serving-edge event loops (fleet/evloop.py via
# obs/reactorobs.py): every series carries the `loop` tag (evdoor,
# wirelistener) because the door and the replica listener each run their
# own reactor.  The per-tick series are tick-batched and flush-sampled —
# the reactor thread pays plain arithmetic per tick, never a registry
# lock per tick.
EVLOOP_LAG_M = Measure(
    "evloop_lag_seconds",
    "Scheduling skew of the reactor's self-scheduled heartbeat timer: "
    "how late the loop fired a timer it armed for a known instant — "
    "THE loop-health gauge (a slow callback anywhere delays every "
    "connection by at least this much)",
    unit="s",
)
EVLOOP_TICK_M = Measure(
    "evloop_tick_seconds",
    "Duration of one reactor tick (select wait + I/O callbacks + "
    "timers + posted callbacks + tick hooks), flush-sampled",
    unit="s",
)
EVLOOP_UTIL_M = Measure(
    "evloop_utilization",
    "Fraction of reactor wall time spent running callbacks rather than "
    "waiting in select() over the last telemetry flush window (1.0 = "
    "the loop thread is saturated and queueing work)",
)
EVLOOP_CBS_M = Measure(
    "evloop_callbacks_per_tick",
    "Callbacks (I/O + timer + posted) dispatched in one reactor tick, "
    "flush-sampled",
)
EVLOOP_DRIFT_M = Measure(
    "evloop_timer_drift_seconds",
    "Timer-wheel drift: how far past its due instant a timer actually "
    "fired (sweep, heartbeat, deadline-expiry timers all ride the same "
    "monotonic heap)",
    unit="s",
)
EVLOOP_SLOW_M = Measure(
    "evloop_slow_callbacks",
    "Reactor callbacks that ran past the slow-callback threshold and "
    "landed in the top-K culprit table (each also emits an "
    "evloop_stall flight-recorder event, rate-bounded per culprit)",
)
EVLOOP_STALLS_M = Measure(
    "evloop_stalls",
    "Reactor stalls past the watchdog budget caught by the cross-"
    "thread watchdog (each dumps a flight-recorder incident carrying "
    "the reactor thread's folded stack)",
)
# GKW1 wire telemetry, both ends: `end` is door (fleet/evdoor.py) or
# replica (fleet/wirelistener.py), `kind` the frame kind.  Chunk/byte
# counts are tick-batched on the reactor threads and flushed on the
# reactorobs cadence.
WIRE_CHUNKS_M = Measure(
    "wire_chunks",
    "GKW1 chunk frames moved on the door<->replica wire, by end (door, "
    "replica) and frame kind (request, response)",
)
WIRE_RECORDS_M = Measure(
    "wire_chunk_records",
    "Records batched into one GKW1 chunk frame (the tick-coalescing "
    "win the batched protocol exists for), by end and kind",
)
WIRE_BYTES_M = Measure(
    "wire_bytes",
    "Bytes moved on the door<->replica wire, by end and direction "
    "(in, out)",
    unit="By",
)
WIRE_DECODE_ERRORS_M = Measure(
    "wire_decode_errors",
    "GKW1 frame streams abandoned as undecodable (wireproto."
    "ProtocolError; the carrying connection closes — there is no "
    "resync point in a length-prefixed stream that lied)",
)
WIRE_RECONNECTS_M = Measure(
    "wire_reconnects",
    "Door-side wire-connection rebuilds to a backend whose previous "
    "persistent connection was lost, by backend replica id",
)
WIRE_BACKLOG_STALL_M = Measure(
    "wire_backlog_stall_seconds",
    "Duration of one door-side wire-connection backlog episode: the "
    "span from a chunk write leaving bytes buffered (the kernel socket "
    "buffer filled) until the backlog fully drained, by backend",
    unit="s",
)

# bucket boundaries copied from the reference's view.Distribution calls
_INGEST_BUCKETS = (
    0.01, 0.02, 0.03, 0.04, 0.05, 0.06, 0.07, 0.08, 0.09, 0.1,
    0.2, 0.3, 0.4, 0.5, 1, 2, 3, 4, 5,
)
_REQUEST_BUCKETS = (
    0.001, 0.002, 0.003, 0.004, 0.005, 0.006, 0.007, 0.008, 0.009, 0.01,
    0.02, 0.03, 0.04, 0.05,
)
_AUDIT_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1, 2, 3, 4, 5)
_SYNC_BUCKETS = (
    0.0001, 0.0002, 0.0003, 0.0004, 0.0005, 0.0006, 0.0007, 0.0008, 0.0009,
    0.001, 0.002, 0.003, 0.004, 0.005, 0.01, 0.02, 0.03, 0.04, 0.05,
)
# stage timings span ~50us (warm host pack) to seconds (cold XLA compile)
_STAGE_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)
_BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
# rows per shard slab: admission batches (tens) to 1M-row clusters over
# an 8-chip mesh (125k rows/shard)
_SHARD_ROWS_BUCKETS = (
    8, 64, 512, 2048, 8192, 32768, 131072, 524288,
)
# snapshot write/load span ~10ms (small corpora) to tens of seconds (100k
# rows through json+npz on a loaded node)
_SNAPSHOT_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def catalog_views():
    return [
        View("constraints", CONSTRAINTS_M, AGG_LAST_VALUE,
             tag_keys=("enforcement_action", "status")),
        View("constraint_templates", CT_M, AGG_LAST_VALUE,
             tag_keys=("status",)),
        View("constraint_template_ingestion_count", INGEST_DURATION_M,
             AGG_COUNT,
             description="Total number of constraint template ingestion actions",
             tag_keys=("status",)),
        View("constraint_template_ingestion_duration_seconds",
             INGEST_DURATION_M, AGG_DISTRIBUTION,
             description="Distribution of how long it took to ingest a "
                         "constraint template in seconds",
             tag_keys=("status",), buckets=_INGEST_BUCKETS),
        View("request_count", REQUEST_DURATION_M, AGG_COUNT,
             description="The number of requests that are routed to webhook",
             tag_keys=("admission_status",)),
        View("request_duration_seconds", REQUEST_DURATION_M, AGG_DISTRIBUTION,
             tag_keys=("admission_status",), buckets=_REQUEST_BUCKETS),
        View("violations", VIOLATIONS_M, AGG_LAST_VALUE,
             tag_keys=("enforcement_action",)),
        View("audit_duration_seconds", AUDIT_DURATION_M, AGG_DISTRIBUTION,
             buckets=_AUDIT_BUCKETS),
        View("audit_last_run_time", AUDIT_LAST_RUN_M, AGG_LAST_VALUE),
        View("sync", SYNC_M, AGG_LAST_VALUE, tag_keys=("kind", "status")),
        View("sync_duration_seconds", SYNC_DURATION_M, AGG_DISTRIBUTION,
             buckets=_SYNC_BUCKETS),
        View("sync_last_run_time", SYNC_LAST_RUN_M, AGG_LAST_VALUE),
        View("watch_manager_watched_gvk", WATCHED_GVK_M, AGG_LAST_VALUE),
        View("watch_manager_intended_watch_gvk", INTENDED_GVK_M,
             AGG_LAST_VALUE),
        View("audit_last_run_status", AUDIT_STATUS_M, AGG_LAST_VALUE),
        View("audit_consecutive_failures", AUDIT_FAILS_M, AGG_LAST_VALUE),
        View("tpu_breaker_state", BREAKER_STATE_M, AGG_LAST_VALUE),
        View("tpu_breaker_trips", BREAKER_TRIPS_M, AGG_LAST_VALUE),
        View("tpu_breaker_degraded_seconds", BREAKER_DEGRADED_M,
             AGG_LAST_VALUE),
        View("webhook_batch_queue_seconds", WEBHOOK_QUEUE_M,
             AGG_DISTRIBUTION, buckets=_STAGE_BUCKETS),
        View("webhook_batch_size", BATCH_SIZE_M, AGG_DISTRIBUTION,
             buckets=_BATCH_SIZE_BUCKETS),
        View("tpu_pack_seconds", PACK_M, AGG_DISTRIBUTION,
             tag_keys=("path",), buckets=_STAGE_BUCKETS),
        View("tpu_compile_seconds", COMPILE_M, AGG_DISTRIBUTION,
             tag_keys=("path",), buckets=_STAGE_BUCKETS),
        View("tpu_dispatch_seconds", DISPATCH_M, AGG_DISTRIBUTION,
             tag_keys=("path", "tier"), buckets=_STAGE_BUCKETS),
        View("cache_requests_total", CACHE_M, AGG_COUNT,
             tag_keys=("cache", "outcome")),
        View("render_cells_total", RENDER_CELLS_M, AGG_COUNT,
             tag_keys=("plan",)),
        View("snapshot_write_seconds", SNAPSHOT_WRITE_M, AGG_DISTRIBUTION,
             buckets=_SNAPSHOT_BUCKETS),
        View("snapshot_load_seconds", SNAPSHOT_LOAD_M, AGG_DISTRIBUTION,
             buckets=_SNAPSHOT_BUCKETS),
        View("snapshot_bytes", SNAPSHOT_BYTES_M, AGG_LAST_VALUE),
        View("snapshot_restore_outcome_total", SNAPSHOT_RESTORE_M, AGG_COUNT,
             tag_keys=("outcome",)),
        View("cost_device_ms", COST_DEVICE_MS_M, AGG_LAST_VALUE,
             tag_keys=("template",)),
        View("cost_render_ms", COST_RENDER_MS_M, AGG_LAST_VALUE,
             tag_keys=("template",)),
        View("cost_cells", COST_CELLS_M, AGG_LAST_VALUE,
             tag_keys=("template",)),
        View("cost_render_cells", COST_RENDER_CELLS_M, AGG_LAST_VALUE,
             tag_keys=("template", "plan")),
        View("cost_violations", COST_VIOLATIONS_M, AGG_LAST_VALUE,
             tag_keys=("template",)),
        View("cost_memo_hit_ratio", COST_MEMO_HIT_RATIO_M, AGG_LAST_VALUE,
             tag_keys=("template",)),
        View("audit_shard_rows", AUDIT_SHARD_ROWS_M, AGG_DISTRIBUTION,
             tag_keys=("path",), buckets=_SHARD_ROWS_BUCKETS),
        View("audit_shard_pack_seconds", AUDIT_SHARD_PACK_M,
             AGG_DISTRIBUTION, tag_keys=("path",), buckets=_STAGE_BUCKETS),
        View("audit_shard_dispatch_seconds", AUDIT_SHARD_DISPATCH_M,
             AGG_DISTRIBUTION, tag_keys=("path",), buckets=_STAGE_BUCKETS),
        View("replica_up", REPLICA_UP_M, AGG_LAST_VALUE,
             tag_keys=("replica_id",)),
        View("webhook_batch_target_size", BATCH_TARGET_M, AGG_LAST_VALUE,
             tag_keys=("replica_id",)),
        View("webhook_batch_deadline_ms", BATCH_DEADLINE_M, AGG_LAST_VALUE,
             tag_keys=("replica_id",)),
        View("webhook_offered_load_rps", OFFERED_LOAD_M, AGG_LAST_VALUE,
             tag_keys=("replica_id",)),
        View("slo_burn_rate", SLO_BURN_M, AGG_LAST_VALUE,
             tag_keys=("objective", "window")),
        View("slo_error_budget_remaining", SLO_BUDGET_M, AGG_LAST_VALUE,
             tag_keys=("objective",)),
        View("audit_last_run_age_s", AUDIT_AGE_M, AGG_LAST_VALUE),
        View("fleet_replica_restarts_total", REPLICA_RESTARTS_M, AGG_COUNT,
             tag_keys=("replica_id", "reason")),
        View("fleet_replica_state", REPLICA_STATE_M, AGG_LAST_VALUE,
             tag_keys=("replica_id",)),
        View("mesh_dispatch_stalls_total", MESH_STALL_M, AGG_COUNT),
        View("mesh_sweep_width", MESH_WIDTH_M, AGG_LAST_VALUE),
        View("frontdoor_stage_seconds", FRONTDOOR_STAGE_M,
             AGG_DISTRIBUTION, tag_keys=("stage",), buckets=_STAGE_BUCKETS),
        View("frontdoor_requests_total", FRONTDOOR_REQS_M, AGG_COUNT,
             tag_keys=("outcome", "backend")),
        View("fleet_scrape_ok", FLEET_SCRAPE_OK_M, AGG_LAST_VALUE,
             tag_keys=("replica_id",)),
        View("fleet_scrape_age_seconds", FLEET_SCRAPE_AGE_M,
             AGG_LAST_VALUE, tag_keys=("replica_id",)),
        View("fleet_replicas_scraped", FLEET_SCRAPED_M, AGG_LAST_VALUE),
        View("fleet_admission_requests", FLEET_ADMISSIONS_M,
             AGG_LAST_VALUE),
        View("profiler_samples_total", PROFILER_SAMPLES_M, AGG_COUNT),
        View("profiler_overflow_total", PROFILER_OVERFLOW_M, AGG_COUNT),
        View("shed_total", SHED_M, AGG_COUNT, tag_keys=("reason",)),
        View("brownout_level", BROWNOUT_M, AGG_LAST_VALUE),
        View("frontdoor_retry_tokens", RETRY_TOKENS_M, AGG_LAST_VALUE),
        View("frontdoor_retries_denied_total", RETRY_DENIED_M, AGG_COUNT),
        View("route_decisions_total", ROUTE_DECISIONS_M, AGG_COUNT,
             tag_keys=("tier", "reason")),
        View("join_plans", JOIN_PLANS_M, AGG_LAST_VALUE),
        View("join_delta_affected_rows_total", JOIN_AFFECTED_M, AGG_COUNT),
        View("join_plan_divergence_total", JOIN_DIVERGENCE_M, AGG_COUNT),
        View("compile_epoch_lag", COMPILE_LAG_M, AGG_LAST_VALUE),
        View("device_bytes", DEVICE_BYTES_M, AGG_LAST_VALUE,
             tag_keys=("component",)),
        View("xlacache_counters_available", XLA_COUNTERS_M,
             AGG_LAST_VALUE),
        View("decision_log_records_total", DECISION_RECORDS_M, AGG_COUNT,
             tag_keys=("class",)),
        View("decision_log_dropped_total", DECISION_DROPPED_M, AGG_COUNT,
             tag_keys=("reason",)),
        View("decision_log_segments_total", DECISION_SEGMENTS_M,
             AGG_COUNT),
        View("decision_log_bytes_total", DECISION_BYTES_M, AGG_COUNT),
        View("evloop_lag_seconds", EVLOOP_LAG_M, AGG_LAST_VALUE,
             tag_keys=("loop",)),
        View("evloop_tick_seconds", EVLOOP_TICK_M, AGG_DISTRIBUTION,
             tag_keys=("loop",), buckets=_STAGE_BUCKETS),
        View("evloop_utilization", EVLOOP_UTIL_M, AGG_LAST_VALUE,
             tag_keys=("loop",)),
        View("evloop_callbacks_per_tick", EVLOOP_CBS_M, AGG_DISTRIBUTION,
             tag_keys=("loop",), buckets=_BATCH_SIZE_BUCKETS),
        View("evloop_timer_drift_seconds", EVLOOP_DRIFT_M,
             AGG_DISTRIBUTION, tag_keys=("loop",), buckets=_STAGE_BUCKETS),
        View("evloop_slow_callbacks_total", EVLOOP_SLOW_M, AGG_COUNT,
             tag_keys=("loop",)),
        View("evloop_stalls_total", EVLOOP_STALLS_M, AGG_COUNT,
             tag_keys=("loop",)),
        View("wire_chunks_total", WIRE_CHUNKS_M, AGG_COUNT,
             tag_keys=("end", "kind")),
        View("wire_chunk_records", WIRE_RECORDS_M, AGG_DISTRIBUTION,
             tag_keys=("end", "kind"), buckets=_BATCH_SIZE_BUCKETS),
        View("wire_bytes_total", WIRE_BYTES_M, AGG_COUNT,
             tag_keys=("end", "direction")),
        View("wire_decode_errors_total", WIRE_DECODE_ERRORS_M, AGG_COUNT,
             tag_keys=("end",)),
        View("wire_reconnects_total", WIRE_RECONNECTS_M, AGG_COUNT,
             tag_keys=("backend",)),
        View("wire_backlog_stall_seconds", WIRE_BACKLOG_STALL_M,
             AGG_DISTRIBUTION, tag_keys=("backend",),
             buckets=_STAGE_BUCKETS),
    ]


# views whose `template`/`constraint` labels are produced ONLY by the
# top-K-capped cost-ledger collector (obs/costs.py) — the label-
# cardinality lint (tools/check_observability.py) requires every view
# carrying such a tag key to be declared here
CAPPED_CARDINALITY_VIEWS = {
    "cost_device_ms",
    "cost_render_ms",
    "cost_cells",
    "cost_render_cells",
    "cost_violations",
    "cost_memo_hit_ratio",
}


def register_catalog(registry: Optional[Registry] = None) -> Registry:
    registry = registry or global_registry()
    registry.register(*catalog_views())
    return registry


class Reporters:
    """The facade the controllers/webhook/audit call.

    Collapses the reference's per-package StatsReporter types into one
    object with the per-consumer report methods the call sites use.
    """

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = register_catalog(registry)
        self._sync_kinds: set = set()

    # -- constraint controller (report_constraints(totals)) ------------------
    def report_constraints(self, totals: Dict[tuple, int]):
        """totals: {(enforcement_action, status): count} — the reference
        reports every (action,status) cell each reconcile
        (constraint_controller.go:425-473)."""
        for (action, status), n in totals.items():
            self.registry.record(
                CONSTRAINTS_M, float(n),
                {"enforcement_action": action, "status": status},
            )

    # -- constrainttemplate controller ---------------------------------------
    def report_templates(self, status: str, count: int):
        self.registry.record(CT_M, float(count), {"status": status})

    def report_ingestion(self, status: str, duration_s: float):
        self.registry.record(
            INGEST_DURATION_M, duration_s, {"status": status}
        )

    # -- webhook --------------------------------------------------------------
    def report_request(self, admission_status: str, duration_s: float):
        self.registry.record(
            REQUEST_DURATION_M, duration_s,
            {"admission_status": admission_status},
            exemplar_trace_id=_current_trace_id(),
        )

    # -- audit ----------------------------------------------------------------
    def report_audit_status(self, ok: bool, consecutive_failures: int):
        """Last-run status + consecutive-failure gauge: a silently failing
        audit loop (bare except around audit_once) becomes observable."""
        self.registry.record(AUDIT_STATUS_M, 1.0 if ok else 0.0)
        self.registry.record(AUDIT_FAILS_M, float(consecutive_failures))

    def report_total_violations(self, enforcement_action: str, count: int):
        self.registry.record(
            VIOLATIONS_M, float(count),
            {"enforcement_action": enforcement_action},
        )

    def report_audit_duration(self, duration_s: float):
        self.registry.record(AUDIT_DURATION_M, duration_s)

    def report_audit_last_run(self, ts: Optional[float] = None):
        self.registry.record(AUDIT_LAST_RUN_M,
                             ts if ts is not None
                             else time.time())  # wall-clock: ok (epoch gauge)

    # -- sync controller ------------------------------------------------------
    def report_sync(self, counts: Dict[object, int],
                    duration_s: Optional[float] = None):
        """duration_s=None means a bookkeeping-only update (e.g. prune):
        gauge rows refresh but no latency sample is recorded."""
        kinds = set()
        for gvk, n in counts.items():
            kind = gvk[2] if isinstance(gvk, tuple) and len(gvk) == 3 else str(gvk)
            kinds.add(kind)
            self.registry.record(
                SYNC_M, float(n), {"kind": kind, "status": "active"}
            )
        # retract gauge rows for kinds that left the sync set — last_value
        # rows otherwise report stale counts forever
        for kind in self._sync_kinds - kinds:
            self.registry.record(
                SYNC_M, 0.0, {"kind": kind, "status": "active"}
            )
        self._sync_kinds = kinds
        if duration_s is not None:
            self.registry.record(SYNC_DURATION_M, duration_s)
        self.registry.record(SYNC_LAST_RUN_M, time.time())  # wall-clock: ok (epoch gauge)

    # -- watch manager --------------------------------------------------------
    def report_gvk_count(self, watched: int, intended: int):
        self.registry.record(WATCHED_GVK_M, float(watched))
        self.registry.record(INTENDED_GVK_M, float(intended))

    # -- TPU circuit breaker --------------------------------------------------
    def report_breaker(self, status: dict):
        """Record a CircuitBreaker.status() snapshot."""
        record_breaker(status, self.registry)


def record_breaker(status: dict, registry: Optional[Registry] = None):
    """Record a breaker status snapshot against a registry (the global one
    by default).  The driver calls this from its transition hook without
    holding a Reporters instance; views are (idempotently) registered
    first so the rows exist wherever the snapshot lands."""
    registry = registry or global_registry()
    register_catalog(registry)
    registry.record(BREAKER_STATE_M, float(status.get("state_code", 0)))
    registry.record(BREAKER_TRIPS_M, float(status.get("trips", 0)))
    registry.record(
        BREAKER_DEGRADED_M, float(status.get("degraded_seconds", 0.0))
    )


# ---- hot-path stage/cache recording (ISSUE 2) -------------------------------
# The driver, micro-batcher, and AOT cache record without a Reporters
# handle.  The global registry's catalog registration is memoized behind
# one boolean so the steady-state cost is the registry's indexed record.

_GLOBAL_READY = False


_TRACE_ID_FN = None


def _current_trace_id():
    """Trace id of the active span, for histogram exemplars — one
    ContextVar read once the import is memoized; None (no exemplar)
    outside a trace."""
    global _TRACE_ID_FN
    fn = _TRACE_ID_FN
    if fn is None:
        try:
            from ..obs.trace import current_trace_id as fn
        except Exception:  # pragma: no cover - degraded obs layer
            # memoize the failure too: a broken obs import must cost one
            # attribute read per record, not a re-raised import per
            # hot-path sample
            fn = lambda: None  # noqa: E731
        _TRACE_ID_FN = fn
    try:
        return fn()
    except Exception:  # pragma: no cover - telemetry never blocks eval
        return None


def _global() -> Registry:
    global _GLOBAL_READY
    registry = global_registry()
    if not _GLOBAL_READY:
        register_catalog(registry)
        _GLOBAL_READY = True
    return registry


#: site -> count of telemetry recordings swallowed by the record_* guards.
#: Swallowing is the contract (a metrics-layer defect must never fail the
#: evaluation being measured) but the swallow itself must be observable:
#: the first drop per site logs with the traceback, the rest only count.
RECORD_DROPS: Dict[str, int] = {}


def record_dropped(site: str) -> None:
    """Account one swallowed telemetry recording (see RECORD_DROPS)."""
    try:
        n = RECORD_DROPS.get(site, 0) + 1
        RECORD_DROPS[site] = n
        if n == 1:
            import logging

            logging.getLogger("gatekeeper.metrics").warning(
                "telemetry recording failed at %s (guarded by contract; "
                "further drops only counted)", site, exc_info=True,
            )
    # the drop ACCOUNTING itself must never raise back into the hot path
    # gklint: disable=swallowed-exception -- last-ditch guard under a guard
    except Exception:
        pass


def record_stage(measure: Measure, seconds: float,
                 tags: Optional[Dict[str, str]] = None):
    """One stage-duration sample into the new per-stage histograms
    (tpu_pack_seconds / tpu_dispatch_seconds / tpu_compile_seconds /
    webhook_batch_queue_seconds), exemplar-linked to the active trace.
    Guarded: a metrics-layer defect must never fail the admission/audit
    evaluation that is being measured."""
    try:
        _global().record(
            measure, seconds, tags,
            exemplar_trace_id=_current_trace_id(),
        )
    except Exception:  # telemetry never blocks eval
        record_dropped("record_stage")


def record_batch_size(n: int):
    try:
        _global().record(BATCH_SIZE_M, float(n))
    except Exception:  # telemetry never blocks eval
        record_dropped("record_batch_size")


def record_snapshot_write(seconds: float, nbytes: int):
    """One completed snapshot write (the background snapshotter records
    without a Reporters handle).  Guarded like record_stage."""
    try:
        reg = _global()
        reg.record(SNAPSHOT_WRITE_M, seconds)
        reg.record(SNAPSHOT_BYTES_M, float(nbytes))
    except Exception:  # telemetry never blocks eval
        record_dropped("record_snapshot_write")


def record_snapshot_load(seconds: float):
    try:
        _global().record(SNAPSHOT_LOAD_M, seconds)
    except Exception:  # telemetry never blocks eval
        record_dropped("record_snapshot_load")


def record_snapshot_outcome(outcome: str):
    """One restore attempt: outcome in (restored, fallback, none,
    disabled)."""
    try:
        _global().record(SNAPSHOT_RESTORE_M, 1.0, {"outcome": outcome})
    except Exception:  # telemetry never blocks eval
        record_dropped("record_snapshot_outcome")
    try:
        from ..obs import flightrec

        flightrec.record(flightrec.SNAPSHOT_RESTORE, outcome=outcome)
    except Exception:  # the recorder must never fail a restore
        record_dropped("record_snapshot_outcome.flightrec")


def record_render_cells(counts: Dict[str, int]):
    """One render pass's cell counts by plan tier ({tier: n}); the driver
    accumulates per-cell increments locally and flushes once per pass so
    the render hot loop never pays a registry record per cell.  Guarded
    like record_stage."""
    try:
        reg = _global()
        for tier, n in counts.items():
            if n > 0:
                reg.record(
                    RENDER_CELLS_M, float(n), {"plan": tier}, count=n
                )
    except Exception:  # telemetry never blocks eval
        record_dropped("record_render_cells")


def record_audit_shard(rows: int, pack_s: float, dispatch_s: float,
                       path: str = "audit"):
    """One shard's slice through the double-buffered placement pipeline
    (parallel/mesh.py): its slab's row count, host pack time and device
    commit time.  Guarded like record_stage."""
    try:
        reg = _global()
        tags = {"path": path}
        tid = _current_trace_id()
        reg.record(AUDIT_SHARD_ROWS_M, float(rows), tags,
                   exemplar_trace_id=tid)
        reg.record(AUDIT_SHARD_PACK_M, pack_s, tags, exemplar_trace_id=tid)
        reg.record(AUDIT_SHARD_DISPATCH_M, dispatch_s, tags,
                   exemplar_trace_id=tid)
    except Exception:  # telemetry never blocks eval
        record_dropped("record_audit_shard")


def _replica_tags() -> Dict[str, str]:
    from ..util import replica_id

    return {"replica_id": replica_id()}


def record_replica_up():
    """Stamp this process's replica identity (App.start; also the fleet
    replica runtime).  Guarded like record_stage."""
    try:
        _global().record(REPLICA_UP_M, 1.0, _replica_tags())
    except Exception:  # telemetry never blocks startup
        record_dropped("record_replica_up")


def record_batcher_state(target_size: int, deadline_ms: float,
                         offered_load_rps: float):
    """The micro-batcher's current adaptation state (one record per
    dispatch, NOT per request — the batcher throttles).  Guarded like
    record_stage."""
    try:
        reg = _global()
        tags = _replica_tags()
        reg.record(BATCH_TARGET_M, float(target_size), tags)
        reg.record(BATCH_DEADLINE_M, float(deadline_ms), tags)
        reg.record(OFFERED_LOAD_M, float(offered_load_rps), tags)
    except Exception:  # telemetry never blocks eval
        record_dropped("record_batcher_state")


def record_replica_restart(replica_id: str, reason: str):
    """One supervisor-initiated replica restart (reason: crash, wedge,
    rolling).  Guarded like record_stage."""
    try:
        _global().record(
            REPLICA_RESTARTS_M, 1.0,
            {"replica_id": replica_id, "reason": reason},
        )
    except Exception:  # telemetry never blocks healing
        record_dropped("record_replica_restart")


def record_replica_state(replica_id: str, state_code: int):
    """The supervisor's current view of one replica (0 running,
    1 restarting, 2 quarantined, 3 draining, 4 stopped)."""
    try:
        _global().record(
            REPLICA_STATE_M, float(state_code), {"replica_id": replica_id}
        )
    except Exception:  # telemetry never blocks healing
        record_dropped("record_replica_state")


def record_mesh_stall():
    """One mesh-collective dispatch abandoned by the watchdog."""
    try:
        _global().record(MESH_STALL_M, 1.0)
    except Exception:  # telemetry never blocks eval
        record_dropped("record_mesh_stall")


def record_mesh_width(width: int):
    """The sweep sharding width now serving device audits (set_mesh /
    degradation)."""
    try:
        _global().record(MESH_WIDTH_M, float(width))
    except Exception:  # telemetry never blocks eval
        record_dropped("record_mesh_width")


def record_frontdoor_stage(stage: str, seconds: float):
    """One wire-path stage interval at the fleet front door (stage in
    frontdoor.WIRE_STAGES), exemplar-linked to the active wire trace.
    Guarded like record_stage."""
    try:
        _global().record(
            FRONTDOOR_STAGE_M, seconds, {"stage": stage},
            exemplar_trace_id=_current_trace_id(),
        )
    except Exception:  # telemetry never blocks the wire path
        record_dropped("record_frontdoor_stage")


_FRONTDOOR_STAGE_OBS = None


def record_frontdoor_stages(samples, exemplar_trace_id=None):
    """A batch of wire-stage intervals in ONE registry lock hold
    (samples: [(stage, seconds)] with stage in frontdoor.WIRE_STAGES) —
    the event-loop door flushes a whole reactor tick's stage observes
    through here instead of one record_frontdoor_stage round-trip per
    interval.  The prebound observer memoizes per-stage row keys.
    Guarded like record_stage."""
    global _FRONTDOOR_STAGE_OBS
    try:
        obs = _FRONTDOOR_STAGE_OBS
        if obs is None:
            obs = _FRONTDOOR_STAGE_OBS = _global().observer(
                FRONTDOOR_STAGE_M, "stage")
        obs(samples, exemplar_trace_id=exemplar_trace_id)
    except Exception:  # telemetry never blocks the wire path
        record_dropped("record_frontdoor_stages")


def record_frontdoor_requests(counts):
    """Tick-batched request outcomes from the event-loop door: counts
    maps (outcome, backend) -> n, flushed once per reactor tick so the
    hot path pays a dict increment instead of a registry lock per
    request.  Guarded like record_stage."""
    try:
        reg = _global()
        for (outcome, backend), n in counts.items():
            reg.record(
                FRONTDOOR_REQS_M, 1.0,
                {"outcome": outcome, "backend": backend}, count=n,
            )
    except Exception:  # telemetry never blocks the wire path
        record_dropped("record_frontdoor_requests")


def record_frontdoor_request(outcome: str, backend: str):
    """One request through the front door: outcome in (ok,
    backend_error, no_backend, bad_request); backend = the serving
    replica id ('' when none answered).  Guarded like record_stage."""
    try:
        _global().record(
            FRONTDOOR_REQS_M, 1.0,
            {"outcome": outcome, "backend": backend},
        )
    except Exception:  # telemetry never blocks the wire path
        record_dropped("record_frontdoor_request")


def record_scrape(replica_id: str, ok: bool, age_s: float):
    """One federated-scrape health sample for one replica exporter
    (obs/fleetobs.py): ok flag + staleness age.  Guarded like
    record_stage."""
    try:
        reg = _global()
        tags = {"replica_id": replica_id}
        reg.record(FLEET_SCRAPE_OK_M, 1.0 if ok else 0.0, tags)
        reg.record(FLEET_SCRAPE_AGE_M, float(age_s), tags)
    except Exception:  # telemetry never blocks the scrape
        record_dropped("record_scrape")


def record_fleet_rollup(replicas_scraped: int, admission_requests: float):
    """The federator's per-pass fleet rollups.  Guarded like
    record_stage."""
    try:
        reg = _global()
        reg.record(FLEET_SCRAPED_M, float(replicas_scraped))
        reg.record(FLEET_ADMISSIONS_M, float(admission_requests))
    except Exception:  # telemetry never blocks the scrape
        record_dropped("record_fleet_rollup")


def record_profiler(samples: int, overflow: int = 0):
    """One profiler tick's accounting: samples collected + samples
    dropped on the unique-stack bound.  Guarded like record_stage."""
    try:
        reg = _global()
        if samples > 0:
            reg.record(PROFILER_SAMPLES_M, float(samples), count=samples)
        if overflow > 0:
            reg.record(PROFILER_OVERFLOW_M, float(overflow),
                       count=overflow)
    except Exception:  # telemetry never blocks the sampler
        record_dropped("record_profiler")


def record_shed(reason: str, n: int = 1):
    """n requests refused by the overload plane for one reason
    (shed_total{reason}; docs/failure-modes.md shed order).  Also feeds
    the brownout controller's shed-rate signal.  Guarded like
    record_stage."""
    if n <= 0:
        return
    try:
        _global().record(SHED_M, float(n), {"reason": reason}, count=n)
    except Exception:  # telemetry never blocks the shed path
        record_dropped("record_shed")
    try:
        from ..obs.brownout import note_shed

        note_shed(n)
    except Exception:  # the ladder signal must never fail the refusal
        record_dropped("record_shed.brownout")
    try:
        from ..obs import flightrec

        flightrec.note_shed(reason, n)  # coalesced into burst events
    except Exception:  # the recorder must never fail the refusal
        record_dropped("record_shed.flightrec")


def record_brownout_level(level: int):
    """The brownout controller's current ladder level (recorded on every
    transition and on controller start)."""
    try:
        _global().record(BROWNOUT_M, float(level))
    except Exception:  # telemetry never blocks degradation
        record_dropped("record_brownout_level")


def record_retry_budget(tokens: float):
    """The front door's current retry-budget bucket level."""
    try:
        _global().record(RETRY_TOKENS_M, float(tokens))
    except Exception:  # telemetry never blocks the wire path
        record_dropped("record_retry_budget")


def record_retry_denied():
    """One front-door retry denied on an empty retry budget."""
    try:
        _global().record(RETRY_DENIED_M, 1.0)
    except Exception:  # telemetry never blocks the wire path
        record_dropped("record_retry_denied")


def record_route_decision(tier: str, reason: str):
    """One routing decision (route_decisions_total{tier,reason}; fed per
    batch by obs/routeledger.py).  Guarded like record_stage."""
    try:
        _global().record(
            ROUTE_DECISIONS_M, 1.0, {"tier": tier, "reason": reason}
        )
    except Exception:  # telemetry never blocks eval
        record_dropped("record_route_decision")


def set_join_plans(n: int):
    """Active referential join plans (join_plans gauge; set when the
    driver's join index syncs, ops/joinkernel.py)."""
    try:
        _global().record(JOIN_PLANS_M, float(n))
    except Exception:  # telemetry never blocks a sweep
        record_dropped("set_join_plans")


def record_join_affected(rows: int):
    """Key-group reader rows co-dispatched by one delta sweep
    (join_delta_affected_rows_total)."""
    try:
        _global().record(JOIN_AFFECTED_M, float(rows), count=int(rows))
    except Exception:  # telemetry never blocks a sweep
        record_dropped("record_join_affected")


def record_join_divergence(kind: str):
    """One exact-join-plan cell the oracle refused to render
    (join_plan_divergence_total); the template kind goes to the log, not
    a label (unbounded cardinality)."""
    try:
        _global().record(JOIN_DIVERGENCE_M, 1.0)
        import logging

        logging.getLogger("gatekeeper.joinkernel").warning(
            "join-plan divergence: %s flagged a cell the interpreter "
            "renders empty", kind,
        )
    except Exception:  # telemetry never blocks rendering
        record_dropped("record_join_divergence")


def record_compile_lag(lag: int):
    """The async compiler's epoch backlog (compile_epoch_lag gauge)."""
    try:
        _global().record(COMPILE_LAG_M, float(lag))
    except Exception:  # telemetry never blocks a mutation
        record_dropped("record_compile_lag")


def record_device_bytes(component: str, nbytes: int):
    """Device-resident bytes for one placement component
    (device_bytes{component} gauge, fed by obs/compilestats.py)."""
    try:
        _global().record(
            DEVICE_BYTES_M, float(nbytes), {"component": component}
        )
    except Exception:  # telemetry never blocks a placement
        record_dropped("record_device_bytes")


def record_xla_counters_available(ok: bool):
    """Whether jax's persistent-cache monitoring counters exist on this
    build (the xlacache silent-absence contract, ops/xlacache.py)."""
    try:
        _global().record(XLA_COUNTERS_M, 1.0 if ok else 0.0)
    except Exception:  # telemetry never blocks cache setup
        record_dropped("record_xla_counters_available")


def record_decision_record(dclass: str, n: int = 1):
    """n decision records accepted by the decision log in one batch
    (decision_log_records_total{class}; obs/decisionlog.py flushes its
    hot-path counts batched)."""
    if n <= 0:
        return
    try:
        _global().record(DECISION_RECORDS_M, float(n), {"class": dclass},
                         count=n)
    except Exception:  # telemetry never blocks the verdict
        record_dropped("record_decision_record")


def record_decision_dropped(reason: str, n: int = 1):
    """n decision records not written, by reason
    (decision_log_dropped_total{reason}) — sampling, queue sheds and
    write failures are all counted drops, never silent."""
    if n <= 0:
        return
    try:
        _global().record(DECISION_DROPPED_M, float(n), {"reason": reason},
                         count=n)
    except Exception:  # telemetry never blocks the verdict
        record_dropped("record_decision_dropped")


def record_decision_segment(nbytes: int):
    """One completed decision-log segment of nbytes committed."""
    try:
        _global().record(DECISION_SEGMENTS_M, 1.0)
        _global().record(DECISION_BYTES_M, float(nbytes),
                         count=max(int(nbytes), 0))
    except Exception:  # telemetry never blocks rotation
        record_dropped("record_decision_segment")


def record_cache(cache: str, hit: bool, n: int = 1):
    """n hit/miss outcomes for one named cache (request_memo, aotcache,
    xlacache) in one lock hold.  Guarded like record_stage."""
    if n <= 0:
        return
    try:
        _global().record(
            CACHE_M, float(n),
            {"cache": cache, "outcome": "hit" if hit else "miss"},
            count=n,
        )
    except Exception:  # telemetry never blocks eval
        record_dropped("record_cache")


# ---- reactor observability plane (ISSUE 20) ---------------------------------

_EVLOOP_TICK_OBS = None
_EVLOOP_CBS_OBS = None
_EVLOOP_DRIFT_OBS = None


def record_evloop_flush(loop: str, utilization: float,
                        tick_samples, cb_samples, drift_samples):
    """One reactor telemetry flush window (obs/reactorobs.py, every
    FLUSH_S): the utilization gauge plus the window's sampled tick /
    callbacks-per-tick / timer-drift observes, each batch through a
    prebound single-tag observer so the reactor thread pays a handful
    of lock holds per window, never one per tick.  Guarded like
    record_stage."""
    global _EVLOOP_TICK_OBS, _EVLOOP_CBS_OBS, _EVLOOP_DRIFT_OBS
    try:
        reg = _global()
        reg.record(EVLOOP_UTIL_M, float(utilization), {"loop": loop})
        if tick_samples:
            obs = _EVLOOP_TICK_OBS
            if obs is None:
                obs = _EVLOOP_TICK_OBS = reg.observer(EVLOOP_TICK_M,
                                                      "loop")
            obs([(loop, s) for s in tick_samples])
        if cb_samples:
            obs = _EVLOOP_CBS_OBS
            if obs is None:
                obs = _EVLOOP_CBS_OBS = reg.observer(EVLOOP_CBS_M, "loop")
            obs([(loop, float(s)) for s in cb_samples])
        if drift_samples:
            obs = _EVLOOP_DRIFT_OBS
            if obs is None:
                obs = _EVLOOP_DRIFT_OBS = reg.observer(EVLOOP_DRIFT_M,
                                                       "loop")
            obs([(loop, s) for s in drift_samples])
    except Exception:  # telemetry never blocks the reactor
        record_dropped("record_evloop_flush")


def record_evloop_lag(loop: str, lag_s: float):
    """One heartbeat skew sample — THE loop-lag gauge (at most a few
    per second per loop, so it records directly).  Guarded like
    record_stage."""
    try:
        _global().record(EVLOOP_LAG_M, float(lag_s), {"loop": loop})
    except Exception:  # telemetry never blocks the reactor
        record_dropped("record_evloop_lag")


def record_evloop_slow_callback(loop: str, n: int = 1):
    """n reactor callbacks over the slow-callback threshold."""
    if n <= 0:
        return
    try:
        _global().record(EVLOOP_SLOW_M, float(n), {"loop": loop},
                         count=n)
    except Exception:  # telemetry never blocks the reactor
        record_dropped("record_evloop_slow_callback")


def record_evloop_stall(loop: str):
    """One watchdog-caught reactor stall (the incident counter; the
    watchdog thread also dumps the flight recorder)."""
    try:
        _global().record(EVLOOP_STALLS_M, 1.0, {"loop": loop})
    except Exception:  # telemetry never blocks the watchdog
        record_dropped("record_evloop_stall")


def record_wire_flush(end: str, counts: Dict[str, int],
                      record_samples=None):
    """One end's GKW1 wire-telemetry window (tick-batched on the
    reactor threads, flushed on the reactorobs cadence).  ``counts``
    keys: request_chunks, response_chunks, bytes_in, bytes_out,
    decode_errors (absent/zero keys skip); ``record_samples`` is
    [(kind, n_records)] feeding the chunk-batch-size histogram.
    Guarded like record_stage."""
    try:
        reg = _global()
        for key, kind in (("request_chunks", "request"),
                          ("response_chunks", "response")):
            n = int(counts.get(key, 0))
            if n > 0:
                reg.record(WIRE_CHUNKS_M, float(n),
                           {"end": end, "kind": kind}, count=n)
        for key, direction in (("bytes_in", "in"), ("bytes_out", "out")):
            n = int(counts.get(key, 0))
            if n > 0:
                reg.record(WIRE_BYTES_M, float(n),
                           {"end": end, "direction": direction}, count=n)
        n = int(counts.get("decode_errors", 0))
        if n > 0:
            reg.record(WIRE_DECODE_ERRORS_M, float(n), {"end": end},
                       count=n)
        if record_samples:
            for kind, nrec in record_samples:
                reg.record(WIRE_RECORDS_M, float(nrec),
                           {"end": end, "kind": kind})
    except Exception:  # telemetry never blocks the wire path
        record_dropped("record_wire_flush")


def record_wire_reconnect(backend: str):
    """One door-side wire-connection rebuild to a backend whose
    previous persistent connection was lost (rare; records directly)."""
    try:
        _global().record(WIRE_RECONNECTS_M, 1.0, {"backend": backend})
    except Exception:  # telemetry never blocks the wire path
        record_dropped("record_wire_reconnect")


def record_wire_backlog_stall(backend: str, seconds: float):
    """One completed door-side write-backlog episode: the span from a
    chunk write leaving bytes buffered until the backlog drained."""
    try:
        _global().record(WIRE_BACKLOG_STALL_M, float(seconds),
                         {"backend": backend})
    except Exception:  # telemetry never blocks the wire path
        record_dropped("record_wire_backlog_stall")
