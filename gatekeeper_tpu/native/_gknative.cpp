// _gknative — C++ fast path for the host-side packing pipeline.
//
// The TPU driver's cold-path cost is JSON-dict traversal + string interning
// (gatekeeper_tpu/ops/pack.py pack_reviews, ops/columns.py extract_columns).
// Both are pure per-object loops over Python dicts; this module re-implements
// them against the CPython API, filling caller-allocated numpy buffers via
// the buffer protocol.  Semantics are pinned by differential tests against
// the Python implementations (tests/test_native.py).
//
// Interning mutates the Python Interner's own dict/list (under the GIL), so
// ids stay consistent across the C and Python paths.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int32_t ID_MISSING = -1;   // Interner.MISSING
constexpr int32_t ID_NON_STRING = -3;  // Interner.NON_STRING
constexpr int32_t UNDEF = -4;        // pack.py UNDEF

// tcode values (columns.py)
constexpr int8_t T_UNDEF = 0, T_NULL = 1, T_FALSE = 2, T_TRUE = 3,
                 T_NUM = 4, T_STR = 5, T_COMP = 6;

PyObject *g_np_empty = nullptr;   // numpy.empty
PyObject *g_sorted = nullptr;     // builtins.sorted
PyObject *g_str = nullptr;        // builtins.str

// ---- interner ------------------------------------------------------------

int32_t intern(PyObject *ids, PyObject *strings, PyObject *s) {
  PyObject *v = PyDict_GetItemWithError(ids, s);  // borrowed
  if (v) return (int32_t)PyLong_AsLong(v);
  if (PyErr_Occurred()) return ID_MISSING;  // unhashable: caller clears
  Py_ssize_t n = PyList_GET_SIZE(strings);
  PyObject *idobj = PyLong_FromSsize_t(n);
  if (!idobj) return ID_MISSING;
  if (PyDict_SetItem(ids, s, idobj) < 0) {
    Py_DECREF(idobj);
    return ID_MISSING;
  }
  Py_DECREF(idobj);
  if (PyList_Append(strings, s) < 0) return ID_MISSING;
  return (int32_t)n;
}

int32_t intern_value(PyObject *ids, PyObject *strings, PyObject *v) {
  if (v && PyUnicode_Check(v)) return intern(ids, strings, v);
  return ID_NON_STRING;
}

// ---- get_default semantics (target/match.py _get) ------------------------
// missing key or None -> nullptr ("missing"); non-dict container -> missing.

PyObject *get_field(PyObject *obj, const char *field) {  // borrowed or null
  if (!obj || !PyDict_Check(obj)) return nullptr;
  PyObject *v = PyDict_GetItemString(obj, field);
  if (!v || v == Py_None) return nullptr;
  return v;
}

bool is_ns_kind(PyObject *kind) {
  if (!kind || !PyDict_Check(kind)) return false;
  PyObject *g = PyDict_GetItemString(kind, "group");
  PyObject *k = PyDict_GetItemString(kind, "kind");
  if (!g || !k || !PyUnicode_Check(g) || !PyUnicode_Check(k)) return false;
  return PyUnicode_GetLength(g) == 0 &&
         PyUnicode_CompareWithASCIIString(k, "Namespace") == 0;
}

// ---- buffer helpers ------------------------------------------------------

struct Buf {
  Py_buffer view{};
  bool ok = false;
  ~Buf() {
    if (ok) PyBuffer_Release(&view);
  }
  bool acquire(PyObject *obj) {
    if (PyObject_GetBuffer(obj, &view, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) <
        0)
      return false;
    ok = true;
    return true;
  }
  int32_t *i32() { return static_cast<int32_t *>(view.buf); }
  int8_t *i8() { return static_cast<int8_t *>(view.buf); }
  double *f64() { return static_cast<double *>(view.buf); }
  bool *b() { return static_cast<bool *>(view.buf); }
};

// allocate a 1-D/2-D int32 numpy array via numpy.empty
PyObject *np_empty_i32(Py_ssize_t a, Py_ssize_t b = -1) {
  PyObject *shape =
      (b < 0) ? Py_BuildValue("(n)", a) : Py_BuildValue("(nn)", a, b);
  if (!shape) return nullptr;
  PyObject *arr = PyObject_CallFunction(g_np_empty, "Os", shape, "int32");
  Py_DECREF(shape);
  return arr;
}

bool fill_i32(PyObject *arr, const std::vector<int32_t> &vals) {
  Buf buf;
  if (!buf.acquire(arr)) return false;
  std::memcpy(buf.view.buf, vals.data(), vals.size() * sizeof(int32_t));
  return true;
}

// ---- label interning (pack.py _intern_labels) ----------------------------
// appends (key_id, value_id) pairs sorted by str(key)

void intern_labels(PyObject *ids, PyObject *strings, PyObject *labels,
                   std::vector<int32_t> &out) {
  if (!labels || !PyDict_Check(labels)) return;
  PyObject *keys = PyDict_Keys(labels);
  if (!keys) {
    PyErr_Clear();
    return;
  }
  bool all_str = true;
  Py_ssize_t n = PyList_GET_SIZE(keys);
  for (Py_ssize_t i = 0; i < n; i++) {
    if (!PyUnicode_Check(PyList_GET_ITEM(keys, i))) {
      all_str = false;
      break;
    }
  }
  if (all_str) {
    if (PyList_Sort(keys) < 0) PyErr_Clear();
  } else {
    // rare: mirror sorted(keys, key=str)
    PyObject *kw = PyDict_New();
    PyDict_SetItemString(kw, "key", g_str);
    PyObject *args = PyTuple_Pack(1, keys);
    PyObject *srt = PyObject_Call(g_sorted, args, kw);
    Py_DECREF(args);
    Py_DECREF(kw);
    if (srt) {
      Py_DECREF(keys);
      keys = srt;
      n = PyList_GET_SIZE(keys);
    } else {
      PyErr_Clear();
    }
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *k = PyList_GET_ITEM(keys, i);
    PyObject *v = PyDict_GetItemWithError(labels, k);
    if (!v) {
      PyErr_Clear();
      continue;
    }
    out.push_back(intern_value(ids, strings, k));
    out.push_back(intern_value(ids, strings, v));
  }
  Py_DECREF(keys);
}

// ==========================================================================
// pack_reviews_core
// ==========================================================================
//
// Args: reviews(list), ids(dict), strings(list), cached_ns(callable),
//       dict of preallocated 1-D buffers:
//         group,kind,ns_name: int32[rows]; ns_mode: int8[rows];
//         always,ns_empty,is_ns,obj_empty,old_empty,autoreject,valid: bool[rows]
// Returns: (obj_flat[int32 N,2], obj_counts[int32 n],
//           old_flat, old_counts, ns_flat, ns_counts)

PyObject *pack_reviews_core(PyObject *, PyObject *args) {
  PyObject *reviews, *ids, *strings, *cached_ns, *bufs;
  if (!PyArg_ParseTuple(args, "OOOOO", &reviews, &ids, &strings, &cached_ns,
                        &bufs))
    return nullptr;
  if (!PyList_Check(reviews) || !PyDict_Check(ids) || !PyList_Check(strings) ||
      !PyDict_Check(bufs)) {
    PyErr_SetString(PyExc_TypeError, "bad argument types");
    return nullptr;
  }

  Buf group, kind, ns_name, ns_mode, always, ns_empty, is_ns, obj_empty,
      old_empty, autoreject, valid;
  struct {
    const char *name;
    Buf *buf;
  } needed[] = {
      {"group", &group},         {"kind", &kind},
      {"ns_name", &ns_name},     {"ns_mode", &ns_mode},
      {"always", &always},       {"ns_empty", &ns_empty},
      {"is_ns", &is_ns},         {"obj_empty", &obj_empty},
      {"old_empty", &old_empty}, {"autoreject", &autoreject},
      {"valid", &valid},
  };
  for (auto &nb : needed) {
    PyObject *o = PyDict_GetItemString(bufs, nb.name);
    if (!o || !nb.buf->acquire(o)) {
      PyErr_Format(PyExc_ValueError, "missing/bad buffer %s", nb.name);
      return nullptr;
    }
  }

  Py_ssize_t n = PyList_GET_SIZE(reviews);
  std::vector<int32_t> obj_flat, old_flat, nsl_flat;
  std::vector<int32_t> obj_counts(n), old_counts(n), ns_counts(n);

  // memoized cached_namespace lookups for this batch
  PyObject *ns_memo = PyDict_New();
  if (!ns_memo) return nullptr;

  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *review = PyList_GET_ITEM(reviews, i);
    valid.b()[i] = true;

    PyObject *rkind_raw =
        PyDict_Check(review) ? PyDict_GetItemString(review, "kind") : nullptr;
    PyObject *rkind =
        (rkind_raw && PyDict_Check(rkind_raw)) ? rkind_raw : nullptr;
    // NOTE pack.py uses rkind.get("group", _MISSING) — plain get, null is
    // a value here (intern_value -> NON_STRING), matching the original
    PyObject *g = rkind ? PyDict_GetItemString(rkind, "group") : nullptr;
    PyObject *k = rkind ? PyDict_GetItemString(rkind, "kind") : nullptr;
    group.i32()[i] = g ? intern_value(ids, strings, g) : UNDEF;
    kind.i32()[i] = k ? intern_value(ids, strings, k) : UNDEF;

    bool isns = is_ns_kind(rkind_raw);
    is_ns.b()[i] = isns;

    PyObject *ns = get_field(review, "namespace");  // _get default ""
    bool nsempty = !ns || (PyUnicode_Check(ns) && PyUnicode_GetLength(ns) == 0);
    // _get(review,"namespace","") returns "" for missing; == "" only for
    // string empties; non-string namespace -> not empty
    if (ns && !PyUnicode_Check(ns)) nsempty = false;
    ns_empty.b()[i] = nsempty;
    bool alw = !isns && nsempty;
    always.b()[i] = alw;

    // get_ns_name
    if (isns) {
      PyObject *obj = get_field(review, "object");
      PyObject *meta = obj ? get_field(obj, "metadata") : nullptr;
      PyObject *nm = meta ? get_field(meta, "name") : nullptr;
      ns_name.i32()[i] = nm ? intern_value(ids, strings, nm) : UNDEF;
    } else {
      ns_name.i32()[i] = ns ? intern_value(ids, strings, ns) : UNDEF;
    }

    PyObject *obj = get_field(review, "object");
    PyObject *old = get_field(review, "oldObject");
    obj_empty.b()[i] =
        !obj || (PyDict_Check(obj) && PyDict_GET_SIZE(obj) == 0);
    old_empty.b()[i] =
        !old || (PyDict_Check(old) && PyDict_GET_SIZE(old) == 0);

    size_t before = obj_flat.size();
    PyObject *ometa = obj ? get_field(obj, "metadata") : nullptr;
    intern_labels(ids, strings, ometa ? get_field(ometa, "labels") : nullptr,
                  obj_flat);
    obj_counts[i] = (int32_t)((obj_flat.size() - before) / 2);

    before = old_flat.size();
    PyObject *olmeta = old ? get_field(old, "metadata") : nullptr;
    intern_labels(ids, strings, olmeta ? get_field(olmeta, "labels") : nullptr,
                  old_flat);
    old_counts[i] = (int32_t)((old_flat.size() - before) / 2);

    // namespaceSelector resolution mode + ns labels
    before = nsl_flat.size();
    int8_t mode;
    PyObject *resolved_ns = nullptr;  // new reference when set
    if (isns) {
      mode = 3;
    } else if (alw) {
      mode = 0;
    } else {
      PyObject *unstable = get_field(review, "_unstable");
      PyObject *uns = unstable ? get_field(unstable, "namespace") : nullptr;
      if (uns) {
        resolved_ns = uns;
        Py_INCREF(resolved_ns);
      } else if (ns && PyUnicode_Check(ns)) {
        PyObject *memo = PyDict_GetItemWithError(ns_memo, ns);
        if (memo) {
          resolved_ns = memo;
          Py_INCREF(resolved_ns);
        } else {
          PyErr_Clear();
          resolved_ns = PyObject_CallFunctionObjArgs(cached_ns, ns, nullptr);
          if (!resolved_ns) {
            Py_DECREF(ns_memo);
            return nullptr;
          }
          PyDict_SetItem(ns_memo, ns, resolved_ns);
        }
        if (resolved_ns == Py_None) {
          Py_DECREF(resolved_ns);
          resolved_ns = nullptr;
        }
      }
      if (!resolved_ns) {
        mode = 2;
      } else {
        mode = 1;
        PyObject *nmeta = get_field(resolved_ns, "metadata");
        intern_labels(ids, strings,
                      nmeta ? get_field(nmeta, "labels") : nullptr, nsl_flat);
      }
    }
    Py_XDECREF(resolved_ns);
    ns_mode.i8()[i] = mode;
    ns_counts[i] = (int32_t)((nsl_flat.size() - before) / 2);

    // needs_autoreject for a namespaceSelector constraint (match.py:236):
    bool rejects = true;
    PyObject *nsv =
        PyDict_Check(review) ? PyDict_GetItemString(review, "namespace")
                             : nullptr;
    PyObject *ns_str =
        (nsv && nsv != Py_None && PyUnicode_Check(nsv)) ? nsv : nullptr;
    // treat null like _get: None -> missing
    if (nsv == Py_None) ns_str = nullptr;
    if (ns_str) {
      PyObject *memo = PyDict_GetItemWithError(ns_memo, ns_str);
      PyObject *cached;
      if (memo) {
        cached = memo;
        Py_INCREF(cached);
      } else {
        PyErr_Clear();
        cached = PyObject_CallFunctionObjArgs(cached_ns, ns_str, nullptr);
        if (!cached) {
          Py_DECREF(ns_memo);
          return nullptr;
        }
        PyDict_SetItem(ns_memo, ns_str, cached);
      }
      if (cached != Py_None) rejects = false;
      Py_DECREF(cached);
    }
    if (rejects) {
      PyObject *unstable = review && PyDict_Check(review)
                               ? PyDict_GetItemString(review, "_unstable")
                               : nullptr;
      if (unstable && PyDict_Check(unstable)) {
        PyObject *uv = PyDict_GetItemString(unstable, "namespace");
        if (uv && uv != Py_False) rejects = false;
      }
    }
    if (rejects && ns_str && PyUnicode_GetLength(ns_str) == 0) rejects = false;
    autoreject.b()[i] = rejects;
  }
  Py_DECREF(ns_memo);

  PyObject *ret = PyTuple_New(6);
  struct {
    std::vector<int32_t> *flat;
    std::vector<int32_t> *counts;
  } outs[] = {{&obj_flat, &obj_counts},
              {&old_flat, &old_counts},
              {&nsl_flat, &ns_counts}};
  for (int j = 0; j < 3; j++) {
    PyObject *flat_arr =
        np_empty_i32((Py_ssize_t)outs[j].flat->size() / 2, 2);
    PyObject *counts_arr = np_empty_i32(n);
    if (!flat_arr || !counts_arr || !fill_i32(flat_arr, *outs[j].flat) ||
        !fill_i32(counts_arr, *outs[j].counts)) {
      Py_XDECREF(flat_arr);
      Py_XDECREF(counts_arr);
      Py_DECREF(ret);
      return nullptr;
    }
    PyTuple_SET_ITEM(ret, j * 2, flat_arr);
    PyTuple_SET_ITEM(ret, j * 2 + 1, counts_arr);
  }
  return ret;
}

// ==========================================================================
// extract_columns cores
// ==========================================================================

// walk(obj, path, i): collect values at path; "[]" iterates lists
void walk(PyObject *obj, PyObject *path, Py_ssize_t i,
          std::vector<PyObject *> &out) {  // borrowed refs out
  Py_ssize_t plen = PyTuple_GET_SIZE(path);
  if (i == plen) {
    out.push_back(obj);
    return;
  }
  PyObject *seg = PyTuple_GET_ITEM(path, i);
  if (PyUnicode_CompareWithASCIIString(seg, "[]") == 0) {
    if (PyList_Check(obj)) {
      Py_ssize_t n = PyList_GET_SIZE(obj);
      for (Py_ssize_t j = 0; j < n; j++)
        walk(PyList_GET_ITEM(obj, j), path, i + 1, out);
    }
    return;
  }
  if (PyDict_Check(obj)) {
    PyObject *v = PyDict_GetItemWithError(obj, seg);
    if (!v) {
      PyErr_Clear();
      return;
    }
    walk(v, path, i + 1, out);
  }
}

// _get_rel: []-free path; nullptr = absent (missing key only; None is a value)
PyObject *get_rel(PyObject *obj, PyObject *path) {
  PyObject *cur = obj;
  Py_ssize_t plen = PyTuple_GET_SIZE(path);
  for (Py_ssize_t i = 0; i < plen; i++) {
    if (!PyDict_Check(cur)) return nullptr;
    PyObject *v = PyDict_GetItemWithError(cur, PyTuple_GET_ITEM(path, i));
    if (!v) {
      PyErr_Clear();
      return nullptr;
    }
    cur = v;
  }
  return cur;
}

// encode one value into tcode/sid/num at index idx (columns.py _encode)
void encode_at(PyObject *v, Py_ssize_t idx, int8_t *tcode, int32_t *sid,
               double *num, PyObject *ids, PyObject *strings) {
  if (!v) {
    tcode[idx] = T_UNDEF;
  } else if (v == Py_None) {
    tcode[idx] = T_NULL;
  } else if (v == Py_True) {
    tcode[idx] = T_TRUE;
  } else if (v == Py_False) {
    tcode[idx] = T_FALSE;
  } else if (PyUnicode_Check(v)) {
    tcode[idx] = T_STR;
    sid[idx] = intern(ids, strings, v);
  } else if (PyLong_Check(v) || PyFloat_Check(v)) {
    tcode[idx] = T_NUM;
    num[idx] = PyFloat_Check(v) ? PyFloat_AS_DOUBLE(v)
                                : PyLong_AsDouble(v);
    if (PyErr_Occurred()) {  // int beyond double range
      PyErr_Clear();
      num[idx] = HUGE_VAL;
    }
  } else {
    tcode[idx] = T_COMP;
  }
}

// extract_scalar(resources, path, tcode_buf, sid_buf, num_buf, ids, strings)
PyObject *extract_scalar(PyObject *, PyObject *args) {
  PyObject *resources, *path, *tc, *si, *nu, *ids, *strings;
  if (!PyArg_ParseTuple(args, "OOOOOOO", &resources, &path, &tc, &si, &nu,
                        &ids, &strings))
    return nullptr;
  Buf tcode, sid, num;
  if (!tcode.acquire(tc) || !sid.acquire(si) || !num.acquire(nu))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(resources);
  std::vector<PyObject *> hits;
  for (Py_ssize_t i = 0; i < n; i++) {
    hits.clear();
    walk(PyList_GET_ITEM(resources, i), path, 0, hits);
    encode_at(hits.empty() ? nullptr : hits[0], i, tcode.i8(), sid.i32(),
              num.f64(), ids, strings);
  }
  Py_RETURN_NONE;
}

// slot_entities(resources, iter_paths) -> (list of list, max_width)
PyObject *slot_entities(PyObject *, PyObject *args) {
  PyObject *resources, *iter_paths;
  if (!PyArg_ParseTuple(args, "OO", &resources, &iter_paths)) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(resources);
  Py_ssize_t np_ = PyTuple_GET_SIZE(iter_paths);
  PyObject *ents = PyList_New(n);
  if (!ents) return nullptr;
  Py_ssize_t maxw = 0;
  std::vector<PyObject *> hits;
  for (Py_ssize_t i = 0; i < n; i++) {
    hits.clear();
    for (Py_ssize_t p = 0; p < np_; p++)
      walk(PyList_GET_ITEM(resources, i), PyTuple_GET_ITEM(iter_paths, p), 0,
           hits);
    PyObject *row = PyList_New((Py_ssize_t)hits.size());
    if (!row) {
      Py_DECREF(ents);
      return nullptr;
    }
    for (size_t j = 0; j < hits.size(); j++) {
      Py_INCREF(hits[j]);
      PyList_SET_ITEM(row, (Py_ssize_t)j, hits[j]);
    }
    PyList_SET_ITEM(ents, i, row);
    if ((Py_ssize_t)hits.size() > maxw) maxw = (Py_ssize_t)hits.size();
  }
  return Py_BuildValue("(Nn)", ents, maxw);
}

// encode_slots(entities, rel_path, width, tcode[R,W], sid, num, mask(bool),
//              ids, strings)
PyObject *encode_slots(PyObject *, PyObject *args) {
  PyObject *entities, *rel_path, *tc, *si, *nu, *ma, *ids, *strings;
  Py_ssize_t width;
  if (!PyArg_ParseTuple(args, "OOnOOOOOO", &entities, &rel_path, &width, &tc,
                        &si, &nu, &ma, &ids, &strings))
    return nullptr;
  Buf tcode, sid, num, mask;
  if (!tcode.acquire(tc) || !sid.acquire(si) || !num.acquire(nu) ||
      !mask.acquire(ma))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(entities);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *row = PyList_GET_ITEM(entities, i);
    Py_ssize_t rn = PyList_GET_SIZE(row);
    for (Py_ssize_t j = 0; j < width; j++) {
      Py_ssize_t idx = i * width + j;
      if (j < rn) {
        mask.b()[idx] = true;
        PyObject *v = PyTuple_GET_SIZE(rel_path)
                          ? get_rel(PyList_GET_ITEM(row, j), rel_path)
                          : PyList_GET_ITEM(row, j);
        encode_at(v, idx, tcode.i8(), sid.i32(), num.f64(), ids, strings);
      } else {
        encode_at(nullptr, idx, tcode.i8(), sid.i32(), num.f64(), ids,
                  strings);
      }
    }
  }
  Py_RETURN_NONE;
}

// keyset(resources, iter_paths, rel_path, exclude_set, ids, strings)
//   -> (flat int32 array, counts int32 array)
PyObject *keyset(PyObject *, PyObject *args) {
  PyObject *resources, *iter_paths, *rel_path, *exclude, *ids, *strings;
  if (!PyArg_ParseTuple(args, "OOOOOO", &resources, &iter_paths, &rel_path,
                        &exclude, &ids, &strings))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(resources);
  Py_ssize_t np_ = PyTuple_GET_SIZE(iter_paths);
  std::vector<int32_t> flat;
  std::vector<int32_t> counts(n);
  std::vector<PyObject *> hits;
  for (Py_ssize_t i = 0; i < n; i++) {
    hits.clear();
    for (Py_ssize_t p = 0; p < np_; p++)
      walk(PyList_GET_ITEM(resources, i), PyTuple_GET_ITEM(iter_paths, p), 0,
           hits);
    size_t before = flat.size();
    PyObject *seen = PySet_New(nullptr);
    if (!seen) return nullptr;
    for (PyObject *h : hits) {
      PyObject *target =
          PyTuple_GET_SIZE(rel_path) ? get_rel(h, rel_path) : h;
      if (!target || !PyDict_Check(target)) continue;
      PyObject *k, *v;
      Py_ssize_t pos = 0;
      while (PyDict_Next(target, &pos, &k, &v)) {
        if (!PyUnicode_Check(k) || v == Py_False) continue;
        int ex = PySequence_Contains(exclude, k);
        if (ex != 0) {
          if (ex < 0) PyErr_Clear();
          continue;
        }
        int sn = PySet_Contains(seen, k);
        if (sn != 0) {
          if (sn < 0) PyErr_Clear();
          continue;
        }
        PySet_Add(seen, k);
        flat.push_back(intern(ids, strings, k));
      }
    }
    Py_DECREF(seen);
    counts[i] = (int32_t)(flat.size() - before);
  }
  PyObject *flat_arr = np_empty_i32((Py_ssize_t)flat.size());
  PyObject *counts_arr = np_empty_i32(n);
  if (!flat_arr || !counts_arr || !fill_i32(flat_arr, flat) ||
      !fill_i32(counts_arr, counts)) {
    Py_XDECREF(flat_arr);
    Py_XDECREF(counts_arr);
    return nullptr;
  }
  return Py_BuildValue("(NN)", flat_arr, counts_arr);
}

// ---- freeze: JSON-like tree -> frozen Rego value --------------------------
//
// The profiled cold-start cost of data ingestion is engine/value.py
// freeze(): a recursive Python walk over every K8s object.  This C walk
// builds the SAME Python value types (tuples; FrozenDict/RSet instances
// constructed through the classes registered by freeze_init), so
// isinstance checks, hashing, and equality behave identically; parity is
// pinned by tests/test_native.py differential cases.

PyObject *g_frozendict_cls = nullptr;
PyObject *g_rset_cls = nullptr;

PyObject *freeze_rec_guarded(PyObject *v);

PyObject *freeze_rec(PyObject *v);

// snapshot an iterable's items and freeze each into a fresh tuple; shared
// by the list/tuple, set, and RSet branches
PyObject *freeze_items_tuple(PyObject *iterable) {
  PyObject *snap = PySequence_Tuple(iterable);
  if (!snap) return nullptr;
  Py_ssize_t n = PyTuple_GET_SIZE(snap);
  PyObject *out = PyTuple_New(n);
  if (!out) {
    Py_DECREF(snap);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *f = freeze_rec(PyTuple_GET_ITEM(snap, i));
    if (!f) {
      Py_DECREF(snap);
      Py_DECREF(out);
      return nullptr;
    }
    PyTuple_SET_ITEM(out, i, f);
  }
  Py_DECREF(snap);
  return out;
}

// snapshot a dict's items and deep-freeze into a new FrozenDict; iterating
// a live dict across Python re-entry is unsafe under mutation
PyObject *freeze_dict_items(PyObject *d) {
  PyObject *items = PyDict_Items(d);
  if (!items) return nullptr;
  PyObject *inner = PyDict_New();
  if (!inner) {
    Py_DECREF(items);
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(items);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *pair = PyList_GET_ITEM(items, i);
    PyObject *fk = freeze_rec(PyTuple_GET_ITEM(pair, 0));
    if (!fk) {
      Py_DECREF(items);
      Py_DECREF(inner);
      return nullptr;
    }
    PyObject *fv = freeze_rec(PyTuple_GET_ITEM(pair, 1));
    if (!fv) {
      Py_DECREF(fk);
      Py_DECREF(items);
      Py_DECREF(inner);
      return nullptr;
    }
    int rc = PyDict_SetItem(inner, fk, fv);
    Py_DECREF(fk);
    Py_DECREF(fv);
    if (rc < 0) {
      Py_DECREF(items);
      Py_DECREF(inner);
      return nullptr;
    }
  }
  Py_DECREF(items);
  PyObject *out = PyObject_CallOneArg(g_frozendict_cls, inner);
  Py_DECREF(inner);
  return out;
}

PyObject *freeze_rec(PyObject *v) {
  // per-level recursion guard: arbitrarily deep user JSON must raise
  // RecursionError, not smash the C stack
  if (Py_EnterRecursiveCall(" in freeze")) return nullptr;
  PyObject *out = freeze_rec_guarded(v);
  Py_LeaveRecursiveCall();
  return out;
}

PyObject *freeze_rec_guarded(PyObject *v) {
  if (v == Py_None || PyBool_Check(v) || PyUnicode_Check(v)) {
    Py_INCREF(v);
    return v;
  }
  if (PyFloat_Check(v)) {
    double d = PyFloat_AS_DOUBLE(v);
    // canonicalize integral floats (JSON "1.0") to ints like value.py
    if (std::isfinite(d) && d == std::floor(d)) return PyLong_FromDouble(d);
    Py_INCREF(v);
    return v;
  }
  if (PyLong_Check(v)) {
    Py_INCREF(v);
    return v;
  }
  if (PyList_Check(v) || PyTuple_Check(v)) {
    // snapshot-before-iterate: freezing nested dicts calls back into
    // Python, which may release the eval lock to a thread mutating this
    // very list — a cached item pointer would dangle
    return freeze_items_tuple(v);
  }
  if (PyDict_Check(v)) {
    // hot path first: plain dicts dominate K8s-object input; the generic
    // isinstance checks below only matter for the rare frozen inputs
    return freeze_dict_items(v);
  }
  // frozen containers are REBUILT like the Python oracle does: a
  // FrozenDict constructed directly around raw values must come out
  // deep-frozen, not passed through with mutables inside
  int is_fd = PyObject_IsInstance(v, g_frozendict_cls);
  if (is_fd < 0) return nullptr;
  int is_rs = is_fd ? 0 : PyObject_IsInstance(v, g_rset_cls);
  if (is_rs < 0) return nullptr;
  if (is_fd) {
    PyObject *d = PyObject_GetAttrString(v, "_d");
    if (!d) return nullptr;
    PyObject *out = freeze_dict_items(d);
    Py_DECREF(d);
    return out;
  }
  if (is_rs) {
    PyObject *s = PyObject_GetAttrString(v, "_s");
    if (!s) return nullptr;
    PyObject *frozen = freeze_items_tuple(s);
    Py_DECREF(s);
    if (!frozen) return nullptr;
    PyObject *out = PyObject_CallOneArg(g_rset_cls, frozen);
    Py_DECREF(frozen);
    return out;
  }
  if (PyAnySet_Check(v)) {
    PyObject *frozen = freeze_items_tuple(v);
    if (!frozen) return nullptr;
    PyObject *out = PyObject_CallOneArg(g_rset_cls, frozen);
    Py_DECREF(frozen);
    return out;
  }
  PyErr_Format(PyExc_TypeError, "cannot freeze %R", (PyObject *)Py_TYPE(v));
  return nullptr;
}

// --------------------------------------------------------------------------
// thaw_core: frozen Rego value -> plain JSON-able Python value
// (engine/value.py thaw).  The audit pack rebuild thaws every cached
// object on a cold start — pure-Python recursion was ~3s per 20k pods.
// Iteration order matches the Python oracle exactly: FrozenDict.items()
// and RSet.sorted_items() yield canonical (compare-sorted) order, so the
// produced dicts/lists are byte-identical in serialization.
// --------------------------------------------------------------------------

PyObject *thaw_rec(PyObject *v);

PyObject *thaw_rec_guarded(PyObject *v) {
  if (v == Py_None || PyBool_Check(v) || PyLong_Check(v) ||
      PyFloat_Check(v) || PyUnicode_Check(v)) {
    Py_INCREF(v);
    return v;
  }
  if (PyTuple_Check(v)) {
    Py_ssize_t n = PyTuple_GET_SIZE(v);
    PyObject *out = PyList_New(n);
    if (!out) return nullptr;
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *item = thaw_rec(PyTuple_GET_ITEM(v, i));
      if (!item) {
        Py_DECREF(out);
        return nullptr;
      }
      PyList_SET_ITEM(out, i, item);
    }
    return out;
  }
  int is_fd = PyObject_IsInstance(v, g_frozendict_cls);
  if (is_fd < 0) return nullptr;
  if (is_fd) {
    // all-string-key fast path (the overwhelming K8s-object shape):
    // OPA's compare() on strings is plain lexicographic order, so a
    // native unicode sort of _d's keys reproduces items()' canonical
    // order without the Python sort machinery
    PyObject *d = PyObject_GetAttrString(v, "_d");
    if (d && PyDict_Check(d)) {
      bool all_str = true;
      PyObject *key, *val;
      Py_ssize_t pos = 0;
      while (PyDict_Next(d, &pos, &key, &val)) {
        if (!PyUnicode_Check(key)) {
          all_str = false;
          break;
        }
      }
      if (all_str) {
        PyObject *keys = PyDict_Keys(d);
        if (!keys || PyList_Sort(keys) < 0) {
          Py_XDECREF(keys);
          Py_DECREF(d);
          return nullptr;
        }
        PyObject *out = PyDict_New();
        if (!out) {
          Py_DECREF(keys);
          Py_DECREF(d);
          return nullptr;
        }
        Py_ssize_t n = PyList_GET_SIZE(keys);
        for (Py_ssize_t i = 0; i < n; i++) {
          PyObject *k = PyList_GET_ITEM(keys, i);
          PyObject *dv = PyDict_GetItem(d, k);  // borrowed
          PyObject *tv = dv ? thaw_rec(dv) : nullptr;
          int rc = tv ? PyDict_SetItem(out, k, tv) : -1;
          Py_XDECREF(tv);
          if (rc < 0) {
            Py_DECREF(keys);
            Py_DECREF(d);
            Py_DECREF(out);
            return nullptr;
          }
        }
        Py_DECREF(keys);
        Py_DECREF(d);
        return out;
      }
    }
    Py_XDECREF(d);
    if (PyErr_Occurred()) return nullptr;
    // items() yields canonical sorted order (cached on the FrozenDict)
    PyObject *items = PyObject_CallMethod(v, "items", nullptr);
    if (!items) return nullptr;
    PyObject *out = PyDict_New();
    if (!out) {
      Py_DECREF(items);
      return nullptr;
    }
    PyObject *it = PyObject_GetIter(items);
    Py_DECREF(items);
    if (!it) {
      Py_DECREF(out);
      return nullptr;
    }
    PyObject *pair;
    while ((pair = PyIter_Next(it)) != nullptr) {
      PyObject *tk = thaw_rec(PyTuple_GET_ITEM(pair, 0));
      PyObject *tv = tk ? thaw_rec(PyTuple_GET_ITEM(pair, 1)) : nullptr;
      int rc = (tk && tv) ? PyDict_SetItem(out, tk, tv) : -1;
      Py_XDECREF(tk);
      Py_XDECREF(tv);
      Py_DECREF(pair);
      if (rc < 0) {
        Py_DECREF(it);
        Py_DECREF(out);
        return nullptr;
      }
    }
    Py_DECREF(it);
    if (PyErr_Occurred()) {
      Py_DECREF(out);
      return nullptr;
    }
    return out;
  }
  int is_rs = PyObject_IsInstance(v, g_rset_cls);
  if (is_rs < 0) return nullptr;
  if (is_rs) {
    PyObject *sorted_items = PyObject_CallMethod(v, "sorted_items", nullptr);
    if (!sorted_items) return nullptr;
    Py_ssize_t n = PyList_Check(sorted_items)
                       ? PyList_GET_SIZE(sorted_items)
                       : -1;
    if (n < 0) {
      Py_DECREF(sorted_items);
      PyErr_SetString(PyExc_TypeError, "sorted_items did not return a list");
      return nullptr;
    }
    PyObject *out = PyList_New(n);
    if (!out) {
      Py_DECREF(sorted_items);
      return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *item = thaw_rec(PyList_GET_ITEM(sorted_items, i));
      if (!item) {
        Py_DECREF(sorted_items);
        Py_DECREF(out);
        return nullptr;
      }
      PyList_SET_ITEM(out, i, item);
    }
    Py_DECREF(sorted_items);
    return out;
  }
  PyErr_Format(PyExc_TypeError, "cannot thaw %R", (PyObject *)Py_TYPE(v));
  return nullptr;
}

PyObject *thaw_rec(PyObject *v) {
  if (Py_EnterRecursiveCall(" in thaw")) return nullptr;
  PyObject *out = thaw_rec_guarded(v);
  Py_LeaveRecursiveCall();
  return out;
}

PyObject *thaw_core(PyObject *, PyObject *arg) {
  if (!g_frozendict_cls || !g_rset_cls) {
    PyErr_SetString(PyExc_RuntimeError, "freeze_init not called");
    return nullptr;
  }
  return thaw_rec(arg);
}

PyObject *freeze_init(PyObject *, PyObject *args) {
  PyObject *fd, *rs;
  if (!PyArg_ParseTuple(args, "OO", &fd, &rs)) return nullptr;
  Py_XDECREF(g_frozendict_cls);
  Py_XDECREF(g_rset_cls);
  Py_INCREF(fd);
  Py_INCREF(rs);
  g_frozendict_cls = fd;
  g_rset_cls = rs;
  Py_RETURN_NONE;
}

PyObject *freeze_core(PyObject *, PyObject *arg) {
  if (!g_frozendict_cls || !g_rset_cls) {
    PyErr_SetString(PyExc_RuntimeError, "freeze_init not called");
    return nullptr;
  }
  return freeze_rec(arg);  // freeze_rec guards every recursion level
}

PyMethodDef methods[] = {
    {"freeze_init", freeze_init, METH_VARARGS,
     "register the FrozenDict and RSet classes"},
    {"freeze_core", freeze_core, METH_O,
     "JSON-like tree -> frozen Rego value (engine/value.py freeze)"},
    {"thaw_core", thaw_core, METH_O,
     "frozen Rego value -> plain JSON-able value (engine/value.py thaw)"},
    {"pack_reviews_core", pack_reviews_core, METH_VARARGS,
     "fill review-side fixed buffers; returns label pair flats+counts"},
    {"extract_scalar", extract_scalar, METH_VARARGS,
     "encode first-hit path values into tcode/sid/num buffers"},
    {"slot_entities", slot_entities, METH_VARARGS,
     "collect iteration-path entities per resource"},
    {"encode_slots", encode_slots, METH_VARARGS,
     "encode per-entity rel-path values into padded buffers"},
    {"keyset", keyset, METH_VARARGS,
     "interned truthy keys at paths, dedup, minus exclusions"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_gknative",
                         "C++ packing fast path", -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit__gknative(void) {
  PyObject *np = PyImport_ImportModule("numpy");
  if (!np) return nullptr;
  g_np_empty = PyObject_GetAttrString(np, "empty");
  Py_DECREF(np);
  if (!g_np_empty) return nullptr;
  PyObject *builtins = PyImport_ImportModule("builtins");
  if (!builtins) return nullptr;
  g_sorted = PyObject_GetAttrString(builtins, "sorted");
  g_str = PyObject_GetAttrString(builtins, "str");
  Py_DECREF(builtins);
  if (!g_sorted || !g_str) return nullptr;
  return PyModule_Create(&moduledef);
}
