"""Native (C++) packing fast path — build-on-first-use loader.

The extension accelerates the host-side ingest pipeline (review packing and
columnar extraction, the profiled cold-path cost of a device sweep).  It is
OPTIONAL: every consumer keeps the pure-Python implementation both as the
fallback and as the differential-test oracle (tests/test_native.py).

Set GK_NATIVE=0 to force the Python path; GK_NATIVE=require to fail hard
when the extension can't be built (CI lane for the native path).
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading

_lock = threading.Lock()
_mod = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "_gknative.cpp")


def _so_path() -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(os.path.dirname(__file__), f"_gknative{suffix}")


def build(force: bool = False) -> str:
    """Compile the extension with g++; returns the .so path."""
    so = _so_path()
    if (
        not force
        and os.path.exists(so)
        and os.path.getmtime(so) >= os.path.getmtime(_SRC)
    ):
        return so
    include = sysconfig.get_paths()["include"]
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", _SRC, "-o", so,
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return so


def load():
    """The extension module, or None if unavailable/disabled."""
    global _mod, _tried
    if _mod is not None:
        return _mod
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        mode = os.environ.get("GK_NATIVE", "1")
        if mode == "0":
            return None
        try:
            # gklint: disable=blocking-under-lock -- the lock EXISTS to
            # serialize the one-time native-extension compile; concurrent
            # first callers must wait for the single build, and every
            # later call is a cached-path no-op
            so = build()
            spec = importlib.util.spec_from_file_location("_gknative", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception:
            if mode == "require":
                raise
            print(
                "gatekeeper_tpu: native packing unavailable, "
                "using Python fallback",
                file=sys.stderr,
            )
            return None
        return _mod
