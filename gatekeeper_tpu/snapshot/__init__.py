"""State snapshot & warm-resume subsystem (ISSUE 3).

Persists the serving state whose rebuild dominates a cold start — the
interner vocabulary, the packed audit column store, and the template/
constraint registry — to a versioned, HMAC-sealed on-disk snapshot, and
restores it on startup with a resourceVersion-driven delta resync so a
restarted process's first audit sweep costs O(churn while down) instead
of O(cluster).  See docs/snapshots.md.

    SnapshotWriter  — capture + atomic persist + retention
    Snapshotter     — background cadence thread (audit-sweep hooked)
    SnapshotLoader  — validate + restore + delta resync, cold-path
                      fallback on ANY validation failure
    SnapshotError   — the "not usable, fall back" signal
"""

from .format import SnapshotError
from .loader import SnapshotLoader
from .writer import Snapshotter, SnapshotWriter

__all__ = [
    "SnapshotError",
    "SnapshotLoader",
    "Snapshotter",
    "SnapshotWriter",
]
