"""SnapshotLoader: validate, restore, delta-resync.

Restore sequence (startup only, before controllers/audit start):

  1. validate    — manifest HMAC + schema + code fingerprint + per-file
                   checksums (format.read_manifest); any failure moves
                   on to the next-older snapshot, then to the cold path
  2. load        — np.load the packed arrays, parse row metadata,
                   structural consistency checks
  3. install     — interner vocabulary, template/constraint registry
                   (via the client, so CRDs re-synthesize), the frozen
                   inventory tree + reviews wholesale, audit-pack
                   adoption (ops/auditpack.py adopt_restored)
  4. delta resync— list the snapshot's GVKs from the kube API
                   (metadata-only listing when the kube surface offers
                   one) and reconcile per object by resourceVersion:
                     same RV   -> nothing: the restored tree, reviews
                                  and packed row already hold exactly
                                  this content
                     diff RV / -> normal add_data (change-logged; only
                     new path     this row re-packs on the next sweep,
                                  via the existing ops/auditpack.py /
                                  ops/deltasweep.py machinery)
                     gone path -> delete_data (change-logged; the pack
                                  tombstones the row on sync)
                   so the first sweep's host cost is O(churn while
                   down), not O(cluster)
  5. delta basis — when the snapshot carries the incremental-sweep
                   state (counts, candidates, bit-packed base mask,
                   rendered-result cache) and the restored constraint
                   order matches, the first capped sweep runs the
                   O(churn) delta path — no full [C, R] dispatch, and
                   unchanged constraints reuse their persisted rendered
                   results.

Outcomes (snapshot_restore_outcome_total{outcome}):
  restored — a snapshot validated and seeded the pack
  fallback — snapshots existed but none was usable, a mid-restore
             failure forced a state wipe, or the RVs were fully stale
             (every row re-packs: cold-equivalent work, done safely)
  none     — no snapshot on disk (ordinary cold start)

plus one `quarantined` sample per snapshot that FAILED validation and
was renamed aside into `<root>/.quarantine/` (docs/failure-modes.md):
a corrupt snapshot is inspected once, never re-validated — and
re-failed — on every subsequent restart.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .. import faults
from .. import logging as gklog
from ..metrics.catalog import record_snapshot_load, record_snapshot_outcome
from ..obs import trace as obstrace
from ..process.excluder import SYNC
from . import format as fmt
from .format import SnapshotError

log = gklog.get("snapshot")


def _load_json(snap_dir: str, name: str):
    try:
        with open(os.path.join(snap_dir, name)) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise SnapshotError(f"{name} unreadable: {e}")


class SnapshotLoader:
    def __init__(self, root: str, quarantine: Optional[bool] = None):
        self.root = root
        # quarantine policy: None (default) follows ownership — the
        # resync=True restore path is the dir's OWNER (single-process or
        # the audit role) and moves validation-failed snapshots aside;
        # the resync=False path is a read-mostly fleet consumer of a
        # SHARED dir and must never mutate warmth it does not own
        # (docs/fleet.md trust model, tests/test_snapshot_concurrent.py)
        self.quarantine = quarantine
        # filled by restore(): resync statistics for logs/bench, and
        # whether the incremental-sweep basis was installed
        self.stats: Dict[str, Any] = {}
        self.delta_restored = False

    # ---- read + validate one snapshot -------------------------------------

    def _read(self, snap_dir: str) -> Dict[str, Any]:
        fmt.read_manifest(snap_dir)  # hmac + fingerprint + checksums
        if faults.ENABLED:
            # post-seal payload-validation seam: an error-mode rule models
            # a snapshot whose sealed bytes fail structural validation —
            # the quarantine path, not the try-the-next-snapshot path
            try:
                faults.fire(faults.SNAPSHOT_CORRUPT)
            except Exception as e:
                raise SnapshotError(f"injected corruption: {e}")
        interner = _load_json(snap_dir, fmt.INTERNER)
        registry = _load_json(snap_dir, fmt.REGISTRY)
        pack = _load_json(snap_dir, fmt.PACK)
        if not isinstance(interner, list) or not interner or interner[0] != "":
            raise SnapshotError("interner table malformed")
        if not isinstance(registry, dict) or not isinstance(pack, dict):
            raise SnapshotError("registry/pack malformed")
        try:
            with np.load(
                os.path.join(snap_dir, fmt.ARRAYS), allow_pickle=False
            ) as npz:
                arrays = {k: npz[k] for k in npz.files}
        except Exception as e:
            raise SnapshotError(f"arrays unreadable: {e}")
        rp = {
            k[len("rp:"):]: v for k, v in arrays.items()
            if k.startswith("rp:")
        }
        col_index = [fmt.decode_key(k) for k in pack.get("col_index", [])]
        cols: Dict[Any, Dict[str, np.ndarray]] = {ck: {} for ck in col_index}
        for k, v in arrays.items():
            if not k.startswith("col:"):
                continue
            _tag, idx_s, leaf = k.split(":", 2)
            try:
                ck = col_index[int(idx_s)]
            except (ValueError, IndexError):
                raise SnapshotError(f"array key {k!r} has no column index")
            cols[ck][leaf] = v
        row_path = pack.get("row_path")
        row_ns = pack.get("row_ns")
        free = pack.get("free")
        rvs = pack.get("rv")
        n_rows = pack.get("n_rows")
        if not (
            isinstance(row_path, list) and isinstance(row_ns, list)
            and isinstance(free, list) and isinstance(rvs, list)
            and isinstance(n_rows, int)
            and len(row_path) == len(row_ns) == len(rvs)
        ):
            raise SnapshotError("pack row metadata malformed")
        if not rp or "valid" not in rp:
            raise SnapshotError("review-side arrays missing")
        capacity = len(rp["valid"])
        if n_rows > capacity or len(row_path) > capacity:
            raise SnapshotError("row metadata exceeds array capacity")
        for k, v in rp.items():
            if len(v) != capacity:
                raise SnapshotError(f"rp[{k}] capacity mismatch")
        for ck, leaves in cols.items():
            if not leaves:
                raise SnapshotError("column store entry with no arrays")
            for leaf, v in leaves.items():
                if len(v) != capacity:
                    raise SnapshotError("column capacity mismatch")
        col_keys = tuple(fmt.decode_key(k) for k in pack.get("col_keys", []))
        if set(col_keys) != set(col_index):
            raise SnapshotError("column key set mismatch")
        # the inventory pickle is parsed LAST and only because the
        # manifest hmac + checksum already authenticated its bytes
        import pickle

        try:
            with open(os.path.join(snap_dir, fmt.INVENTORY), "rb") as f:
                inv = pickle.load(f)
        except Exception as e:
            raise SnapshotError(f"inventory unreadable: {e}")
        if not isinstance(inv, dict):
            raise SnapshotError("inventory malformed")
        reviews = inv.get("reviews")
        row_gen = inv.get("row_gen")
        if not (
            isinstance(reviews, list) and isinstance(row_gen, list)
            and len(reviews) == len(row_path) == len(row_gen)
        ):
            raise SnapshotError("inventory row lists malformed")
        return {
            "interner": interner,
            "templates": registry.get("templates") or [],
            "constraints": registry.get("constraints") or [],
            "rp": rp,
            "cols": cols,
            "col_keys": col_keys,
            "row_path": [
                tuple(p) if isinstance(p, list) else None for p in row_path
            ],
            "row_ns": row_ns,
            "free": free,
            "n_rows": n_rows,
            "rv": rvs,
            "reviews": reviews,
            "row_gen": row_gen,
            "delta": inv.get("delta"),
        }

    # ---- install -----------------------------------------------------------

    def _install(self, client, state: Dict[str, Any]):
        driver = client.driver
        interner = driver.interner
        with driver._lock:
            strings = state["interner"]
            if interner._strings != strings[: len(interner._strings)]:
                raise SnapshotError(
                    "live interner diverges from snapshot vocabulary"
                )
            interner._strings = list(strings)
            interner._ids = {s: i for i, s in enumerate(strings)}
            for tmpl in state["templates"]:
                client.add_template(tmpl)
            for c in state["constraints"]:
                # schema validation happened when the constraint first
                # entered the engine; the manifest seal vouches for the
                # persisted copy, so restore installs directly
                kind = c.get("kind")
                name = (c.get("metadata") or {}).get("name")
                if not kind or not name:
                    raise SnapshotError("constraint missing kind/name")
                driver.put_constraint(kind, name, c)
            # rebuild the store tree from the reviews' objects.  Leaves
            # are frozen eagerly ONLY when an installed template reads
            # data.inventory (the one consumer that hashes them —
            # _inventory_for_render's contract); inventory-free corpora
            # adopt plain-dict leaves and skip the O(cluster) freeze,
            # with store.frozen() converting lazily if a later template
            # install ever needs it
            from ..engine.value import freeze

            uses_inv = any(
                getattr(t.policy, "uses_inventory", True)
                for t in driver.templates.values()
            )
            tree: Dict[str, Any] = {}
            for row, seg in enumerate(state["row_path"]):
                if seg is None:
                    continue
                review = state["reviews"][row]
                obj = (
                    review.get("object") if isinstance(review, dict)
                    else None
                )
                if obj is None:
                    raise SnapshotError(f"row {row} review missing object")
                node = tree
                for s in seg[:-1]:
                    node = node.setdefault(s, {})
                node[seg[-1]] = freeze(obj) if uses_inv else obj
            driver.store.adopt_tree(tree, leaves_frozen=uses_inv)
            driver._audit_pack.adopt_restored(
                rp=state["rp"],
                cols=state["cols"],
                col_keys=state["col_keys"],
                reviews=state["reviews"],
                row_path=state["row_path"],
                row_ns=state["row_ns"],
                row_gen=state["row_gen"],
                free=state["free"],
                n_rows=state["n_rows"],
                synced_epoch=driver.store.epoch,
            )

    # ---- delta resync -------------------------------------------------------

    @staticmethod
    def _kube_get(kube, gvk, name: str, ns: str):
        try:
            return kube.get(gvk, name, ns)
        except Exception:
            return None  # deleted between list and get: next pass catches

    def _resync(self, client, kube, state: Dict[str, Any],
                excluder=None) -> Dict[str, int]:
        """Reconcile the restored state against the live API by
        resourceVersion.  The listing is metadata-only when the kube
        surface offers `list_rvs` (the real apiserver analogue is a
        PartialObjectMetadata list) — matched objects then cost one dict
        lookup, never a body transfer or a freeze."""
        driver = client.driver
        recorded: Dict[Tuple[str, ...], Tuple[int, str]] = {}
        snap_kinds = set()
        for row, seg in enumerate(state["row_path"]):
            if seg is None:
                continue
            ident = fmt.path_identity(seg)
            if ident is None:
                raise SnapshotError(f"row path {seg!r} not object-depth")
            recorded[seg] = (row, state["rv"][row])
            snap_kinds.add((ident[0], ident[1]))
        stats = {"matched": 0, "changed": 0, "added": 0, "deleted": 0}
        seen_rows: set = set()
        if faults.ENABLED:
            faults.fire(faults.SNAPSHOT_RESYNC)
        with driver._lock:
            for gvk in kube.list_gvks():
                api = fmt.gvk_api_version(gvk)
                kind = gvk[2]
                if (api, kind) not in snap_kinds:
                    # GVKs the snapshot never held flow through the normal
                    # controller replay (store.put dedups re-lists by RV)
                    continue
                if hasattr(kube, "list_rvs"):
                    entries = [
                        (ns, name, rv, None)
                        for (ns, name), rv in kube.list_rvs(gvk).items()
                    ]
                else:
                    entries = []
                    for obj in kube.list(gvk):
                        meta = obj.get("metadata") or {}
                        entries.append((
                            meta.get("namespace") or "",
                            meta.get("name") or "",
                            str(meta.get("resourceVersion") or ""),
                            obj,
                        ))
                for ns, name, rv, obj in entries:
                    segments = (
                        ("namespace", ns, api, kind, name) if ns
                        else ("cluster", api, kind, name)
                    )
                    rec = recorded.get(segments)
                    if rec is None:
                        if excluder is not None and ns and \
                                excluder.is_namespace_excluded(SYNC, ns):
                            continue
                        obj = obj if obj is not None else self._kube_get(
                            kube, gvk, name, ns)
                        if obj is None:
                            continue
                        client.add_data(obj)  # created while down: new row
                        stats["added"] += 1
                        continue
                    row, snap_rv = rec
                    seen_rows.add(row)
                    if snap_rv and str(rv) == snap_rv:
                        # the restored tree, review and packed row already
                        # hold exactly this content: nothing to do
                        stats["matched"] += 1
                        continue
                    obj = obj if obj is not None else self._kube_get(
                        kube, gvk, name, ns)
                    if obj is None:
                        driver.delete_data(segments)
                        stats["deleted"] += 1
                        continue
                    client.add_data(obj)  # change-logged: row re-packs
                    stats["changed"] += 1
            for seg, (row, _rv) in recorded.items():
                if row not in seen_rows:
                    # change-logged delete: the pack tombstones the row
                    # through the ordinary sync machinery
                    driver.delete_data(seg)
                    stats["deleted"] += 1
            # epoch bump without a change-log entry: sweep/frozen caches
            # re-read; ap.synced_epoch stays at its adoption value, so the
            # next sync() consumes exactly the changes logged above
            driver.store.invalidate_frozen()
        return stats

    # ---- delta-sweep basis ---------------------------------------------------

    def _restore_delta(self, client, state: Dict[str, Any]) -> bool:
        """Install the persisted incremental-sweep state so the first
        capped audit runs the O(churn) delta path.  Refused (False) when
        the restored constraint order diverges from the snapshot's — the
        per-constraint indices would be misaligned; the first sweep then
        falls back to one full dispatch, which rebases everything."""
        delta = state.get("delta")
        if not delta:
            return False
        driver = client.driver
        import jax

        from ..ops.deltasweep import DeltaState, MaskSource

        with driver._lock:
            ap = driver._audit_pack
            cur_keys = [
                (k, n) for k, n, _c in driver._ordered_constraints()
            ]
            if cur_keys != [tuple(k) for k in delta["ordered_keys"]]:
                log.warning(
                    "snapshot delta basis dropped: constraint order "
                    "diverged (first sweep will be a full dispatch)"
                )
                return False
            # width-drift invalidation: a basis produced under a different
            # sweep sharding layout (mesh width) carries that layout's row
            # padding in its base mask — rebase via one full sweep instead
            # of serving candidates across a drifted slab geometry.  A
            # basis missing the field predates the stamp; those were all
            # produced by the single-device sweep, so treat as width 1.
            snap_width = int(delta.get("mesh_width") or 1)
            live_width = driver.mesh_layout()
            if snap_width != live_width:
                log.warning(
                    "snapshot delta basis dropped: sweep sharding width "
                    "drifted (snapshot %d, live %d); first sweep will be "
                    "a full dispatch", snap_width, live_width,
                )
                return False
            shape = tuple(delta["mask_shape"])
            mask = np.unpackbits(
                np.asarray(delta["mask_packed"]), axis=1, count=shape[1]
            ).astype(bool)
            if mask.shape != shape or shape[1] != ap.capacity:
                log.warning("snapshot delta basis dropped: mask shape "
                            "mismatch")
                return False
            # re-bind the compiled render plans eagerly and validate the
            # classification against the snapshot's: the persisted render
            # cache holds rendered Results, and reusing them under a
            # DIFFERENT plan classification (a plan-compiler change
            # between writer and reader) could mask a rendering change —
            # drop the cache and re-render on mismatch, keep the rest of
            # the warm basis either way
            render_cache = delta["render_cache"]
            persisted_plans = delta.get("render_plans")
            if persisted_plans is not None:
                if driver._render_plan_tiers() != dict(persisted_plans):
                    log.warning(
                        "snapshot render-plan classification diverged "
                        "from the rebuilt plans; dropping the persisted "
                        "render cache (first sweep re-renders)"
                    )
                    render_cache = {}
            # referential policies: rebuild the persisted join-group
            # index (ops/joinkernel.py).  Plan drift — a template change
            # reclassifying the join families between writer and reader —
            # or a missing index drops the WHOLE basis: candidates and
            # counts were produced by the old aggregates, and the delta
            # path cannot maintain aggregates it has no index for.
            plans = ()
            if hasattr(driver, "_active_join_plans"):
                plans = driver._active_join_plans()
            join_state = None
            if plans:
                from ..ops.joinkernel import JoinState

                ji = delta.get("join_index")
                join_state = (
                    JoinState.restore(tuple(plans), ji, ap.rebuild_gen)
                    if ji else None
                )
                if join_state is None:
                    log.warning(
                        "snapshot delta basis dropped: referential join "
                        "plans active but the persisted join index is "
                        "missing or drifted (first sweep will be a full "
                        "dispatch)"
                    )
                    return False
            # device upload stays lazy: the first sweep with zero churn
            # never needs the mask at all.  Under a mesh the mask commits
            # row-sharded on "data" (the same-width check above guarantees
            # the slab geometry matches) — a single-device commit would
            # collide with the mesh-replicated constraint side inside the
            # first delta dispatch
            mesh = driver._mesh()
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                sh = NamedSharding(mesh, P(None, "data"))
                mask_src = MaskSource(
                    lambda: jax.device_put(mask, sh)
                )
            else:
                mask_src = MaskSource(lambda: jax.device_put(mask))
            driver._delta_state = DeltaState.from_restore(
                counts=delta["counts"],
                cand=delta["cand"],
                horizon=delta["horizon"],
                crow=delta["crow"],
                K=int(delta["K"]),
                mask_src=mask_src,
                row_cols=delta["row_cols"],
                render_cache=render_cache,
                cs_epoch=driver._cs_epoch,
                layout_gen=ap.layout_gen,
                store_epoch=driver.store.epoch,
                # the same-width check above ran against the live layout,
                # so the restored basis carries exactly that topology
                mesh_width=live_width,
            )
            if join_state is not None:
                driver._join_state = join_state
        return True

    # ---- the whole restore --------------------------------------------------

    def restore(self, client, kube, excluder=None, resync: bool = True) -> str:
        """Try every snapshot newest-first; returns the outcome string
        (restored / fallback / none) after recording it in metrics.
        Validation failures fall through to older snapshots; a failure
        AFTER state installation wipes back to a clean cold start.

        ``resync=False`` skips step 4 (the resourceVersion reconcile
        against the live API): fleet webhook replicas adopting a SHARED
        warm snapshot pass this — their local store starts empty, so a
        resync would read every restored row as deleted and tombstone
        the pack they just adopted.  The watch replay still reconciles
        the store afterwards (store RV dedup turns it into a delta
        resync), and the pack they restored is read-mostly state they
        do not own (docs/fleet.md)."""
        t0 = time.perf_counter()
        names = fmt.list_snapshots(self.root)
        if not names:
            record_snapshot_outcome("none")
            self.stats = {}
            return "none"
        outcome = "fallback"
        quarantine = self.quarantine if self.quarantine is not None \
            else resync
        with obstrace.root_span("snapshot.restore", snapshots=len(names)):
            for name in names:
                snap_dir = os.path.join(self.root, name)
                try:
                    with obstrace.span("snapshot.load", snapshot=name):
                        if faults.ENABLED:
                            faults.fire(faults.SNAPSHOT_LOAD)
                        state = self._read(snap_dir)
                except SnapshotError as e:
                    log.warning("snapshot %s rejected: %s", name, e)
                    if quarantine:
                        self._quarantine(snap_dir, name, str(e))
                    continue
                except Exception as e:
                    log.exception("snapshot %s unreadable", name)
                    if quarantine:
                        self._quarantine(snap_dir, name, repr(e))
                    continue
                try:
                    with obstrace.span("snapshot.install",
                                       rows=state["n_rows"]):
                        self._install(client, state)
                    if resync:
                        with obstrace.span("snapshot.resync") as sp:
                            stats = self._resync(
                                client, kube, state, excluder=excluder
                            )
                            sp.set_attrs(**stats)
                    else:
                        stats = {"resync": "skipped"}
                    self.delta_restored = self._restore_delta(client, state)
                except Exception:
                    # any failure past validation may have left partial
                    # state (e.g. adopt_tree landed, adopt_restored did
                    # not): always wipe — on a still-clean driver the
                    # wipe is a harmless no-op
                    log.exception(
                        "snapshot %s failed mid-restore; wiping to the "
                        "cold path", name,
                    )
                    self._wipe(client)
                    break
                self.stats = stats
                live_rows = sum(
                    1 for p in state["row_path"] if p is not None
                )
                if not resync:
                    # adopted wholesale (fleet shared-warmth path): the
                    # snapshot IS the state; staleness is the watch
                    # replay's problem, not a fallback condition
                    outcome = "restored"
                elif live_rows and not stats["matched"]:
                    # fully stale RVs: every row re-packs — safe, but
                    # cold-equivalent, so report it as the fallback it is
                    log.warning(
                        "snapshot %s resourceVersions fully stale "
                        "(%d rows, 0 matched): first sweep re-packs "
                        "everything", name, live_rows,
                    )
                    outcome = "fallback"
                else:
                    outcome = "restored"
                gklog.log_event(
                    log, "snapshot restored",
                    **{gklog.EVENT_TYPE: "snapshot_restored",
                       "snapshot_dir": snap_dir, "outcome": outcome,
                       **stats},
                )
                break
        record_snapshot_load(time.perf_counter() - t0)
        record_snapshot_outcome(outcome)
        return outcome

    def _quarantine(self, snap_dir: str, name: str, reason: str):
        """Move a snapshot that failed validation aside into
        `<root>/.quarantine/<name>` so it is inspected once and never
        re-validated (and re-failed) on every subsequent restart — a
        corrupt newest snapshot otherwise taxes every restore attempt
        forever.  One `snapshot_restore_outcome_total{outcome=
        "quarantined"}` sample per moved snapshot; a failed rename is
        logged and swallowed (quarantine is hygiene, never a reason to
        fail the restore that already fell past this snapshot)."""
        qroot = os.path.join(self.root, fmt.QUARANTINE_DIR)
        try:
            os.makedirs(qroot, exist_ok=True)
            dst = os.path.join(qroot, name)
            if os.path.exists(dst):
                # a same-named quarantined dir already exists (clock
                # reuse): keep both, suffixed by arrival order
                n = 1
                while os.path.exists(f"{dst}.{n}"):
                    n += 1
                dst = f"{dst}.{n}"
            os.rename(snap_dir, dst)
        except OSError:
            log.exception("failed to quarantine snapshot %s", name)
            return
        record_snapshot_outcome("quarantined")
        gklog.log_event(
            log, "snapshot quarantined",
            **{gklog.EVENT_TYPE: "snapshot_quarantined",
               "snapshot_dir": snap_dir, "quarantined_to": dst,
               "reason": reason[:500]},
        )

    @staticmethod
    def _wipe(client):
        """Return a partially-restored driver to a clean cold start:
        wipe the replicated inventory (change-logged as a wipe, so every
        downstream cache rebuilds) and drop the adopted pack.  The
        template/constraint registry stays — those restored via the
        client API are valid regardless."""
        driver = client.driver
        try:
            with driver._lock:
                driver.store.delete(())
                from ..ops.auditpack import AuditPackCache

                driver._audit_pack = AuditPackCache()
        except Exception:
            log.exception("post-failure wipe failed")
