"""On-disk snapshot format: layout, manifest, sealing, key codecs.

A snapshot is one directory (written temp-then-rename, so readers only
ever see complete snapshots):

    snap-<millis>-<pid>/
      MANIFEST.json     schema version, code fingerprint, per-file
                        sha256 checksums, HMAC seal (util/seal.py)
      interner.json     the global string vocabulary, id order preserved
      registry.json     raw ConstraintTemplate + constraint objects
      pack.json         audit-pack row metadata: column keys, row paths,
                        namespaces, free list, per-row resourceVersions
      arrays.npz        the packed review-side + column arrays

Trust model (shared with ops/aotcache.py; docs/snapshots.md): the
manifest is HMAC-sealed and every file is checksummed in it, so nothing
is parsed — not even json — before its bytes authenticate.  Validation
failures are NEVER errors to the caller's caller: the loader reports
them and the process falls back to the cold start path.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..util import seal as sealmod

SCHEMA_VERSION = 1
MANIFEST = "MANIFEST.json"
INTERNER = "interner.json"
REGISTRY = "registry.json"
PACK = "pack.json"
ARRAYS = "arrays.npz"
# the frozen inventory tree + per-row reviews, pickled: restoring them
# wholesale is what turns the resync into a metadata-only pass — the
# cold path's per-object freeze of the whole cluster (seconds at 100k
# objects) disappears.  Pickle is only parsed AFTER the manifest HMAC
# and this file's checksum verify (the aotcache trust model).
INVENTORY = "inventory.pkl"

PAYLOAD_FILES = (INTERNER, REGISTRY, PACK, ARRAYS, INVENTORY)

SNAP_PREFIX = "snap-"
TMP_PREFIX = ".tmp-"
# snapshots that failed validation are renamed under here (dot-prefixed:
# excluded from list_snapshots and the writer's prune) instead of being
# re-validated — and re-failed — on every subsequent restart
QUARANTINE_DIR = ".quarantine"


class SnapshotError(Exception):
    """Any reason a snapshot cannot be written or restored; carries a
    short machine-greppable reason as its message."""


# ---- column-key / path codecs ----------------------------------------------
# AuditPackCache keys columns by nested tuples of strings
# ((kind, iter_paths, rel_path, exclude)); JSON has no tuples, so the
# codec is a structure-preserving tuple<->list swap.


def encode_key(key) -> Any:
    if isinstance(key, tuple):
        return [encode_key(k) for k in key]
    return key


def decode_key(key) -> Any:
    if isinstance(key, list):
        return tuple(decode_key(k) for k in key)
    return key


# ---- manifest ---------------------------------------------------------------


def _canonical(manifest: Dict[str, Any]) -> bytes:
    body = {k: v for k, v in manifest.items() if k != "hmac"}
    return json.dumps(body, sort_keys=True, separators=(",", ":")).encode()


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_manifest(snap_dir: str) -> None:
    files = {}
    for name in PAYLOAD_FILES:
        files[name] = file_sha256(os.path.join(snap_dir, name))
    manifest: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "fingerprint": sealmod.code_fingerprint(),
        "files": files,
    }
    manifest["hmac"] = sealmod.seal(_canonical(manifest))
    with open(os.path.join(snap_dir, MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)


def read_manifest(snap_dir: str) -> Dict[str, Any]:
    """Parse + authenticate the manifest and verify every payload file's
    checksum.  Raises SnapshotError with a short reason on any failure —
    nothing beyond the manifest json itself is parsed before the HMAC
    verifies, and no payload is parsed before its checksum does."""
    path = os.path.join(snap_dir, MANIFEST)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise SnapshotError(f"manifest unreadable: {e}")
    if not isinstance(manifest, dict):
        raise SnapshotError("manifest not an object")
    if not sealmod.verify(_canonical(manifest), manifest.get("hmac", "")):
        raise SnapshotError("manifest hmac verification failed")
    if manifest.get("schema") != SCHEMA_VERSION:
        raise SnapshotError(
            f"schema {manifest.get('schema')!r} != {SCHEMA_VERSION}"
        )
    if manifest.get("fingerprint") != sealmod.code_fingerprint():
        raise SnapshotError("code fingerprint mismatch (different build)")
    files = manifest.get("files")
    if not isinstance(files, dict) or set(files) != set(PAYLOAD_FILES):
        raise SnapshotError("manifest file list mismatch")
    for name, want in files.items():
        fpath = os.path.join(snap_dir, name)
        try:
            got = file_sha256(fpath)
        except OSError as e:
            raise SnapshotError(f"{name} unreadable: {e}")
        if got != want:
            raise SnapshotError(f"{name} checksum mismatch")
    return manifest


# ---- directory management ---------------------------------------------------


def list_snapshots(root: str) -> List[str]:
    """Completed snapshot dir names, newest first (names embed the write
    time, so the lexicographic order is the age order)."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return sorted(
        (n for n in names if n.startswith(SNAP_PREFIX)), reverse=True
    )


def dir_bytes(snap_dir: str) -> int:
    total = 0
    try:
        for name in os.listdir(snap_dir):
            try:
                total += os.path.getsize(os.path.join(snap_dir, name))
            except OSError:
                pass
    except OSError:
        pass
    return total


def path_rv(frozen_obj: Any) -> str:
    """metadata.resourceVersion of a (frozen) stored object, or ''."""
    try:
        meta = frozen_obj.get("metadata")
        rv = meta.get("resourceVersion") if meta is not None else None
        return str(rv) if rv else ""
    except Exception:
        return ""


def gvk_api_version(gvk: Tuple[str, str, str]) -> str:
    group, version, _kind = gvk
    return f"{group}/{version}" if group else version


def path_identity(seg: Tuple[str, ...]) -> Optional[Tuple[str, str, str, str]]:
    """(api_version, kind, name, namespace) of an object-depth store path
    (the same shape ops/auditpack.py uses), else None."""
    if seg and seg[0] == "cluster" and len(seg) == 4:
        return seg[1], seg[2], seg[3], ""
    if seg and seg[0] == "namespace" and len(seg) == 5:
        return seg[2], seg[3], seg[4], seg[1]
    return None
