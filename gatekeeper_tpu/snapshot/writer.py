"""SnapshotWriter + the background Snapshotter thread.

The writer captures, under the driver lock, exactly the state whose
rebuild dominates a cold start (round-5 VERDICT: 16.1s of the 20.3s
restart was the first sweep's relist + intern + pack):

  - the interner vocabulary (ids are baked into every packed array)
  - the resident audit pack (review-side arrays + column store + row
    metadata), as synced to the inventory store
  - per-row resourceVersions, so the loader can delta-resync against
    the live API instead of re-packing the world
  - the raw template/constraint registry

Capture is a few array copies (~ms per 100MB) so admission traffic
stalls briefly at worst; serialization and the atomic rename happen
outside the lock.  A snapshot is only taken when the pack is exactly
synced to the store (the state right after an audit sweep) — per-row
resourceVersions must describe the packed content, not newer writes.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import faults
from .. import logging as gklog
from ..metrics.catalog import record_snapshot_write
from ..obs import trace as obstrace
from ..util import seal as sealmod
from . import format as fmt
from .format import SnapshotError
from ..util import join_thread

log = gklog.get("snapshot")

DEFAULT_RETAIN = 3

# advisory cross-process writer lock (fleet shared snapshot dirs,
# docs/fleet.md): two audit-role processes pointed at one directory must
# not interleave prunes with each other's renames.  POSIX-only; where
# fcntl is unavailable the writer degrades to the single-process
# behavior it always had.
try:
    import fcntl as _fcntl
except ImportError:  # pragma: no cover - non-POSIX
    _fcntl = None
WRITE_LOCK = ".write.lock"


class _WriterLock:
    """Non-blocking exclusive flock on <root>/.write.lock; raises
    SnapshotError when another process holds it (the Snapshotter treats
    that as an ordinary skip and retries next cycle).  Readers never
    take it — the atomic tmp-dir rename is what makes concurrent
    restores safe."""

    def __init__(self, root: str):
        self._path = os.path.join(root, WRITE_LOCK)
        self._fh = None

    def __enter__(self):
        if _fcntl is None:
            return self
        self._fh = open(self._path, "a+")
        try:
            _fcntl.flock(self._fh, _fcntl.LOCK_EX | _fcntl.LOCK_NB)
        except OSError:
            self._fh.close()
            self._fh = None
            raise SnapshotError(
                "another process is writing to this snapshot dir"
            )
        return self

    def __exit__(self, *exc):
        if self._fh is not None:
            try:
                _fcntl.flock(self._fh, _fcntl.LOCK_UN)
            finally:
                self._fh.close()
                self._fh = None
        return False


class SnapshotWriter:
    def __init__(self, root: str, retain: int = DEFAULT_RETAIN,
                 capture_delta: bool = True):
        self.root = root
        self.retain = max(1, retain)
        # capture_delta=False skips the incremental-sweep basis (and the
        # base-mask resolution wait it may imply); restores then pay one
        # full device sweep — tests of the validation/fallback surface
        # use this to stay fast
        self.capture_delta = capture_delta
        sealmod.secure_makedirs(root)

    # ---- capture ----------------------------------------------------------

    @staticmethod
    def _capture_delta(driver, ap) -> Optional[Dict[str, Any]]:
        """The incremental-sweep basis (ops/deltasweep.py DeltaState), when
        it is current: counts, candidate lists, the rendered-result cache,
        and a REFERENCE to the base-mask source.  The mask itself resolves
        in _resolve_mask OUTSIDE the driver lock — its dispatch runs for
        seconds at 100k rows and must never stall admission reviews
        queueing on the lock.  With the basis, a restart's first capped
        sweep runs the O(churn) delta path instead of a full [C, R]
        dispatch.  None when unavailable — the snapshot is still valid,
        the restart just pays one full device sweep."""
        st = getattr(driver, "_delta_state", None)
        if st is None:
            return None
        if (
            st.cs_epoch != driver._cs_epoch
            or st.layout_gen != ap.layout_gen
            or st.store_epoch != driver.store.epoch
        ):
            return None
        ordered_keys = [
            (k, n) for k, n, _c in driver._ordered_constraints()
        ]
        # referential policies: the delta path needs the host join-group
        # index (ops/joinkernel.py) — a basis restored without it could
        # not maintain the aggregates incrementally, so when plans are
        # active and the index is stale the basis is withheld entirely
        # (the restart's first sweep rebases via one full dispatch)
        join_index = None
        plans = ()
        if hasattr(driver, "_active_join_plans"):
            plans = driver._active_join_plans()
        if plans:
            js = getattr(driver, "_join_state", None)
            if (
                js is None or not js.built
                or js.rebuild_gen != ap.rebuild_gen
                or js.sig != tuple(p.sig for p in plans)
            ):
                return None
            join_index = js.persist()
        # compiled message-plan tiers per constraint: the loader re-binds
        # plans after template replay and validates the classification
        # against this map — a drift (e.g. a plan-compiler change between
        # writer and reader versions) drops the persisted render cache
        # instead of silently reusing results a different tier produced
        return {
            "render_plans": driver._render_plan_tiers(),
            # sweep sharding layout the basis was produced under (mesh
            # device count, 1 = single-device) — the basis's OWN stamp,
            # not the live driver layout: a topology poke between the
            # basis's full sweep and the snapshot tick must not mis-label
            # a mask whose row padding belongs to the old geometry.  The
            # loader refuses the basis when the restoring process's
            # layout differs and rebases via one full sweep rather than
            # serve candidates off a mask whose padded tail no longer
            # matches the live slab geometry.
            "mesh_width": int(st.mesh_width),
            "counts": st.counts.copy(),
            "cand": [list(c) for c in st.cand],
            "horizon": list(st.horizon),
            "crow": np.asarray(st.crow, np.int64).copy(),
            "K": st.K,
            "row_cols": {
                int(r): np.array(c) for r, c in st.row_cols.items()
            },
            "render_cache": dict(st.render_cache),
            "ordered_keys": ordered_keys,
            # the join-group index (None for row-local corpora): restores
            # keep the O(churn) delta path for referential policies; the
            # loader drops the whole basis on plan drift
            "join_index": join_index,
            # resolved post-lock; a MaskSource is internally locked and
            # its value is pinned to this basis's full sweep
            "mask_src": st.mask_src,
        }

    @staticmethod
    def _resolve_mask(mask_src) -> Optional[np.ndarray]:
        """The [C_total, R] base mask as a host bool array, waiting out
        (bounded) an in-flight background prefetch; None when it cannot
        be had.  Runs WITHOUT the driver lock."""
        from ..ops.deltasweep import MaskSource

        mask = mask_src.peek(wait_s=300.0)
        if mask is None:
            try:
                mask = mask_src.get()
            except Exception:
                return None
        if mask is None or mask is MaskSource.BUSY:
            return None
        return np.asarray(mask).astype(bool)

    def _capture(self, client) -> Dict[str, Any]:
        """Consistent copy of the serving state (driver lock held)."""
        driver = client.driver
        ap = getattr(driver, "_audit_pack", None)
        interner = getattr(driver, "interner", None)
        if ap is None or interner is None:
            raise SnapshotError("driver exposes no packed audit state")
        with driver._lock:
            if ap.rp is None or ap.col_keys is None:
                raise SnapshotError("no packed audit state yet (no sweep)")
            if ap.synced_epoch != driver.store.epoch:
                # per-row RVs must describe the packed rows; a store that
                # moved past the pack gets snapshotted after its next sweep
                raise SnapshotError("store ahead of pack; retry after sweep")
            rp = {k: np.array(v) for k, v in ap.rp.items()}
            cols_order = sorted(ap.cols.keys())
            cols = {
                ck: {leaf: np.array(a) for leaf, a in ap.cols[ck].items()}
                for ck in cols_order
            }
            rvs: List[str] = []
            for seg in ap.row_path:
                rvs.append(
                    fmt.path_rv(driver.store.get(seg)) if seg else ""
                )
            templates = []
            for kind in client.templates():
                tmpl = client._templates.get(kind)
                if tmpl is None or not tmpl.raw:
                    raise SnapshotError(f"template {kind} has no raw form")
                templates.append(tmpl.raw)
            constraints = [
                c
                for kind in sorted(driver.constraints)
                for _name, c in sorted(driver.constraints[kind].items())
            ]
            # the loader rebuilds the frozen store tree from the reviews'
            # objects, so every stored object must BE a pack row — exotic
            # store paths (deep put_data) would silently drop on restore
            n_objects = sum(1 for _ in driver.store.iter_objects())
            n_live = sum(1 for p in ap.row_path if p is not None)
            if n_objects != n_live:
                raise SnapshotError(
                    f"store holds {n_objects} objects but the pack has "
                    f"{n_live} live rows; snapshot skipped"
                )
            return {
                "interner": list(interner._strings),
                "templates": templates,
                "constraints": constraints,
                "rp": rp,
                "cols_order": cols_order,
                "cols": cols,
                "col_keys": ap.col_keys,
                "row_path": [
                    list(p) if p is not None else None for p in ap.row_path
                ],
                "row_ns": list(ap.row_ns),
                "free": list(ap.free),
                "n_rows": ap.n_rows,
                "rv": rvs,
                # the pickle payload: reviews (plain dicts — they pickle
                # and unpickle at C speed, unlike a FrozenDict graph; the
                # loader re-freezes their objects natively to rebuild the
                # store tree) + render-cache-keying generations + the
                # delta basis
                "reviews": list(ap.reviews),
                "row_gen": list(ap.row_gen),
                "delta": (
                    self._capture_delta(driver, ap)
                    if self.capture_delta else None
                ),
            }

    # ---- serialize --------------------------------------------------------

    def write(self, client) -> str:
        """Capture + persist one snapshot; returns its directory path.
        Raises SnapshotError when the state is not snapshotable and lets
        unexpected errors propagate (the Snapshotter guards)."""
        t0 = time.perf_counter()
        state = self._capture(client)
        delta = state["delta"]
        if delta is not None:
            # outside the driver lock: the mask dispatch may take seconds
            mask = self._resolve_mask(delta.pop("mask_src"))
            if mask is None:
                state["delta"] = None
            else:
                delta["mask_packed"] = np.packbits(mask, axis=1)
                delta["mask_shape"] = list(mask.shape)
        if faults.ENABLED:
            faults.fire(faults.SNAPSHOT_WRITE)
        name = f"{fmt.SNAP_PREFIX}{int(time.time() * 1000):013d}-{os.getpid()}"  # wall-clock: ok (dir name)
        tmp = os.path.join(self.root, f"{fmt.TMP_PREFIX}{name}")
        final = os.path.join(self.root, name)
        # the on-disk phase is serialized ACROSS processes: a concurrent
        # writer's prune must never sweep this writer's tmp dir or race
        # its retention scan (readers stay lock-free — they only ever see
        # complete, atomically-renamed snapshot dirs)
        lock = _WriterLock(self.root)
        lock.__enter__()
        try:
            os.makedirs(tmp, mode=0o700)
            with open(os.path.join(tmp, fmt.INTERNER), "w") as f:
                json.dump(state["interner"], f)
            with open(os.path.join(tmp, fmt.REGISTRY), "w") as f:
                json.dump(
                    {
                        "templates": state["templates"],
                        "constraints": state["constraints"],
                    },
                    f,
                )
            with open(os.path.join(tmp, fmt.PACK), "w") as f:
                json.dump(
                    {
                        "col_keys": fmt.encode_key(list(state["col_keys"])),
                        "col_index": [
                            fmt.encode_key(k) for k in state["cols_order"]
                        ],
                        "row_path": state["row_path"],
                        "row_ns": state["row_ns"],
                        "free": state["free"],
                        "n_rows": state["n_rows"],
                        "rv": state["rv"],
                    },
                    f,
                )
            arrays: Dict[str, np.ndarray] = {}
            for k, v in state["rp"].items():
                arrays[f"rp:{k}"] = v
            for i, ck in enumerate(state["cols_order"]):
                for leaf, a in state["cols"][ck].items():
                    arrays[f"col:{i}:{leaf}"] = a
            with open(os.path.join(tmp, fmt.ARRAYS), "wb") as f:
                np.savez(f, **arrays)
            # the inventory pickle: one dump shares object identity, so a
            # render-cache Result and ap.reviews[row] restore as the SAME
            # dict (the render reuse path depends on nothing more than
            # value equality, but sharing keeps memory flat).  Parsed on
            # restore only after the manifest HMAC + checksum verify.
            import pickle

            with open(os.path.join(tmp, fmt.INVENTORY), "wb") as f:
                pickle.dump(
                    {
                        "reviews": state["reviews"],
                        "row_gen": state["row_gen"],
                        "delta": state["delta"],
                    },
                    f, protocol=pickle.HIGHEST_PROTOCOL,
                )
            fmt.write_manifest(tmp)
            os.rename(tmp, final)
            self._prune()
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        finally:
            lock.__exit__()
        dur = time.perf_counter() - t0
        nbytes = fmt.dir_bytes(final)
        record_snapshot_write(dur, nbytes)
        gklog.log_event(
            log, "snapshot written",
            **{gklog.EVENT_TYPE: "snapshot_written",
               "snapshot_dir": final, "snapshot_bytes": nbytes,
               "rows": state["n_rows"],
               "duration_ms": round(dur * 1e3, 1)},
        )
        return final

    # tmp dirs older than this are orphans of a killed writer (a live
    # write finishes in seconds); swept on every prune so crash-loops
    # cannot fill the volume with near-full-size partial snapshots
    TMP_ORPHAN_S = 3600.0

    def _prune(self):
        for name in fmt.list_snapshots(self.root)[self.retain:]:
            shutil.rmtree(os.path.join(self.root, name), ignore_errors=True)
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        now = time.time()  # wall-clock: ok (mtime comparison)
        for name in names:
            if not name.startswith(fmt.TMP_PREFIX):
                continue
            path = os.path.join(self.root, name)
            try:
                if now - os.path.getmtime(path) > self.TMP_ORPHAN_S:
                    shutil.rmtree(path, ignore_errors=True)
            except OSError:
                pass


class Snapshotter:
    """Background snapshot cadence: one snapshot after the first audit
    sweep, then at most one per `interval_s`, re-armed by each completed
    sweep (AuditManager.notify hook) and by a timer so idle clusters
    still refresh their RV horizon.  Write failures are logged and
    retried next cycle — persistence must never affect serving."""

    def __init__(self, client, root: str, interval_s: float = 300.0,
                 retain: int = DEFAULT_RETAIN, capture_delta: bool = True):
        self.client = client
        self.writer = SnapshotWriter(
            root, retain=retain, capture_delta=capture_delta
        )
        self.interval_s = max(1.0, interval_s)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_write = 0.0  # perf_counter timeline
        self.last_path: Optional[str] = None
        self.last_error: Optional[str] = None

    def start(self):
        # idempotent: a second start() (warm-restore paths call it after
        # App wiring) must not spawn a second writer loop — two loops
        # would double the write cadence and race the retention prune
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="snapshotter", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            join_thread(self._thread, 5.0, "snapshotter loop")
            self._thread = None

    def notify_sweep(self):
        """Called by the audit manager after each successful sweep: the
        pack is freshly synced, the ideal capture point."""
        self._wake.set()

    def _due(self) -> bool:
        return (
            self._last_write == 0.0
            or time.perf_counter() - self._last_write >= self.interval_s
        )

    def _loop(self):
        from ..obs import brownout as _brownout

        while not self._stop.is_set():
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            if _brownout.defer_background():
                # brownout ladder level >= 1: a snapshot capture takes
                # the driver lock and serializes the pack — deferred
                # while admissions are saturated.  The wake flag is
                # already cleared; the next sweep (or the interval
                # timer) re-arms once pressure clears
                log.info("snapshot arming deferred by brownout ladder")
                continue
            if not self._due():
                continue
            self.write_once()

    def write_once(self) -> Optional[str]:
        """One guarded write attempt (also the direct call for tests and
        the bench)."""
        with obstrace.root_span("snapshot.write"):
            try:
                path = self.writer.write(self.client)
            except SnapshotError as e:
                # expected skips (no sweep yet, store mid-churn): debug only
                log.debug("snapshot skipped: %s", e)
                self.last_error = str(e)
                return None
            except Exception as e:
                log.exception("snapshot write failed")
                self.last_error = str(e)
                return None
        self._last_write = time.perf_counter()
        self.last_path = path
        self.last_error = None
        return path
