"""Status aggregation controllers (reference
pkg/controller/constraintstatus/constraintstatus_controller.go and
pkg/controller/constrainttemplatestatus/).

Every pod writes per-object ConstraintPodStatus / ConstraintTemplatePodStatus
CRs; these controllers map a status event back to its parent (via the
internal.gatekeeper.sh labels), list ALL pods' statuses for that parent, and
fold them — sorted by pod id — into the parent's status.byPod.  Statuses
whose recorded UID no longer matches the live parent are dropped (drift
detection, constraintpodstatus_types.go:44-47)."""

from __future__ import annotations

from typing import Optional

from ..apis import status as status_api
from ..kube.inmem import InMemoryKube, NotFound, WatchEvent
from ..readiness.tracker import CONSTRAINTS_GROUP
from .base import GVK, Controller

TEMPLATES_API = "templates.gatekeeper.sh/v1beta1"


class ConstraintStatusController(Controller):
    name = "constraintstatus"

    def __init__(self, kube: InMemoryKube, switch=None,
                 namespace: str = "gatekeeper-system"):
        super().__init__(switch)
        self.kube = kube
        self.namespace = namespace

    def reconcile(self, gvk: GVK, event: WatchEvent):
        labels = (event.object.get("metadata") or {}).get("labels") or {}
        kind = labels.get(status_api.CONSTRAINT_KIND_LABEL)
        name = labels.get(status_api.CONSTRAINT_NAME_LABEL)
        if not kind or not name:
            return
        cgvk = (CONSTRAINTS_GROUP, "v1beta1", kind)
        try:
            parent = self.kube.get(cgvk, name)
        except NotFound:
            return  # parent gone; nothing to fold into
        parent_uid = (parent.get("metadata") or {}).get("uid")
        by_pod = []
        for st in self.kube.list(status_api.CONSTRAINT_POD_STATUS_GVK, self.namespace):
            l = (st.get("metadata") or {}).get("labels") or {}
            if l.get(status_api.CONSTRAINT_KIND_LABEL) != kind:
                continue
            if l.get(status_api.CONSTRAINT_NAME_LABEL) != name:
                continue
            s = st.get("status") or {}
            # UID drift: status written for a deleted+recreated constraint
            if parent_uid and s.get("constraintUID") and s["constraintUID"] != parent_uid:
                continue
            by_pod.append(s)
        by_pod.sort(key=lambda s: s.get("id", ""))
        parent.setdefault("status", {})["byPod"] = by_pod
        # optimistic concurrency: a concurrent spec writer bumps the
        # resourceVersion; Conflict propagates to the controller retry
        # loop, which re-reads the fresh parent instead of clobbering it.
        # Status().Update (constraintstatus_controller.go:222).
        self.kube.update(parent, check_version=True, subresource="status")


class ConstraintTemplateStatusController(Controller):
    name = "constrainttemplatestatus"

    def __init__(self, kube: InMemoryKube, switch=None,
                 namespace: str = "gatekeeper-system"):
        super().__init__(switch)
        self.kube = kube
        self.namespace = namespace

    def reconcile(self, gvk: GVK, event: WatchEvent):
        labels = (event.object.get("metadata") or {}).get("labels") or {}
        name = labels.get(status_api.TEMPLATE_NAME_LABEL)
        if not name:
            return
        tgvk = ("templates.gatekeeper.sh", "v1beta1", "ConstraintTemplate")
        try:
            parent = self.kube.get(tgvk, name)
        except NotFound:
            return
        parent_uid = (parent.get("metadata") or {}).get("uid")
        by_pod = []
        for st in self.kube.list(status_api.TEMPLATE_POD_STATUS_GVK, self.namespace):
            l = (st.get("metadata") or {}).get("labels") or {}
            if l.get(status_api.TEMPLATE_NAME_LABEL) != name:
                continue
            s = st.get("status") or {}
            if parent_uid and s.get("templateUID") and s["templateUID"] != parent_uid:
                continue
            by_pod.append(s)
        by_pod.sort(key=lambda s: s.get("id", ""))
        parent.setdefault("status", {})
        parent["status"]["byPod"] = by_pod
        # created = every pod ingested without errors (template status
        # controller sets .status.created)
        parent["status"]["created"] = bool(by_pod) and all(
            not s.get("errors") for s in by_pod
        )
        # Status().Update (constrainttemplatestatus_controller.go:196)
        self.kube.update(parent, check_version=True, subresource="status")
