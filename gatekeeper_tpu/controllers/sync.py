"""Sync (data replication) reconciler (reference
pkg/controller/sync/sync_controller.go).

Fed by the dynamic watches the config controller installs: every event for a
synced GVK replicates the object into the engine inventory (add_data) or
removes it on deletion.  Namespaces excluded for the `sync` process are
skipped; writes for GVKs that leave the sync set are dropped
(FilteredDataClient, opadataclient.go:32-69).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Set, Tuple

from ..kube.inmem import InMemoryKube, WatchEvent
from ..process.excluder import SYNC, Excluder
from ..readiness.tracker import Tracker
from .base import GVK, Controller


class SyncController(Controller):
    name = "sync"

    def __init__(
        self,
        kube: InMemoryKube,
        client,
        excluder: Excluder,
        tracker: Optional[Tracker] = None,
        switch=None,
        reporter=None,
    ):
        super().__init__(switch)
        self.kube = kube
        self.client = client
        self.excluder = excluder
        self.tracker = tracker
        self.reporter = reporter
        self._lock = threading.Lock()
        # metrics state: per-GVK synced object counts (stats_reporter.go)
        self._counts: Dict[GVK, int] = {}
        self._synced: Set[Tuple[GVK, str, str]] = set()

    def allowed(self, gvk: GVK) -> bool:
        """FilteredDataClient: only GVKs in the registrar's current watch
        set replicate (drops late events for removed kinds)."""
        return self.registrar is None or self.registrar.watched().contains(gvk)

    def reconcile(self, gvk: GVK, event: WatchEvent):
        obj = event.object
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace") or ""
        name = meta.get("name") or ""
        key = (gvk, ns, name)
        t0 = time.monotonic()
        if not self.allowed(gvk):
            return
        if event.type == "DELETED":
            self.client.remove_data(obj)
            with self._lock:
                if key in self._synced:
                    self._synced.discard(key)
                    self._counts[gvk] = max(0, self._counts.get(gvk, 0) - 1)
            if self.tracker:
                self.tracker.for_data(gvk).cancel_expect(obj)
        else:
            if self.excluder.is_namespace_excluded(SYNC, ns):
                # excluded objects must not block readiness: the tracker
                # expected them from the raw List (sync_controller.go calls
                # CancelExpect on the skip path)
                if self.tracker:
                    self.tracker.for_data(gvk).cancel_expect(obj)
                return
            self.client.add_data(obj)
            with self._lock:
                if key not in self._synced:
                    self._synced.add(key)
                    self._counts[gvk] = self._counts.get(gvk, 0) + 1
            if self.tracker:
                self.tracker.for_data(gvk).observe(obj)
        if self.reporter:
            self.reporter.report_sync(self.counts(), time.monotonic() - t0)

    def counts(self) -> Dict[GVK, int]:
        with self._lock:
            return dict(self._counts)

    def prune(self):
        """Drop bookkeeping for GVKs that left the sync set — their engine
        data was wiped by the config controller and their DELETED events are
        filtered by allowed(), so counts would otherwise stick forever."""
        if self.registrar is None:
            return
        watched = self.registrar.watched()
        with self._lock:
            for gvk in [g for g in self._counts if not watched.contains(g)]:
                del self._counts[gvk]
            self._synced = {k for k in self._synced if watched.contains(k[0])}
        if self.reporter:
            self.reporter.report_sync(self.counts())
