"""Constraint reconciler (reference
pkg/controller/constraint/constraint_controller.go).

Type-erased: one controller serves every constraint kind; its registrar is
fed dynamically by the template controller (events carry the GVK, the
reference packs it into request names — pkg/util/pack.go).  Upsert validates
against the template-synthesized CRD schema and installs into the engine;
per-pod ConstraintPodStatus records enforcement + errors; a totals cache
feeds the `constraints` metric by (kind, enforcement action, status).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from .. import logging as gklog
from .. import util
from ..apis import status as status_api
from ..client.client import ClientError
from ..kube.inmem import InMemoryKube, NotFound, WatchEvent
from ..readiness.tracker import Tracker
from .base import GVK, Controller


class ConstraintsCache:
    """Per-(kind, action) totals for the constraints metric
    (constraint_controller.go:425-473)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache: Dict[Tuple[str, str], Dict[str, str]] = {}

    def add(self, kind: str, name: str, action: str, status: str):
        with self._lock:
            self._cache[(kind, name)] = {"action": action, "status": status}

    def remove(self, kind: str, name: str):
        with self._lock:
            self._cache.pop((kind, name), None)

    def totals(self) -> Dict[Tuple[str, str], int]:
        """-> {(enforcement_action, status): count}"""
        with self._lock:
            out: Dict[Tuple[str, str], int] = {}
            for entry in self._cache.values():
                key = (entry["action"], entry["status"])
                out[key] = out.get(key, 0) + 1
            return out


class ConstraintController(Controller):
    name = "constraint"

    def __init__(
        self,
        kube: InMemoryKube,
        client,
        tracker: Optional[Tracker] = None,
        switch=None,
        pod_id: str = "",
        namespace: str = "gatekeeper-system",
        operations=None,
        reporter=None,
        get_pod=None,
    ):
        super().__init__(switch)
        self.kube = kube
        self.client = client
        self.tracker = tracker
        self.pod_id = pod_id or util.get_id() or "pod-local"
        self.namespace = namespace
        self.operations = operations
        self.cache = ConstraintsCache()
        self.reporter = reporter
        self.get_pod = get_pod

    def reconcile(self, gvk: GVK, event: WatchEvent):
        constraint = event.object
        kind = constraint.get("kind", "")
        name = (constraint.get("metadata") or {}).get("name", "")
        if event.type == "DELETED":
            self.client.remove_constraint(constraint)
            self.cache.remove(kind, name)
            try:
                self.kube.delete(
                    status_api.CONSTRAINT_POD_STATUS_GVK,
                    status_api.key_for_constraint(self.pod_id, constraint),
                    self.namespace,
                )
            except NotFound:
                pass
            self._report()
            return

        action = util.get_enforcement_action(constraint)
        status = status_api.new_constraint_status_for_pod(
            self.pod_id, self.namespace, constraint,
            self.operations.assigned_string_list() if self.operations else [],
            owner_pod=self.get_pod() if self.get_pod else None,
        )
        try:
            self.client.add_constraint(constraint)
        except ClientError as e:
            status["status"]["errors"] = [status_api.status_error("add_error", str(e))]
            status["status"]["enforced"] = False
            self.kube.apply(status)
            self.cache.add(kind, name, action, "error")
            if self.tracker:
                # invalid constraints must not block readiness forever
                self.tracker.for_gvk(gvk).cancel_expect(constraint)
            gklog.log_event(
                self.log, "constraint ingestion failed",
                **{gklog.CONSTRAINT_KIND: kind, gklog.CONSTRAINT_NAME: name,
                   gklog.DETAILS: str(e)},
            )
            self._report()
            return

        status["status"]["enforced"] = True
        self.kube.apply(status)
        self.cache.add(kind, name, action, "active")
        if self.tracker:
            self.tracker.for_gvk(gvk).observe(constraint)
        self._report()

    def _report(self):
        if self.reporter:
            self.reporter.report_constraints(self.cache.totals())
