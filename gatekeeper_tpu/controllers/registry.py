"""Controller wiring (reference pkg/controller/controller.go:121-166 +
main.go setupControllers).

`Manager` owns the watch manager, registrars, and all reconcilers; `start()`
resets the engine client (controller.go:124-126 — device buffers and
compiled programs are a cache, rebuilt from the API server), registers the
watches, and spins the worker threads.  Controllers gated on the `status`
operation only run when assigned (constrainttemplate_controller.go:132)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .. import operations as ops_mod
from ..apis import status as status_api
from ..apis.config import GVK as CONFIG_GVK
from ..kube.inmem import InMemoryKube
from ..process.excluder import Excluder
from ..readiness.tracker import TEMPLATES_GVK, Tracker
from ..watch.manager import ControllerSwitch, WatchManager
from .config import ConfigController
from .constraint import ConstraintController
from .constrainttemplate import ConstraintTemplateController
from .status import ConstraintStatusController, ConstraintTemplateStatusController
from .sync import SyncController


@dataclass
class Dependencies:
    """controller.go:110-118 Dependencies."""

    kube: InMemoryKube
    client: object  # gatekeeper_tpu.client.Client
    excluder: Excluder = field(default_factory=Excluder)
    tracker: Optional[Tracker] = None
    switch: Optional[ControllerSwitch] = None
    operations: Optional[ops_mod.Operations] = None
    pod_id: str = "pod-local"
    namespace: str = "gatekeeper-system"
    reporter: object = None
    # () -> Pod dict or None; status CRs owner-reference this pod so they
    # are GC'd with it.  None selects the default lazy fetch
    # (controller.go:78-118 defaultPodGetter: no watch, cached once found).
    get_pod: object = None


def default_pod_getter(kube, pod_id: str, namespace: str):
    """Lazy, cached fetch of the owning Pod without creating a watch."""
    cache: list = []

    def get():
        if cache:
            return cache[0]
        try:
            pod = kube.get(("", "v1", "Pod"), pod_id, namespace)
        except Exception:
            return None
        cache.append(pod)
        return pod

    return get


class Manager:
    def __init__(self, deps: Dependencies):
        self.deps = deps
        self.switch = deps.switch or ControllerSwitch()
        self.operations = deps.operations or ops_mod.get()
        self.watch_manager = WatchManager(
            deps.kube,
            metrics_hook=(
                deps.reporter.report_gvk_count if deps.reporter else None
            ),
        )
        self.controllers: List = []

        wm = self.watch_manager
        sync_reg = wm.new_registrar("sync")
        constraint_reg = wm.new_registrar("constraint")
        template_reg = wm.new_registrar("constrainttemplate")
        config_reg = wm.new_registrar("config")

        self.sync = SyncController(
            deps.kube, deps.client, deps.excluder, deps.tracker, self.switch,
            reporter=deps.reporter,
        )
        self.sync.registrar = sync_reg

        get_pod = deps.get_pod or default_pod_getter(
            deps.kube, deps.pod_id, deps.namespace
        )
        self.constraint = ConstraintController(
            deps.kube, deps.client, deps.tracker, self.switch,
            pod_id=deps.pod_id, namespace=deps.namespace,
            operations=self.operations, reporter=deps.reporter,
            get_pod=get_pod,
        )
        self.constraint.registrar = constraint_reg

        self.template = ConstraintTemplateController(
            deps.kube, deps.client, constraint_reg, deps.tracker, self.switch,
            pod_id=deps.pod_id, namespace=deps.namespace,
            operations=self.operations, reporter=deps.reporter,
            get_pod=get_pod,
        )
        self.template.registrar = template_reg

        self.config = ConfigController(
            deps.kube, deps.client, sync_reg, deps.excluder, deps.tracker,
            self.switch, reporter=deps.reporter, sync_controller=self.sync,
        )
        self.config.registrar = config_reg

        self.controllers = [self.sync, self.constraint, self.template, self.config]

        if self.operations.is_assigned(ops_mod.STATUS):
            status_reg = wm.new_registrar("constraintstatus")
            tstatus_reg = wm.new_registrar("constrainttemplatestatus")
            self.constraint_status = ConstraintStatusController(
                deps.kube, self.switch, namespace=deps.namespace
            )
            self.constraint_status.registrar = status_reg
            self.template_status = ConstraintTemplateStatusController(
                deps.kube, self.switch, namespace=deps.namespace
            )
            self.template_status.registrar = tstatus_reg
            self.controllers += [self.constraint_status, self.template_status]

    def start(self, reset: bool = True):
        # engine state is derived; rebuild from the API server on boot.
        # reset=False is the warm-resume path (docs/snapshots.md,
        # docs/fleet.md): a successful snapshot restore already installed
        # the engine state, and the watch replay's RV/content dedup turns
        # the rebuild into a delta resync — resetting here would throw
        # the restored pack away and pay the cold path anyway.
        if reset:
            self.deps.client.reset()
        self.template.registrar.add_watch(TEMPLATES_GVK)
        self.config.registrar.add_watch(CONFIG_GVK)
        if self.operations.is_assigned(ops_mod.STATUS):
            self.constraint_status.registrar.add_watch(
                status_api.CONSTRAINT_POD_STATUS_GVK
            )
            self.template_status.registrar.add_watch(
                status_api.TEMPLATE_POD_STATUS_GVK
            )
        for c in self.controllers:
            c.start()
        if self.deps.tracker is not None:
            # objects deleted between tracker seeding and watch registration
            # never get a DELETED tombstone; collect them once now that
            # watches are live (ready_tracker.go:198-218)
            self.deps.tracker.collect(self.deps.kube)

    def stop(self):
        self.switch.stop()
        for c in self.controllers:
            c.stop()
        self.watch_manager.stop()

    def drain(self, timeout: float = 5.0) -> bool:
        """Test helper: wait until every controller queue is empty."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.watch_manager.replays_active() == 0 and all(
                c.registrar.events.empty() for c in self.controllers
            ):
                # one more tick for in-flight reconciles
                time.sleep(0.05)
                if self.watch_manager.replays_active() == 0 and all(
                    c.registrar.events.empty() for c in self.controllers
                ):
                    return True
            time.sleep(0.01)
        return False
