"""Controller runtime: the event-driven reconciler loop.

The reference builds on controller-runtime workqueues (per-controller
serialized reconcile with retry/backoff, SURVEY.md section 2.4).  Here each
controller owns a watch-manager Registrar and one worker thread draining its
event queue; reconcile errors requeue with capped exponential backoff.
Reconcile methods are plain calls so tests can drive them synchronously.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional, Tuple

from .. import logging as gklog
from ..kube.inmem import WatchEvent
from ..watch.manager import ControllerSwitch, Registrar
from ..util import join_thread

GVK = Tuple[str, str, str]

MAX_RETRIES = 5
BASE_BACKOFF = 0.01


class Controller:
    name = "controller"

    def __init__(self, switch: Optional[ControllerSwitch] = None):
        self.switch = switch
        self.log = gklog.get(self.name)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.registrar: Optional[Registrar] = None

    # ---- the reconcile seam ----------------------------------------------

    def reconcile(self, gvk: GVK, event: WatchEvent) -> None:
        raise NotImplementedError

    def process(self, gvk: GVK, event: WatchEvent) -> None:
        """One guarded reconcile: teardown gate + retry/backoff (the
        reference's workqueue semantics)."""
        if self.switch is not None and not self.switch.enter():
            return
        for attempt in range(MAX_RETRIES):
            try:
                self.reconcile(gvk, event)
                return
            except Exception:
                if attempt == MAX_RETRIES - 1:
                    self.log.exception(
                        "reconcile failed after %d attempts (%s %s)",
                        MAX_RETRIES, gvk, event.type,
                    )
                    return
                time.sleep(BASE_BACKOFF * (2**attempt))

    # ---- worker loop ------------------------------------------------------

    def start(self):
        assert self.registrar is not None, f"{self.name}: no registrar bound"
        # idempotent: a double start must not leak a second worker loop
        # draining the same registrar queue (events would split between
        # the two at random)
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"ctrl-{self.name}"
        )
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                gvk, ev = self.registrar.events.get(timeout=0.1)
            except queue.Empty:
                continue
            self.process(gvk, ev)

    def stop(self):
        self._stop.set()
        if self._thread:
            join_thread(self._thread, 2.0, f"controller {self.name}")
            self._thread = None

    def drain(self, timeout: float = 5.0):
        """Test helper: block until this controller's queue is empty."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.registrar is not None and self.registrar.events.empty():
                return True
            time.sleep(0.01)
        return False
