"""Config reconciler (reference pkg/controller/config/config_controller.go).

The Config singleton is the dynamic-config hot path: on change it
(1) swaps the process excluder from spec.match (:263),
(2) wipes all replicated engine data (:268-270),
(3) replaces the sync controller's dynamic watches with spec.sync.syncOnly
    (:278-281), and
(4) replays still-watched data via List+add_data (:294-331) so the engine
    inventory converges without waiting for organic events.
"""

from __future__ import annotations

from typing import Optional

from ..apis.config import CONFIG_NAME, parse_config
from ..kube.inmem import InMemoryKube, WatchEvent
from ..process.excluder import SYNC, Excluder
from ..readiness.tracker import Tracker
from .base import GVK, Controller


class ConfigController(Controller):
    name = "config"

    def __init__(
        self,
        kube: InMemoryKube,
        client,
        sync_registrar,
        excluder: Excluder,
        tracker: Optional[Tracker] = None,
        switch=None,
        reporter=None,
        sync_controller=None,
    ):
        super().__init__(switch)
        self.kube = kube
        self.client = client
        self.sync_registrar = sync_registrar
        self.sync_controller = sync_controller
        self.excluder = excluder
        self.tracker = tracker
        self.reporter = reporter

    def reconcile(self, gvk: GVK, event: WatchEvent):
        obj = event.object
        name = (obj.get("metadata") or {}).get("name", "")
        if name != CONFIG_NAME:
            # only the singleton is honored (pkg/keys/config.go:25)
            return
        if event.type == "DELETED":
            spec = parse_config(None)
            if self.tracker:
                # a config deleted during startup must not block readiness
                self.tracker.config.cancel_expect(obj)
        else:
            spec = parse_config(obj)

        # (1) swap the excluder
        new_ex = Excluder()
        new_ex.add(spec.match)
        if not self.excluder.equals(new_ex):
            self.excluder.replace(new_ex)

        # (2) wipe replicated data — the sync set may have shrunk
        self.client.wipe_data()

        # (3) replace dynamic watches
        sync_gvks = [e.gvk() for e in spec.sync_only]
        if self.sync_registrar is not None:
            self.sync_registrar.replace_watch(sync_gvks)
        if self.sync_controller is not None:
            self.sync_controller.prune()

        # (4) replay: list each still-watched GVK and re-add its objects
        # (the watch replay would also deliver them; doing it inline makes
        # convergence synchronous with the reconcile, as the reference does)
        for g in sync_gvks:
            for o in self.kube.list(g):
                ns = (o.get("metadata") or {}).get("namespace") or ""
                if self.excluder.is_namespace_excluded(SYNC, ns):
                    if self.tracker:
                        self.tracker.for_data(g).cancel_expect(o)
                    continue
                self.client.add_data(o)
                if self.tracker:
                    self.tracker.for_data(g).observe(o)
        if event.type != "DELETED" and self.tracker:
            self.tracker.config.observe(obj)
