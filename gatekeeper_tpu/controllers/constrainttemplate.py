"""ConstraintTemplate reconciler (reference
pkg/controller/constrainttemplate/constrainttemplate_controller.go).

Upsert: write per-pod status (uid/generation/errors), compile + install the
template into the engine (client.add_template), create/update the
constraint CRD object with an owner-ref, register a dynamic watch for the
constraint kind, observe readiness.  Compile errors land in the pod status
(ingestion_controller.go:325-342) and cancel the template's readiness
expectation — they are user errors, not reconcile failures.

Delete: unwind watch -> readiness -> engine (handleDelete, :469-485) and
delete this pod's status objects.
"""

from __future__ import annotations

import time
from typing import Optional

from .. import logging as gklog
from .. import util
from ..apis import status as status_api
from ..client.client import ClientError
from ..kube.inmem import InMemoryKube, NotFound, WatchEvent
from ..readiness.tracker import CONSTRAINTS_GROUP, TEMPLATES_GVK, Tracker
from .base import GVK, Controller

CRD_GVK = ("apiextensions.k8s.io", "v1", "CustomResourceDefinition")


class ConstraintTemplateController(Controller):
    name = "constrainttemplate"

    def __init__(
        self,
        kube: InMemoryKube,
        client,
        constraint_registrar,
        tracker: Optional[Tracker] = None,
        switch=None,
        pod_id: str = "",
        namespace: str = "gatekeeper-system",
        operations=None,
        reporter=None,
        get_pod=None,
    ):
        super().__init__(switch)
        self.kube = kube
        self.client = client
        self.constraint_registrar = constraint_registrar
        self.tracker = tracker
        self.pod_id = pod_id or util.get_id() or "pod-local"
        self.namespace = namespace
        self.operations = operations
        self.reporter = reporter
        self.get_pod = get_pod

    # ---- reconcile --------------------------------------------------------

    def reconcile(self, gvk: GVK, event: WatchEvent):
        template = event.object
        name = (template.get("metadata") or {}).get("name", "")
        if event.type == "DELETED":
            self._handle_delete(template, name)
            return
        self._handle_upsert(template, name)

    def _constraint_kind(self, template: dict) -> str:
        return (
            util.nested_get(template, "spec", "crd", "spec", "names", "kind")
            or ""
        )

    def _handle_upsert(self, template: dict, name: str):
        t0 = time.monotonic()
        status = status_api.new_template_status_for_pod(
            self.pod_id, self.namespace, template,
            self.operations.assigned_string_list() if self.operations else [],
            owner_pod=self.get_pod() if self.get_pod else None,
        )
        kind = self._constraint_kind(template)
        try:
            crd = self.client.add_template(template)
        except ClientError as e:
            # compile/validation failure: record in status, stop tracking
            status["status"]["errors"] = [
                status_api.status_error("create_error", str(e))
            ]
            self.kube.apply(status)
            if self.tracker:
                self.tracker.cancel_template(template)
            if self.reporter:
                self.reporter.report_ingestion("error", time.monotonic() - t0)
            gklog.log_event(
                self.log, "template ingestion failed",
                **{gklog.TEMPLATE_NAME: name, gklog.DETAILS: str(e)},
            )
            return

        # constraint CRD object, owner-ref'd to the template (:431-455)
        crd_obj = {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {
                "name": f"{kind.lower()}.{CONSTRAINTS_GROUP}",
                "ownerReferences": [
                    {
                        "apiVersion": template.get("apiVersion", ""),
                        "kind": "ConstraintTemplate",
                        "name": name,
                        "uid": util.nested_get(template, "metadata", "uid"),
                    }
                ],
            },
            "spec": crd.get("spec", crd),
            "status": {"conditions": [{"type": "Established", "status": "True"}]},
        }
        self.kube.apply(crd_obj)

        # dynamic watch for the constraint kind (:458, :553-561)
        if kind and self.constraint_registrar is not None:
            self.constraint_registrar.add_watch((CONSTRAINTS_GROUP, "v1beta1", kind))

        status["status"]["errors"] = []
        self.kube.apply(status)
        if self.tracker:
            self.tracker.for_gvk(TEMPLATES_GVK).observe(template)
        if self.reporter:
            self.reporter.report_ingestion("active", time.monotonic() - t0)

    def _handle_delete(self, template: dict, name: str):
        kind = self._constraint_kind(template)
        if not kind:
            # template may arrive as a bare tombstone; derive kind from name
            # (framework rule: template name == lower(kind))
            for k in self.client.templates():
                if k.lower() == name:
                    kind = k
                    break
        if kind and self.constraint_registrar is not None:
            self.constraint_registrar.remove_watch(
                (CONSTRAINTS_GROUP, "v1beta1", kind)
            )
        if self.tracker:
            self.tracker.cancel_template(template)
        if kind:
            self.client.remove_template_by_kind(kind)
            self.kube.delete(CRD_GVK, f"{kind.lower()}.{CONSTRAINTS_GROUP}")
        # delete this pod's status object (deleteAllStatus, :487-500)
        try:
            self.kube.delete(
                status_api.TEMPLATE_POD_STATUS_GVK,
                status_api.key_for_template(self.pod_id, name),
                self.namespace,
            )
        except NotFound:
            pass
