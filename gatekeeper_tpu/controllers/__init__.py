from .registry import Dependencies, Manager  # noqa: F401
