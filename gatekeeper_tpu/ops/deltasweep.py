"""Incremental (O(changes)) audit sweep state.

After one full device sweep, the per-constraint audit reduction can be
maintained incrementally: a steady-state sweep re-evaluates ONLY the rows
whose packed content changed (a [C, d] delta evaluation, d = dirty rows)
and folds the before/after candidate columns into host-side state:

  counts[ci]   — device-candidate count per constraint (same semantics as
                 the full sweep's on-device reduction)
  cand[ci]     — sorted known candidate rows, complete up to horizon[ci]
  horizon[ci]  — None when every candidate row is known (count fit within
                 the top-K prefetch at the last full sweep); else the K-th
                 candidate row index: rows beyond it are unknown territory

The full sweep's [C, R] mask stays DEVICE-resident; the delta path reads
the before-columns of newly-dirtied rows from it with one small gather
(row_cols caches the after-columns of rows dirtied earlier).  When capped
rendering exhausts the known candidates of a constraint that still has
unknown ones (NeedsFullSweep), the driver falls back to a full sweep, which
rebuilds this state.

This makes the production audit loop's cost proportional to cluster churn,
not cluster size — the reference re-evaluates everything every interval
(pkg/audit/manager.go:406-431).  It also sidesteps the measured ~30MB/s
divergence penalty the axon dev relay charges full-size re-executions
(the delta program's intermediates are [C, d], not [C, R]).
"""

from __future__ import annotations

from bisect import insort
from typing import Dict, List, Optional

import numpy as np


class NeedsFullSweep(Exception):
    """Capped rendering needs candidates beyond the known horizon."""


import atexit as _atexit
import threading as _threading
import weakref as _weakref
from ..util import join_thread

_BG_THREADS = _weakref.WeakSet()

# Set at interpreter exit (and by App.stop): long-lived cooperative
# workers (the routing-calibration loop) wait on this instead of
# sleeping, so a process that exits without App.stop() — signal, short
# CLI run — does not stall shutdown for a full sleep interval per live
# thread (advisor r4).
BG_STOP = _threading.Event()


@_atexit.register
def _join_bg_threads():
    # interpreter exit JOINS background workers first: a thread killed
    # mid-jax-dispatch aborts the runtime's teardown (observed as a gloo
    # terminate in the multi-host lane).  atexit hooks run LIFO, so this
    # one (registered after jax's import-time hooks) runs before jax
    # tears down.
    BG_STOP.set()
    for t in list(_BG_THREADS):
        join_thread(t, 120.0, "background mask resolution")


def spawn_bg(name: str, target):
    """Daemon worker for background warm-ups, joined at interpreter exit
    (see _join_bg_threads)."""
    t = _threading.Thread(target=target, daemon=True, name=name)
    _BG_THREADS.add(t)
    t.start()
    return t


class MaskSource:
    """Device-resident [C, R] base candidate mask, dispatched LAZILY and
    (on the capped path) never fetched.

    Why lazy: the capped full sweep fetches only the [C, 1+K] reduction.
    Materializing the [C, R] mask as a co-output of that fetch makes the
    relay-attached device charge the big array's transfer against the
    small fetch (~30MB/s measured — the 2.8s r3 full-resweep regression).
    Instead the mask is its own dispatch, issued only when the delta path
    (or the uncapped audit) first needs it, against the SAME committed
    device input buffers the reduction ran on — the scatter updater never
    donates, so those buffers stay valid as the base state even after
    later host-side row packs."""

    #: peek() sentinel: a background resolver owns the resolution
    BUSY = object()

    def __init__(self, thunk):
        import threading

        self._lock = threading.Lock()
        self._thunk = thunk
        self._val = None
        self._done = threading.Event()
        # flipped before the resolver thread starts (cleared by it on
        # failure) so peek() can distinguish "resolver scheduled" from
        # "nobody is resolving" — without it a caller racing
        # Thread.start() would pay the whole trace/compile synchronously
        self._resolving = False

    @classmethod
    def resolved(cls, val):
        src = cls(None)
        src._val = val
        src._done.set()
        return src

    def get(self):
        with self._lock:
            if self._val is None:
                try:
                    self._val = self._thunk()
                except Exception:
                    # wake peek() waiters: _val stays None and _resolving
                    # clears, so they fall into the contained sync-get
                    # path instead of sleeping out the full timeout
                    self._done.set()
                    raise
                finally:
                    self._resolving = False
                self._thunk = None
                self._done.set()
            return self._val

    def peek(self, wait_s: float = 0.0):
        """The mask if it resolves within wait_s; None if unresolved with
        no resolver running (the caller should get() synchronously); BUSY
        when a background resolver is still working past wait_s (the
        caller should fall back to a full sweep rather than block behind
        the trace/compile)."""
        if self._done.wait(wait_s if self._resolving else 0):
            return self._val
        return self.BUSY if self._resolving else None

    def prefetch(self, after=None):
        """Resolve on a daemon thread: the mask executable's trace/compile
        (and its dispatch) happen in the background right after the full
        sweep instead of landing on the first delta sweep's latency.
        `after(mask)` runs on the same thread once resolved (best-effort;
        used to warm downstream executables against the mask)."""
        self._resolving = True

        def run():
            try:
                val = self.get()
            except Exception:
                return  # next get() retries; peek no longer reports BUSY
            if after is not None:
                try:
                    after(val)
                except Exception:
                    # the after-hook warms downstream executables; a
                    # defect there costs the warm start, not correctness
                    # — but it must be visible when it happens
                    import logging

                    logging.getLogger("gatekeeper.deltasweep").warning(
                        "mask prefetch after-hook failed", exc_info=True,
                    )

        spawn_bg("gk-mask-prefetch", run)


class DeltaState:
    """Host-side incremental reduction state for one (constraint side,
    pack layout) generation.  All access under the driver lock."""

    def __init__(self, counts: np.ndarray, topk: np.ndarray, K: int,
                 mask_src: "MaskSource", cs_epoch: int, layout_gen: int,
                 store_epoch: int, crow=None, mesh_width: int = 1):
        self.K = K
        # topology stamp: the basis's mask placement is only valid under
        # the sweep sharding it was produced by (driver._try_delta refuses
        # a drifted basis and rebases via a full sweep)
        self.mesh_width = int(mesh_width)
        self.counts = counts.astype(np.int64).copy()
        self.cand: List[List[int]] = []
        self.horizon: List[Optional[int]] = []
        for ci in range(len(counts)):
            idxs = [int(r) for r in topk[ci] if r >= 0]
            self.cand.append(idxs)  # ascending (stable top_k of 0/1 mask)
            if counts[ci] <= len(idxs):
                self.horizon.append(None)  # complete knowledge
            else:
                self.horizon.append(idxs[-1] if idxs else -1)
        # after-columns of rows dirtied since the full sweep; the
        # before-column of a newly-dirtied row is gathered from mask_dev
        self.row_cols: Dict[int, np.ndarray] = {}
        # lazily-fetched host copy of the base mask for the UNCAPPED audit
        # path: fetched once per state generation, then kept current by
        # overwriting only the columns dirtied since the last patch
        # (pending_mask_rows; absolute values, so patching is idempotent)
        self.host_mask: Optional[np.ndarray] = None
        self.pending_mask_rows: set = set()
        # per-constraint rendered-result reuse across sweeps, keyed by the
        # (count, candidates, row generations) signature (driver
        # _render_capped); traced renders bypass it
        self.render_cache: Dict = {}
        self.mask_src = mask_src
        # ordered-constraint -> group-major mask row (device mask/delta
        # outputs are [C_total]-row; host state here is per ordered
        # constraint)
        self.crow = crow if crow is not None else np.arange(
            len(counts), dtype=np.int64)
        self.cs_epoch = cs_epoch
        self.layout_gen = layout_gen
        self.store_epoch = store_epoch

    @classmethod
    def from_restore(cls, counts, cand, horizon, crow, K, mask_src,
                     row_cols, render_cache, cs_epoch, layout_gen,
                     store_epoch, mesh_width: int = 1):
        """Rebuild a state persisted by the snapshot subsystem
        (gatekeeper_tpu/snapshot/): fields are installed verbatim rather
        than derived from a fresh device reduction, so a restarted
        process's first capped sweep can run the O(churn) delta path
        against the restored basis instead of a full [C, R] dispatch."""
        st = cls.__new__(cls)
        st.K = K
        st.counts = np.asarray(counts, np.int64).copy()
        st.cand = [list(map(int, c)) for c in cand]
        st.horizon = list(horizon)
        st.row_cols = dict(row_cols)
        st.host_mask = None
        st.pending_mask_rows = set()
        st.render_cache = dict(render_cache)
        st.mask_src = mask_src
        st.crow = np.asarray(crow, np.int64)
        st.cs_epoch = cs_epoch
        st.layout_gen = layout_gen
        st.store_epoch = store_epoch
        st.mesh_width = int(mesh_width)
        return st

    # ---- incremental update ----------------------------------------------

    def old_column(self, r: int) -> Optional[np.ndarray]:
        """The current candidate column for row r, or None when it must be
        gathered from the resident full-sweep mask."""
        return self.row_cols.get(r)

    def apply_row(self, r: int, old_col: np.ndarray, new_col: np.ndarray):
        delta = new_col.astype(np.int64) - old_col.astype(np.int64)
        changed = np.nonzero(delta)[0]
        self.counts[changed] += delta[changed]
        for ci in changed:
            h = self.horizon[ci]
            lst = self.cand[ci]
            if h is not None and r > h:
                continue  # beyond known territory; counts tracked only
            if delta[ci] < 0:
                try:
                    lst.remove(r)
                except ValueError:
                    pass
            else:
                insort(lst, r)
        self.row_cols[r] = new_col.astype(bool)
        self.pending_mask_rows.add(r)
