"""Incremental (O(changes)) audit sweep state.

After one full device sweep, the per-constraint audit reduction can be
maintained incrementally: a steady-state sweep re-evaluates ONLY the rows
whose packed content changed (a [C, d] delta evaluation, d = dirty rows)
and folds the before/after candidate columns into host-side state:

  counts[ci]   — device-candidate count per constraint (same semantics as
                 the full sweep's on-device reduction)
  cand[ci]     — sorted known candidate rows, complete up to horizon[ci]
  horizon[ci]  — None when every candidate row is known (count fit within
                 the top-K prefetch at the last full sweep); else the K-th
                 candidate row index: rows beyond it are unknown territory

The full sweep's [C, R] mask stays DEVICE-resident; the delta path reads
the before-columns of newly-dirtied rows from it with one small gather
(row_cols caches the after-columns of rows dirtied earlier).  When capped
rendering exhausts the known candidates of a constraint that still has
unknown ones (NeedsFullSweep), the driver falls back to a full sweep, which
rebuilds this state.

This makes the production audit loop's cost proportional to cluster churn,
not cluster size — the reference re-evaluates everything every interval
(pkg/audit/manager.go:406-431).  It also sidesteps the measured ~30MB/s
divergence penalty the axon dev relay charges full-size re-executions
(the delta program's intermediates are [C, d], not [C, R]).
"""

from __future__ import annotations

from bisect import insort
from typing import Dict, List, Optional

import numpy as np


class NeedsFullSweep(Exception):
    """Capped rendering needs candidates beyond the known horizon."""


class DeltaState:
    """Host-side incremental reduction state for one (constraint side,
    pack layout) generation.  All access under the driver lock."""

    def __init__(self, counts: np.ndarray, topk: np.ndarray, K: int,
                 mask_dev, cs_epoch: int, layout_gen: int, store_epoch: int):
        self.K = K
        self.counts = counts.astype(np.int64).copy()
        self.cand: List[List[int]] = []
        self.horizon: List[Optional[int]] = []
        for ci in range(len(counts)):
            idxs = [int(r) for r in topk[ci] if r >= 0]
            self.cand.append(idxs)  # ascending (stable top_k of 0/1 mask)
            if counts[ci] <= len(idxs):
                self.horizon.append(None)  # complete knowledge
            else:
                self.horizon.append(idxs[-1] if idxs else -1)
        # after-columns of rows dirtied since the full sweep; the
        # before-column of a newly-dirtied row is gathered from mask_dev
        self.row_cols: Dict[int, np.ndarray] = {}
        # lazily-fetched host copy of the base mask for the UNCAPPED audit
        # path: fetched once per state generation, then kept current by
        # overwriting only the columns dirtied since the last patch
        # (pending_mask_rows; absolute values, so patching is idempotent)
        self.host_mask: Optional[np.ndarray] = None
        self.pending_mask_rows: set = set()
        # per-constraint rendered-result reuse across sweeps, keyed by the
        # (count, candidates, row generations) signature (driver
        # _render_capped); traced renders bypass it
        self.render_cache: Dict = {}
        self.mask_dev = mask_dev
        self.cs_epoch = cs_epoch
        self.layout_gen = layout_gen
        self.store_epoch = store_epoch

    # ---- incremental update ----------------------------------------------

    def old_column(self, r: int) -> Optional[np.ndarray]:
        """The current candidate column for row r, or None when it must be
        gathered from the resident full-sweep mask."""
        return self.row_cols.get(r)

    def apply_row(self, r: int, old_col: np.ndarray, new_col: np.ndarray):
        delta = new_col.astype(np.int64) - old_col.astype(np.int64)
        changed = np.nonzero(delta)[0]
        self.counts[changed] += delta[changed]
        for ci in changed:
            h = self.horizon[ci]
            lst = self.cand[ci]
            if h is not None and r > h:
                continue  # beyond known territory; counts tracked only
            if delta[ci] < 0:
                try:
                    lst.remove(r)
                except ValueError:
                    pass
            else:
                insort(lst, r)
        self.row_cols[r] = new_col.astype(bool)
        self.pending_mask_rows.add(r)
