"""TpuDriver: the vectorized JAX/XLA evaluation backend.

Pipeline per Review/Audit:
  1. pack reviews + constraints to integer tensors (host, incremental interner)
  2. device: match kernel -> bool[C, R]; per-kind violation programs
     (vectorizer output) -> bool[C_k, R]; combined candidate mask
  3. host: for each positive cell, exact native match re-check + interpreter
     violation rendering (messages/details) — the over-approximation filter

Correctness therefore never depends on the device mask being tight — only
throughput does.  Templates with no vectorized program get all-true columns
(pure interpreter fallback for their cells).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..client.drivers import CompiledTemplate, InterpDriver, Result
from ..target.match import constraint_matches, needs_autoreject
from ..target.target import K8sValidationTarget
from .columns import extract_columns
from .interning import Interner, PredicateTable
from .matchkernel import match_kernel
from .pack import pack_constraints, pack_reviews
from .params import pack_params
from .vectorizer import vectorize
from .vexpr import EvalEnv, VProgram, eval_program


class TpuDriver(InterpDriver):
    """Drop-in Driver with device-side batched evaluation.  Inherits state
    management (templates/constraints/store) and render fallback from
    InterpDriver."""

    def __init__(self, target: Optional[K8sValidationTarget] = None):
        super().__init__(target)
        # eager native build/load: the g++ compile must happen here, not
        # inside the first admission review under the driver lock
        from ..native import load as _load_native

        _load_native()
        self.interner = Interner()
        self.programs: Dict[str, Optional[VProgram]] = {}
        self.pred_cache: Dict[Tuple[str, str], PredicateTable] = {}
        self._fused = None
        self._fused_key = None
        # multi-chip: data-parallel mesh over every visible device (None on
        # single-chip).  GK_MESH=0 forces the single-device path; tests pin
        # bit-parity between both settings.
        self.mesh_enabled = os.environ.get("GK_MESH", "1") != "0"
        self._mesh_cache: Optional[tuple] = None
        # device placement of the replicated constraint side (mesh path):
        # re-uploading vocab-sized tables to N chips every call would cost
        # N RTTs behind a network relay; cached on the constraint epoch
        self._cs_device_cache = None
        # constraint-side packing is invalidated on any template/constraint
        # mutation and on vocabulary growth (str-pred tables are vocab-sized)
        self._cs_epoch = 0
        self._cs_cache = None
        # audit-side packing cache: the production audit loop sweeps a
        # mostly-unchanged inventory every interval; packing is skipped
        # entirely while the store epoch and constraint side are unchanged
        self._audit_cache = None

    # ---- lifecycle --------------------------------------------------------

    def put_template(self, kind: str, artifact: CompiledTemplate):
        super().put_template(kind, artifact)
        self.programs[kind] = vectorize(artifact.policy)
        self._cs_epoch += 1

    def delete_template(self, kind: str) -> bool:
        self.programs.pop(kind, None)
        self._cs_epoch += 1
        return super().delete_template(kind)

    def put_constraint(self, kind: str, name: str, constraint: dict):
        super().put_constraint(kind, name, constraint)
        self._cs_epoch += 1

    def delete_constraint(self, kind: str, name: str) -> bool:
        self._cs_epoch += 1
        return super().delete_constraint(kind, name)

    def reset(self):
        super().reset()
        self.programs.clear()
        self._cs_epoch += 1
        self._cs_cache = None
        self._cs_device_cache = None
        self._fused = None
        self._fused_key = None

    # ---- device evaluation ------------------------------------------------

    def _ordered_constraints(self) -> List[Tuple[str, str, dict]]:
        out = []
        for kind in sorted(self.constraints):
            for name in sorted(self.constraints[kind]):
                out.append((kind, name, self.constraints[kind][name]))
        return out

    def _constraint_side(self):
        """Cached constraint-side packing: match pack + violation-program
        groups.  Programs are grouped by STRUCTURE, so template clones (the
        synthetic 500-template config) share one traced subgraph with their
        constraints batched on the C axis.  Rebuilt when constraints or
        templates change, or when the vocabulary has grown (str-pred tables
        are vocab-sized)."""
        ordered = self._ordered_constraints()
        vocab = self.interner.snapshot_size()
        key = (self._cs_epoch, vocab)
        if self._cs_cache and self._cs_cache[0] == key:
            return self._cs_cache[1]

        cp = pack_constraints([c for _k, _n, c in ordered], self.interner)
        specs = {}
        by_struct: Dict[str, list] = {}
        for i, (kind, _n, _c) in enumerate(ordered):
            prog = self.programs.get(kind)
            if not prog:
                continue
            sk = prog.structure_key()
            by_struct.setdefault(sk, [prog, []])[1].append(i)
        groups = []
        for _sk, (prog, idxs) in sorted(by_struct.items()):
            for spec in prog.column_specs:
                specs[spec.key] = spec
            kcs = [ordered[i][2] for i in idxs]
            packed = pack_params(kcs, prog, self.interner, self.pred_cache, len(kcs))
            groups.append((prog, np.asarray(idxs, np.int32), packed))
        side = (ordered, cp, groups, list(specs.values()))
        # key uses the vocab size BEFORE param packing interned new strings;
        # recompute so the cache stays valid next call
        key = (self._cs_epoch, self.interner.snapshot_size())
        self._cs_cache = (key, side)
        return side

    def _fused_fn(self):
        """One jitted function for the whole sweep: match kernel + every
        violation-program group, combined into the candidate mask.  ONE
        dispatch and ONE device->host fetch per evaluation — essential when
        the device sits behind a network relay (each fetch is an RTT)."""
        side = self._constraint_side()
        # Keyed on the epoch only: vocabulary growth re-packs arrays but the
        # table shapes are bucketed (ops/params.py), so the compiled
        # executable survives new strings.
        if self._fused is not None and self._fused_key == self._cs_epoch:
            return self._fused, side
        _ordered, _cp, groups, _col_specs = side
        static = [(prog, idxs) for prog, idxs, _packed in groups]

        def fused(rv, cs, cols, group_params):
            match, autoreject = match_kernel(rv, cs)
            mask = match
            R = match.shape[1]
            for (prog, idxs), (params, elems, tables) in zip(static, group_params):
                keysets = {
                    spec.key: cols[spec.key]["ids"]
                    for spec in prog.column_specs
                    if spec.kind == "keyset"
                }
                prog_cols = {
                    spec.key: cols[spec.key]
                    for spec in prog.column_specs
                    if spec.kind != "keyset"
                }
                env = EvalEnv(
                    prog_cols, params, elems, tables, keysets, len(idxs), R
                )
                vmask = eval_program(prog, env)  # [Ck, R]
                mask = mask.at[idxs].set(mask[idxs] & vmask)
            return mask, autoreject

        self._fused = jax.jit(fused)
        self._fused_key = self._cs_epoch
        return self._fused, side

    def _device_inputs(self, reviews: List[dict]):
        """Pack review-side arrays + columns; rebuild the constraint side if
        these reviews interned new strings (pred tables are vocab-sized)."""
        fn, side = self._fused_fn()
        ordered, cp, groups, col_specs = side
        rp = pack_reviews(reviews, self.interner, self.store.cached_namespace)
        rows = len(rp.arrays["valid"])
        cols = extract_columns(reviews, col_specs, self.interner, rows)
        if self.interner.snapshot_size() > self._cs_cache[0][1]:
            fn, side = self._fused_fn()
            ordered, cp, groups, col_specs = side
        group_params = [packed for _prog, _idxs, packed in groups]
        return fn, ordered, rp, cp, cols, group_params

    def _mesh(self):
        """The production device mesh: all visible devices, data-parallel on
        the resource axis (parallel/mesh.py).  None on single-chip or when
        mesh_enabled is off."""
        if not self.mesh_enabled:
            return None
        if self._mesh_cache is None:
            from ..parallel.mesh import maybe_audit_mesh

            self._mesh_cache = (maybe_audit_mesh(),)
        return self._mesh_cache[0]

    def compute_masks(self, reviews: List[dict]):
        """-> (ordered constraints, match&violation candidate mask [C, R],
        autoreject mask [C, R]) as numpy arrays.

        Multi-chip: when a mesh is available the row axis is padded to a
        mesh multiple and committed sharded (input placement drives the
        SPMD compile of the SAME fused jit); results come back trimmed so
        callers see identical shapes on 1 or N devices."""
        fn, ordered, rp, cp, cols, group_params = self._device_inputs(reviews)
        rows = len(rp.arrays["valid"])
        args = (rp.arrays, cp.arrays, cols, group_params)
        mesh = self._mesh()
        if mesh is not None:
            from ..parallel.mesh import replicate_tree, shard_review_side

            key = (self._cs_epoch, self.interner.snapshot_size(), id(mesh))
            if self._cs_device_cache and self._cs_device_cache[0] == key:
                cs_p, gp_p = self._cs_device_cache[1]
            else:
                cs_p, gp_p = replicate_tree(mesh, (cp.arrays, group_params))
                self._cs_device_cache = (key, (cs_p, gp_p))
            rv_p, cols_p, _target = shard_review_side(
                mesh, rows, rp.arrays, cols
            )
            with mesh:
                mask, autoreject = fn(rv_p, cs_p, cols_p, gp_p)
        else:
            mask, autoreject = fn(*args)
        both = np.asarray(jnp.stack([mask, autoreject]))  # one fetch
        return ordered, both[0][:, :rows], both[1][:, :rows]

    # ---- render (exactness filter) ---------------------------------------

    def _render_cell(
        self,
        results: List[Result],
        constraint: dict,
        kind: str,
        review: dict,
        frozen_review,
        inventory,
        tracing_log,
    ):
        from ..engine.value import freeze

        tmpl = self.templates.get(kind)
        if tmpl is None:
            return
        if not constraint_matches(constraint, review, self.store.cached_namespace):
            return  # device over-approximation filtered here
        params = (constraint.get("spec") or {}).get("parameters") or {}
        violations = tmpl.policy.eval_violations(
            frozen_review, freeze(params), inventory
        )
        action = self._enforcement_action(constraint)
        for v in violations:
            results.append(
                Result(
                    msg=str(v.get("msg", "")),
                    metadata={"details": v.get("details", {})},
                    constraint=constraint,
                    review=review,
                    enforcement_action=action,
                )
            )
            if tracing_log is not None:
                tracing_log.append(
                    f"violation {kind}/{constraint['metadata']['name']}: {v.get('msg')}"
                )

    def review(self, review: dict, tracing: bool = False):
        return self.review_batch([review], tracing=tracing)[0]

    # Below this many constraint x review cells the device dispatch costs
    # more than it saves (kernel launch + host<->device transfer — or a
    # full network RTT when the chip sits behind a relay); small batches
    # evaluate host-side with the exact native matcher + interpreter.
    DEVICE_MIN_CELLS = int(os.environ.get("GK_DEVICE_MIN_CELLS", "4096"))

    def review_batch(self, reviews: List[dict], tracing: bool = False):
        """N concurrent admission reviews in ONE device dispatch: the mask
        is [C, N], then each review's positive cells render host-side.
        This is the micro-batching seam the webhook server drives.

        Hybrid dispatch: batches too small to amortize a device call run
        through the interpreter path (identical semantics — the device mask
        is only ever a pruning over-approximation of it)."""
        from ..engine.value import freeze

        if not reviews:
            return []
        n_constraints = sum(len(v) for v in self.constraints.values())
        if len(reviews) * max(n_constraints, 1) < self.DEVICE_MIN_CELLS:
            return [
                InterpDriver.review(self, r, tracing=tracing) for r in reviews
            ]
        with self._lock:
            ordered, mask, autoreject = self.compute_masks(reviews)
            inventory = self.store.frozen()
            out = []
            for ri, review in enumerate(reviews):
                frozen_review = freeze(review)
                results: List[Result] = []
                trace: List[str] = [] if tracing else None
                for i, (kind, name, constraint) in enumerate(ordered):
                    if autoreject[i, ri]:
                        if needs_autoreject(constraint, review, self.store.cached_namespace):
                            results.append(
                                Result(
                                    msg="Namespace is not cached in OPA.",
                                    metadata={"details": {}},
                                    constraint=constraint,
                                    review=review,
                                    enforcement_action=self._enforcement_action(constraint),
                                )
                            )
                            if tracing:
                                trace.append(f"autoreject {kind}/{name}")
                    if mask[i, ri]:
                        self._render_cell(
                            results, constraint, kind, review, frozen_review,
                            inventory, trace,
                        )
                out.append((results, "\n".join(trace) if tracing else None))
            return out

    def _audit_masks(self):
        """Packed audit sweep with epoch caching: reviews + device inputs
        are rebuilt only when the inventory or constraint side changed."""
        from ..engine.value import thaw

        key = (self.store.epoch, self._cs_epoch)
        if self._audit_cache and self._audit_cache[0] == key:
            _key, reviews, ordered, mask = self._audit_cache
            return reviews, ordered, mask
        objs = list(self.store.iter_objects())
        reviews = []
        for obj_frozen, api, kind_name, name, ns in objs:
            obj = thaw(obj_frozen)
            reviews.append(
                self.target.make_audit_review(obj, api, kind_name, name, ns)
            )
        if not reviews:
            return [], [], None
        ordered, mask, _autoreject = self.compute_masks(reviews)
        # re-read the epochs: packing may have interned new strings and
        # bumped the constraint-side cache, but the INPUTS are these epochs'
        self._audit_cache = (key, reviews, ordered, mask)
        return reviews, ordered, mask

    def audit(self, tracing: bool = False):
        from ..engine.value import freeze

        with self._lock:
            reviews, ordered, mask = self._audit_masks()
            if not reviews:
                return [], ("" if tracing else None)
            inventory = self.store.frozen()
            results: List[Result] = []
            trace: List[str] = [] if tracing else None
            # resource-major order, matching InterpDriver.audit; only
            # reviews with a positive cell pay the freeze + render cost
            hot_reviews = np.nonzero(mask.any(axis=0))[0]
            for ri in hot_reviews:
                review = reviews[ri]
                frozen_review = freeze(review)
                for i in np.nonzero(mask[:, ri])[0]:
                    kind, _name, constraint = ordered[i]
                    self._render_cell(
                        results, constraint, kind, review, frozen_review,
                        inventory, trace,
                    )
            return results, ("\n".join(trace) if tracing else None)
