"""TpuDriver: the vectorized JAX/XLA evaluation backend.

Pipeline per Review/Audit:
  1. pack reviews + constraints to integer tensors (host, incremental interner)
  2. device: match kernel -> bool[C, R]; per-kind violation programs
     (vectorizer output) -> bool[C_k, R]; combined candidate mask
  3. host: for each positive cell, exact native match re-check + interpreter
     violation rendering (messages/details) — the over-approximation filter

Correctness therefore never depends on the device mask being tight — only
throughput does.  Templates with no vectorized program get all-true columns
(pure interpreter fallback for their cells).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..client.drivers import CompiledTemplate, InterpDriver, Result
from ..target.match import constraint_matches, needs_autoreject
from ..target.target import K8sValidationTarget
from .columns import extract_columns
from .interning import Interner, PredicateTable
from .matchkernel import match_kernel
from .pack import pack_constraints, pack_reviews
from .params import pack_params
from .vectorizer import vectorize
from .vexpr import EvalEnv, VProgram, eval_program


class TpuDriver(InterpDriver):
    """Drop-in Driver with device-side batched evaluation.  Inherits state
    management (templates/constraints/store) and render fallback from
    InterpDriver."""

    def __init__(
        self,
        target: Optional[K8sValidationTarget] = None,
        async_compile: Optional[bool] = None,
    ):
        super().__init__(target)
        # eager native build/load: the g++ compile must happen here, not
        # inside the first admission review under the driver lock
        from ..native import load as _load_native

        _load_native()
        self.interner = Interner()
        self.programs: Dict[str, Optional[VProgram]] = {}
        self.pred_cache: Dict[Tuple[str, str], PredicateTable] = {}
        self._fused = None
        self._fused_key = None
        # multi-chip: data-parallel mesh over every visible device (None on
        # single-chip).  GK_MESH=0 forces the single-device path; tests pin
        # bit-parity between both settings.
        self.mesh_enabled = os.environ.get("GK_MESH", "1") != "0"
        self._mesh_cache: Optional[tuple] = None
        # device placement of the replicated constraint side (mesh path):
        # re-uploading vocab-sized tables to N chips every call would cost
        # N RTTs behind a network relay; cached on the constraint epoch
        self._cs_device_cache = None
        # resident incremental audit packing (ops/auditpack.py) + rendered
        # cell memo: violations for an unchanged (constraint, row) pair are
        # deterministic unless the template reads data.inventory
        from .auditpack import AuditPackCache

        self._audit_pack = AuditPackCache()
        self._render_memo: Dict[Tuple, Tuple[int, list]] = {}
        self._render_memo_epoch = -1
        # constraint-side packing is invalidated on any template/constraint
        # mutation and on vocabulary growth (str-pred tables are vocab-sized)
        self._cs_epoch = 0
        self._cs_cache = None
        # audit-side packing cache: the production audit loop sweeps a
        # mostly-unchanged inventory every interval; packing is skipped
        # entirely while the store epoch and constraint side are unchanged
        self._audit_cache = None
        # async ingestion (SURVEY §7 hard-part 3): template/constraint
        # mutations hand the XLA re-compile to a background thread and
        # reviews serve from the interpreter until the new fused
        # executable is warm (ops/asynccompile.py)
        self._compiler = None
        if async_compile is None:
            async_compile = os.environ.get("GK_ASYNC_COMPILE", "0") == "1"
        if async_compile:
            from .asynccompile import AsyncCompiler

            self._compiler = AsyncCompiler(self)

    # ---- lifecycle --------------------------------------------------------

    def _epoch_bumped(self):
        if self._compiler is not None:
            self._compiler.kick()

    # Audit-path compile wait: long enough that no realistic template storm
    # (bench: 500 templates ≈ tens of seconds) ever falls through to the
    # synchronous compile under the driver lock (advisor r2), but bounded so
    # pathological epoch churn (mutations forever outpacing compiles) cannot
    # wedge the audit loop permanently.
    AUDIT_COMPILE_WAIT_S = 600.0

    def wait_ready(self, timeout: Optional[float] = 120.0) -> bool:
        """Block until the fused executable for the current constraint-side
        epoch is compiled (no-op when async compile is off).  timeout=None
        waits indefinitely."""
        if self._compiler is None:
            return True
        return self._compiler.wait(timeout)

    def _wait_ready_for_audit(self):
        import time

        t0 = time.monotonic()
        if not self.wait_ready(timeout=self.AUDIT_COMPILE_WAIT_S):
            import logging

            waited = time.monotonic() - t0
            stopped = self._compiler is not None and self._compiler._stopped
            logging.getLogger("gatekeeper_tpu.driver").warning(
                "audit waited %.1fs for the background compile without it "
                "becoming ready (%s); proceeding with a synchronous compile "
                "under the driver lock",
                waited,
                "compiler stopped" if stopped
                else "sustained template/constraint churn?",
            )

    def put_template(self, kind: str, artifact: CompiledTemplate):
        # all mutators hold the driver lock for their FULL body (the async
        # compiler snapshots under this lock) and bump the epoch last, so a
        # kicked compile never sees half-applied state
        with self._lock:
            super().put_template(kind, artifact)
            self.programs[kind] = vectorize(artifact.policy)
            self._cs_epoch += 1
        self._epoch_bumped()

    def delete_template(self, kind: str) -> bool:
        with self._lock:
            self.programs.pop(kind, None)
            out = super().delete_template(kind)
            self._cs_epoch += 1
        self._epoch_bumped()
        return out

    def put_constraint(self, kind: str, name: str, constraint: dict):
        with self._lock:
            super().put_constraint(kind, name, constraint)
            self._cs_epoch += 1
        self._epoch_bumped()

    def delete_constraint(self, kind: str, name: str) -> bool:
        with self._lock:
            out = super().delete_constraint(kind, name)
            self._cs_epoch += 1
        self._epoch_bumped()
        return out

    def reset(self):
        with self._lock:
            super().reset()
            self.programs.clear()
            self._cs_cache = None
            self._cs_device_cache = None
            self._fused = None
            self._fused_key = None
            from .auditpack import AuditPackCache

            self._audit_pack = AuditPackCache()
            self._render_memo.clear()
            self._cs_epoch += 1
        self._epoch_bumped()

    # ---- device evaluation ------------------------------------------------

    def _ordered_constraints(self) -> List[Tuple[str, str, dict]]:
        out = []
        for kind in sorted(self.constraints):
            for name in sorted(self.constraints[kind]):
                out.append((kind, name, self.constraints[kind][name]))
        return out

    def _constraint_side(self):
        """Cached constraint-side packing: match pack + violation-program
        groups.  Programs are grouped by STRUCTURE, so template clones (the
        synthetic 500-template config) share one traced subgraph with their
        constraints batched on the C axis.  Rebuilt when constraints or
        templates change, or when the vocabulary has grown (str-pred tables
        are vocab-sized)."""
        ordered = self._ordered_constraints()
        vocab = self.interner.snapshot_size()
        key = (self._cs_epoch, vocab)
        if self._cs_cache and self._cs_cache[0] == key:
            return self._cs_cache[1]

        cp = pack_constraints([c for _k, _n, c in ordered], self.interner)
        specs = {}
        by_struct: Dict[str, list] = {}
        for i, (kind, _n, _c) in enumerate(ordered):
            prog = self.programs.get(kind)
            if not prog:
                continue
            sk = prog.structure_key()
            by_struct.setdefault(sk, [prog, []])[1].append(i)
        groups = []
        for _sk, (prog, idxs) in sorted(by_struct.items()):
            for spec in prog.column_specs:
                specs[spec.key] = spec
            kcs = [ordered[i][2] for i in idxs]
            packed = pack_params(kcs, prog, self.interner, self.pred_cache, len(kcs))
            groups.append((prog, np.asarray(idxs, np.int32), packed))
        side = (ordered, cp, groups, list(specs.values()))
        # key uses the vocab size BEFORE param packing interned new strings;
        # recompute so the cache stays valid next call
        key = (self._cs_epoch, self.interner.snapshot_size())
        self._cs_cache = (key, side)
        return side

    def _fused_fn(self):
        """One jitted function for the whole sweep: match kernel + every
        violation-program group, combined into the candidate mask.  ONE
        dispatch and ONE device->host fetch per evaluation — essential when
        the device sits behind a network relay (each fetch is an RTT)."""
        side = self._constraint_side()
        # Keyed on the epoch only: vocabulary growth re-packs arrays but the
        # table shapes are bucketed (ops/params.py), so the compiled
        # executable survives new strings.
        if self._fused is not None and self._fused_key == self._cs_epoch:
            return self._fused, side
        _ordered, _cp, groups, _col_specs = side
        static = [(prog, idxs) for prog, idxs, _packed in groups]

        def fused(rv, cs, cols, group_params):
            match, autoreject = match_kernel(rv, cs)
            mask = match
            R = match.shape[1]
            for (prog, idxs), (params, elems, tables) in zip(static, group_params):
                keysets = {
                    spec.key: cols[spec.key]["ids"]
                    for spec in prog.column_specs
                    if spec.kind == "keyset"
                }
                prog_cols = {
                    spec.key: cols[spec.key]
                    for spec in prog.column_specs
                    if spec.kind != "keyset"
                }
                env = EvalEnv(
                    prog_cols, params, elems, tables, keysets, len(idxs), R
                )
                vmask = eval_program(prog, env)  # [Ck, R]
                mask = mask.at[idxs].set(mask[idxs] & vmask)
            return mask, autoreject

        self._fused = jax.jit(fused)
        self._fused_key = self._cs_epoch
        return self._fused, side

    def _repack_if_vocab_grew(self, fn, side):
        """Row packing may have interned new strings; constraint-side string
        predicate tables are vocab-sized, so re-pack them if so.  Shared by
        the review and audit input paths — the invalidation rule must stay
        identical between them."""
        if self.interner.snapshot_size() > self._cs_cache[0][1]:
            return self._fused_fn()
        return fn, side

    def _device_inputs(self, reviews: List[dict]):
        """Pack review-side arrays + columns; rebuild the constraint side if
        these reviews interned new strings (pred tables are vocab-sized)."""
        fn, side = self._fused_fn()
        _ordered, _cp, _groups, col_specs = side
        rp = pack_reviews(reviews, self.interner, self.store.cached_namespace)
        rows = len(rp.arrays["valid"])
        cols = extract_columns(reviews, col_specs, self.interner, rows)
        fn, side = self._repack_if_vocab_grew(fn, side)
        ordered, cp, groups, _col_specs = side
        group_params = [packed for _prog, _idxs, packed in groups]
        return fn, ordered, rp, cp, cols, group_params

    def _mesh(self):
        """The production device mesh: all visible devices, data-parallel on
        the resource axis (parallel/mesh.py).  None on single-chip or when
        mesh_enabled is off."""
        if not self.mesh_enabled:
            return None
        if self._mesh_cache is None:
            from ..parallel.mesh import maybe_audit_mesh

            self._mesh_cache = (maybe_audit_mesh(),)
        return self._mesh_cache[0]

    def _dispatch(self, fn, rv_arrays, cp_arrays, cols, group_params, rows,
                  cs_key=None):
        """Call a fused device function with mesh-aware placement: on a
        multi-chip mesh the review side is padded + sharded on "data" and
        the replicated constraint side is served from the epoch-keyed device
        cache (re-uploading vocab-sized tables to N chips every call would
        cost N RTTs behind a network relay).

        cs_key: (cs_epoch, vocab) the inputs were packed for, captured under
        the driver lock.  The async compile thread dispatches UNLOCKED, so
        reading self._cs_epoch here could key stale constraint arrays under
        a newer epoch (advisor r2); callers that hold the lock may omit it."""
        mesh = self._mesh()
        if mesh is None:
            return fn(rv_arrays, cp_arrays, cols, group_params)
        from ..parallel.mesh import replicate_tree, shard_review_side

        if cs_key is None:
            cs_key = (self._cs_epoch, self.interner.snapshot_size())
        key = (cs_key[0], cs_key[1], id(mesh))
        # single read: the compile thread runs unlocked, and a concurrent
        # reset() may None the cache between a check and a re-read
        cache = self._cs_device_cache
        if cache and cache[0] == key:
            cs_p, gp_p = cache[1]
        else:
            cs_p, gp_p = replicate_tree(mesh, (cp_arrays, group_params))
            # never cache under a key the live epoch has moved past: a later
            # eval with an unchanged vocab would hit misaligned mask rows
            if cs_key[0] == self._cs_epoch:
                self._cs_device_cache = (key, (cs_p, gp_p))
        rv_p, cols_p, _target = shard_review_side(mesh, rows, rv_arrays, cols)
        with mesh:
            return fn(rv_p, cs_p, cols_p, gp_p)

    def compute_masks(self, reviews: List[dict]):
        """-> (ordered constraints, match&violation candidate mask [C, R],
        autoreject mask [C, R]) as numpy arrays.

        Multi-chip: when a mesh is available the row axis is padded to a
        mesh multiple and committed sharded (input placement drives the
        SPMD compile of the SAME fused jit); results come back trimmed so
        callers see identical shapes on 1 or N devices."""
        fn, ordered, rp, cp, cols, group_params = self._device_inputs(reviews)
        rows = len(rp.arrays["valid"])
        mask, autoreject = self._dispatch(
            fn, rp.arrays, cp.arrays, cols, group_params, rows
        )
        both = np.asarray(jnp.stack([mask, autoreject]))  # one fetch
        return ordered, both[0][:, :rows], both[1][:, :rows]

    # ---- render (exactness filter) ---------------------------------------

    def _eval_cell(
        self, constraint: dict, kind: str, review: dict, frozen_review,
        inventory,
    ) -> list:
        """Exact evaluation of one (constraint, review) cell: native match
        re-check + interpreter violation rendering.  Returns the violation
        dicts ([] when the device mask over-approximated)."""
        from ..engine.value import freeze

        tmpl = self.templates.get(kind)
        if tmpl is None:
            return []
        if not constraint_matches(constraint, review, self.store.cached_namespace):
            return []  # device over-approximation filtered here
        params = (constraint.get("spec") or {}).get("parameters") or {}
        return tmpl.policy.eval_violations(
            frozen_review, freeze(params), inventory
        )

    def _render_cell(
        self,
        results: List[Result],
        constraint: dict,
        kind: str,
        review: dict,
        frozen_review,
        inventory,
        tracing_log,
    ):
        violations = self._eval_cell(
            constraint, kind, review, frozen_review, inventory
        )
        action = self._enforcement_action(constraint)
        for v in violations:
            results.append(
                Result(
                    msg=str(v.get("msg", "")),
                    metadata={"details": v.get("details", {})},
                    constraint=constraint,
                    review=review,
                    enforcement_action=action,
                )
            )
            if tracing_log is not None:
                tracing_log.append(
                    f"violation {kind}/{constraint['metadata']['name']}: {v.get('msg')}"
                )

    def review(self, review: dict, tracing: bool = False):
        return self.review_batch([review], tracing=tracing)[0]

    # Below this many constraint x review cells the device dispatch costs
    # more than it saves (kernel launch + host<->device transfer — or a
    # full network RTT when the chip sits behind a relay); small batches
    # evaluate host-side with the exact native matcher + interpreter.
    DEVICE_MIN_CELLS = int(os.environ.get("GK_DEVICE_MIN_CELLS", "4096"))

    def review_batch(self, reviews: List[dict], tracing: bool = False):
        """N concurrent admission reviews in ONE device dispatch: the mask
        is [C, N], then each review's positive cells render host-side.
        This is the micro-batching seam the webhook server drives.

        Hybrid dispatch: batches too small to amortize a device call run
        through the interpreter path (identical semantics — the device mask
        is only ever a pruning over-approximation of it)."""
        from ..engine.value import freeze

        if not reviews:
            return []
        with self._lock:  # concurrent ingest may resize the dicts (RLock)
            n_constraints = sum(len(v) for v in self.constraints.values())
        if len(reviews) * max(n_constraints, 1) < self.DEVICE_MIN_CELLS or (
            # async ingestion: while the background XLA compile for the
            # latest template/constraint epoch is in flight, admission
            # reviews serve from the interpreter instead of blocking
            self._compiler is not None
            and not self._compiler.ready()
        ):
            return [
                InterpDriver.review(self, r, tracing=tracing) for r in reviews
            ]
        with self._lock:
            ordered, mask, autoreject = self.compute_masks(reviews)
            inventory = self.store.frozen()
            out = []
            for ri, review in enumerate(reviews):
                frozen_review = freeze(review)
                results: List[Result] = []
                trace: List[str] = [] if tracing else None
                for i, (kind, name, constraint) in enumerate(ordered):
                    if autoreject[i, ri]:
                        if needs_autoreject(constraint, review, self.store.cached_namespace):
                            results.append(
                                Result(
                                    msg="Namespace is not cached in OPA.",
                                    metadata={"details": {}},
                                    constraint=constraint,
                                    review=review,
                                    enforcement_action=self._enforcement_action(constraint),
                                )
                            )
                            if tracing:
                                trace.append(f"autoreject {kind}/{name}")
                    if mask[i, ri]:
                        self._render_cell(
                            results, constraint, kind, review, frozen_review,
                            inventory, trace,
                        )
                out.append((results, "\n".join(trace) if tracing else None))
            return out

    def _audit_inputs(self):
        """Sync the resident incremental audit pack (ops/auditpack.py) and
        return the current fused fn + constraint side aligned with it."""
        fn, side = self._fused_fn()
        _ordered, _cp, _groups, col_specs = side
        self._audit_pack.sync(self, col_specs)
        fn, side = self._repack_if_vocab_grew(fn, side)
        ordered, cp, groups, _col_specs = side
        group_params = [packed for _prog, _idxs, packed in groups]
        return fn, ordered, cp, group_params

    def _audit_masks(self):
        """Packed audit sweep over the resident pack, with mask-level epoch
        caching: the device is dispatched only when the inventory or the
        constraint side actually changed."""
        key = (self.store.epoch, self._cs_epoch)
        if self._audit_cache and self._audit_cache[0] == key:
            _key, reviews, ordered, mask = self._audit_cache
            return reviews, ordered, mask
        fn, ordered, cp, group_params = self._audit_inputs()
        ap = self._audit_pack
        if ap.n_rows == 0:
            return [], [], None
        mask, _autoreject = self._dispatch(
            fn, ap.rp, cp.arrays, ap.cols, group_params, ap.capacity
        )
        mask = np.asarray(mask)[:, : ap.capacity]
        # re-read the epochs: packing may have interned new strings and
        # bumped the constraint-side cache, but the INPUTS are these epochs'
        self._audit_cache = (key, ap.reviews, ordered, mask)
        return ap.reviews, ordered, mask

    def audit(self, tracing: bool = False):
        from ..engine.value import freeze

        # audit is the throughput path: prefer waiting for the background
        # compile (which holds the driver lock only for host packing) over
        # an interpreter sweep of the whole inventory (advisor r2)
        self._wait_ready_for_audit()
        with self._lock:
            reviews, ordered, mask = self._audit_masks()
            if not reviews:
                return [], ("" if tracing else None)
            inventory = self.store.frozen()
            results: List[Result] = []
            trace: List[str] = [] if tracing else None
            # resource-major order, matching InterpDriver.audit; only
            # reviews with a positive cell pay the freeze + render cost
            hot_reviews = np.nonzero(mask.any(axis=0))[0]
            for ri in hot_reviews:
                review = reviews[ri] if ri < len(reviews) else None
                if review is None:  # tombstoned row (valid=False anyway)
                    continue
                frozen_review = freeze(review)
                for i in np.nonzero(mask[:, ri])[0]:
                    kind, _name, constraint = ordered[i]
                    self._render_cell(
                        results, constraint, kind, review, frozen_review,
                        inventory, trace,
                    )
            return results, ("\n".join(trace) if tracing else None)

    def _memo_cell(
        self, kind, name, ri, constraint, review, frozen_cache, inventory,
        uses_inv, row_gen,
    ) -> list:
        """Violations for one cell, memoized across sweeps: an unchanged
        (constraint side, packed row) pair renders identically unless the
        template reads data.inventory (then any store write invalidates)."""
        mkey = (kind, name, ri)
        if not uses_inv:
            hit = self._render_memo.get(mkey)
            if hit is not None and hit[0] == row_gen:
                return hit[1]
        fr = frozen_cache.get(ri)
        if fr is None:
            from ..engine.value import freeze

            fr = freeze(review)
            frozen_cache[ri] = fr
        violations = self._eval_cell(constraint, kind, review, fr, inventory)
        if not uses_inv:
            if len(self._render_memo) > 2_000_000:
                self._render_memo.clear()
            self._render_memo[mkey] = (row_gen, violations)
        return violations

    def audit_capped(self, cap: int, tracing: bool = False):
        """Cap-aware end-to-end audit: the status write-back keeps at most
        `cap` violations per constraint (--constraint-violations-limit,
        reference manager.go:49), so host rendering walks each constraint's
        candidate cells in row order and stops at the cap.  For templates
        with a vectorized program the candidate mask is tight-ish and the
        exact-eval cost is ~C x cap cells; templates with NO program get
        all-true columns, and for those the walk may exact-eval many cells
        before accumulating cap violations (same cost the plain audit pays).
        The device sweep itself is shared with audit() via _audit_masks().

        Returns (results, totals, trace) with totals
        {(kind, name): (count, how)}: "exact" when every candidate cell of
        that constraint was rendered (count = violation results, reference
        totalViolationsPerConstraint semantics), "resources" when the cap
        cut rendering short (count = device-counted violating resources —
        exact for templates whose vectorized program is exact, an
        over-approximation otherwise)."""
        if cap is None or cap <= 0:
            return InterpDriver.audit_capped(self, cap or 0, tracing=tracing)
        self._wait_ready_for_audit()
        with self._lock:
            reviews, ordered, mask = self._audit_masks()
            ap = self._audit_pack
            trace: List[str] = [] if tracing else None
            if not reviews or mask is None:
                # same contract as InterpDriver: every registered constraint
                # reports an exact zero even when the inventory is empty
                empty = {
                    (kind, cname): (0, "exact")
                    for kind in self.constraints
                    for cname in self.constraints[kind]
                }
                return [], empty, ("\n".join(trace) if tracing else None)
            if self._render_memo_epoch != self._cs_epoch:
                self._render_memo.clear()
                self._render_memo_epoch = self._cs_epoch
            counts = mask.sum(axis=1, dtype=np.int64)
            inventory = self.store.frozen()
            frozen_cache: Dict[int, object] = {}
            results: List[Result] = []
            totals: Dict[Tuple[str, str], Tuple[int, str]] = {}
            R = len(reviews)

            def render(ri, kind, name, constraint, uses_inv, action):
                violations = self._memo_cell(
                    kind, name, ri, constraint, reviews[ri], frozen_cache,
                    inventory, uses_inv, ap.row_gen[ri],
                )
                for v in violations:
                    results.append(
                        Result(
                            msg=str(v.get("msg", "")),
                            metadata={"details": v.get("details", {})},
                            constraint=constraint,
                            review=reviews[ri],
                            enforcement_action=action,
                        )
                    )
                    if trace is not None:
                        trace.append(f"violation {kind}/{name}: {v.get('msg')}")

            for ci, (kind, name, constraint) in enumerate(ordered):
                ckey = (kind, name)
                n_cells = int(counts[ci])
                if n_cells == 0:
                    totals[ckey] = (0, "exact")
                    continue
                tmpl = self.templates.get(kind)
                uses_inv = (
                    True if tmpl is None
                    else getattr(tmpl.policy, "uses_inventory", True)
                )
                action = self._enforcement_action(constraint)
                start = len(results)
                capped = False
                # first-k host selection over this constraint's mask row;
                # rendering stops at the cap (cost caveat for program-less
                # templates: see the docstring)
                for ri in np.nonzero(mask[ci, :R])[0]:
                    if len(results) - start >= cap:
                        capped = True
                        break
                    ri = int(ri)
                    if reviews[ri] is None:
                        continue  # tombstoned row (valid=False on device too)
                    render(ri, kind, name, constraint, uses_inv, action)
                if capped:
                    totals[ckey] = (max(n_cells, len(results) - start), "resources")
                else:
                    totals[ckey] = (len(results) - start, "exact")
            return results, totals, ("\n".join(trace) if tracing else None)
