"""TpuDriver: the vectorized JAX/XLA evaluation backend.

Pipeline per Review/Audit:
  1. pack reviews + constraints to integer tensors (host, incremental interner)
  2. device: match kernel -> bool[C, R]; per-kind violation programs
     (vectorizer output) -> bool[C_k, R]; combined candidate mask
  3. host: for each positive cell, exact native match re-check + interpreter
     violation rendering (messages/details) — the over-approximation filter

Correctness therefore never depends on the device mask being tight — only
throughput does.  Templates with no vectorized program get all-true columns
(pure interpreter fallback for their cells).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ..client.drivers import CompiledTemplate, InterpDriver, Result
from ..target.match import constraint_matches, needs_autoreject
from ..target.target import K8sValidationTarget
from .columns import extract_columns
from .interning import Interner, PredicateTable
from .matchkernel import match_kernel
from .pack import pack_constraints, pack_reviews
from .params import pack_params
from .vectorizer import vectorize
from .vexpr import EvalEnv, VProgram, eval_program


@functools.partial(jax.jit, static_argnames=())
def _match_jit(rv, cs):
    return match_kernel(rv, cs)


def _make_eval_jit(prog: VProgram):
    """One jitted evaluator per template program; C/R are static so jit
    re-specializes per shape bucket."""

    @functools.partial(jax.jit, static_argnames=("C", "R"))
    def run(prog_cols, params, elems, tables, keysets, C, R):
        env = EvalEnv(prog_cols, params, elems, tables, keysets, C, R)
        return eval_program(prog, env)

    return run


class TpuDriver(InterpDriver):
    """Drop-in Driver with device-side batched evaluation.  Inherits state
    management (templates/constraints/store) and render fallback from
    InterpDriver."""

    def __init__(self, target: Optional[K8sValidationTarget] = None):
        super().__init__(target)
        self.interner = Interner()
        self.programs: Dict[str, Optional[VProgram]] = {}
        self.pred_cache: Dict[Tuple[str, str], PredicateTable] = {}
        self._eval_jits: Dict[str, object] = {}
        # constraint-side packing is invalidated on any template/constraint
        # mutation and on vocabulary growth (str-pred tables are vocab-sized)
        self._cs_epoch = 0
        self._cs_cache = None

    # ---- lifecycle --------------------------------------------------------

    def put_template(self, kind: str, artifact: CompiledTemplate):
        super().put_template(kind, artifact)
        self.programs[kind] = vectorize(artifact.policy)
        self._eval_jits.pop(kind, None)
        self._cs_epoch += 1

    def delete_template(self, kind: str) -> bool:
        self.programs.pop(kind, None)
        self._eval_jits.pop(kind, None)
        self._cs_epoch += 1
        return super().delete_template(kind)

    def put_constraint(self, kind: str, name: str, constraint: dict):
        super().put_constraint(kind, name, constraint)
        self._cs_epoch += 1

    def delete_constraint(self, kind: str, name: str) -> bool:
        self._cs_epoch += 1
        return super().delete_constraint(kind, name)

    def reset(self):
        super().reset()
        self.programs.clear()
        self._eval_jits.clear()
        self._cs_epoch += 1
        self._cs_cache = None

    # ---- device evaluation ------------------------------------------------

    def _ordered_constraints(self) -> List[Tuple[str, str, dict]]:
        out = []
        for kind in sorted(self.constraints):
            for name in sorted(self.constraints[kind]):
                out.append((kind, name, self.constraints[kind][name]))
        return out

    def _constraint_side(self):
        """Cached constraint-side packing: match pack, per-kind param packs,
        and column-spec union.  Rebuilt when constraints/templates change or
        the vocabulary has grown (str-pred tables are vocab-indexed)."""
        ordered = self._ordered_constraints()
        vocab = self.interner.snapshot_size()
        key = (self._cs_epoch, vocab)
        if self._cs_cache and self._cs_cache[0] == key:
            return self._cs_cache[1]

        cp = pack_constraints([c for _k, _n, c in ordered], self.interner)
        specs = {}
        by_kind: Dict[str, List[int]] = {}
        for i, (kind, _n, _c) in enumerate(ordered):
            by_kind.setdefault(kind, []).append(i)
        kind_params = {}
        for kind, idxs in by_kind.items():
            prog = self.programs.get(kind)
            if not prog:
                continue
            for spec in prog.column_specs:
                specs[spec.key] = spec
            kcs = [ordered[i][2] for i in idxs]
            kind_params[kind] = pack_params(
                kcs, prog, self.interner, self.pred_cache, len(kcs)
            )
        side = (ordered, cp, by_kind, kind_params, list(specs.values()))
        # key uses the vocab size BEFORE param packing interned new strings;
        # recompute so the cache stays valid next call
        key = (self._cs_epoch, self.interner.snapshot_size())
        self._cs_cache = (key, side)
        return side

    def compute_masks(self, reviews: List[dict]):
        """-> (ordered constraints, match&violation candidate mask [C, R],
        autoreject mask [C, R]) as numpy arrays."""
        ordered, cp, by_kind, kind_params, col_specs = self._constraint_side()
        rp = pack_reviews(reviews, self.interner, self.store.cached_namespace)
        rows = len(rp.arrays["valid"])
        cols = extract_columns(reviews, col_specs, self.interner, rows)
        if self.interner.snapshot_size() > self._cs_cache[0][1]:
            # new strings interned from these reviews: str-pred tables must
            # cover them, so rebuild the constraint side once
            ordered, cp, by_kind, kind_params, col_specs = self._constraint_side()

        match, autoreject = _match_jit(rp.arrays, cp.arrays)
        match = np.asarray(match)
        autoreject = np.asarray(autoreject)

        mask = match.copy()
        for kind, idxs in by_kind.items():
            prog = self.programs.get(kind)
            if not prog or kind not in kind_params:
                continue
            params, elems, tables = kind_params[kind]
            keysets = {
                spec.key: cols[spec.key]["ids"]
                for spec in prog.column_specs
                if spec.kind == "keyset"
            }
            prog_cols = {
                spec.key: cols[spec.key]
                for spec in prog.column_specs
                if spec.kind != "keyset"
            }
            fn = self._eval_jits.get(kind)
            if fn is None:
                fn = _make_eval_jit(prog)
                self._eval_jits[kind] = fn
            vmask = np.asarray(
                fn(prog_cols, params, elems, tables, keysets, len(idxs), rows)
            )
            for j, i in enumerate(idxs):
                mask[i] &= vmask[j]
        return ordered, mask, autoreject

    # ---- render (exactness filter) ---------------------------------------

    def _render_cell(
        self,
        results: List[Result],
        constraint: dict,
        kind: str,
        review: dict,
        frozen_review,
        inventory,
        tracing_log,
    ):
        from ..engine.value import freeze

        tmpl = self.templates.get(kind)
        if tmpl is None:
            return
        if not constraint_matches(constraint, review, self.store.cached_namespace):
            return  # device over-approximation filtered here
        params = (constraint.get("spec") or {}).get("parameters") or {}
        violations = tmpl.policy.eval_violations(
            frozen_review, freeze(params), inventory
        )
        action = self._enforcement_action(constraint)
        for v in violations:
            results.append(
                Result(
                    msg=str(v.get("msg", "")),
                    metadata={"details": v.get("details", {})},
                    constraint=constraint,
                    review=review,
                    enforcement_action=action,
                )
            )
            if tracing_log is not None:
                tracing_log.append(
                    f"violation {kind}/{constraint['metadata']['name']}: {v.get('msg')}"
                )

    def review(self, review: dict, tracing: bool = False):
        from ..engine.value import freeze

        with self._lock:
            ordered, mask, autoreject = self.compute_masks([review])
            inventory = self.store.frozen()
            frozen_review = freeze(review)
            results: List[Result] = []
            trace: List[str] = [] if tracing else None
            for i, (kind, name, constraint) in enumerate(ordered):
                if autoreject[i, 0]:
                    if needs_autoreject(constraint, review, self.store.cached_namespace):
                        results.append(
                            Result(
                                msg="Namespace is not cached in OPA.",
                                metadata={"details": {}},
                                constraint=constraint,
                                review=review,
                                enforcement_action=self._enforcement_action(constraint),
                            )
                        )
                        if tracing:
                            trace.append(f"autoreject {kind}/{name}")
                if mask[i, 0]:
                    self._render_cell(
                        results, constraint, kind, review, frozen_review,
                        inventory, trace,
                    )
            return results, ("\n".join(trace) if tracing else None)

    def audit(self, tracing: bool = False):
        from ..engine.value import freeze, thaw

        with self._lock:
            objs = list(self.store.iter_objects())
            reviews = []
            for obj_frozen, api, kind_name, name, ns in objs:
                obj = thaw(obj_frozen)
                reviews.append(self.target.make_audit_review(obj, api, kind_name, name, ns))
            if not reviews:
                return [], ("" if tracing else None)
            ordered, mask, _autoreject = self.compute_masks(reviews)
            inventory = self.store.frozen()
            results: List[Result] = []
            trace: List[str] = [] if tracing else None
            # resource-major order, matching InterpDriver.audit
            for ri, review in enumerate(reviews):
                frozen_review = freeze(review)
                for i, (kind, _name, constraint) in enumerate(ordered):
                    if mask[i, ri]:
                        self._render_cell(
                            results, constraint, kind, review, frozen_review,
                            inventory, trace,
                        )
            return results, ("\n".join(trace) if tracing else None)
