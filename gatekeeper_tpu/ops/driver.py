"""TpuDriver: the vectorized JAX/XLA evaluation backend.

Pipeline per Review/Audit:
  1. pack reviews + constraints to integer tensors (host, incremental interner)
  2. device: match kernel -> bool[C, R]; per-kind violation programs
     (vectorizer output) -> bool[C_k, R]; combined candidate mask
  3. host: for each positive cell, exact native match re-check + violation
     rendering — via the compiled render plan (ops/renderplan.py: exact
     direct-value evaluation + message assembly, the bulk path) when the
     template's program is exact and its message AST compiled, else the
     interpreter (the residual tail, drained by a bounded worker pool)

Correctness therefore never depends on the device mask being tight — only
throughput does.  Templates with no vectorized program get all-true columns
(pure interpreter fallback for their cells).  Per-cell render tiers are
exported as render_cells_total{plan=static|slots|interp}.
"""

from __future__ import annotations

import copy
import logging
import os
import threading as _threading_mod
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import deadline as _deadline
from .. import faults
from ..metrics.catalog import (
    DISPATCH_M,
    PACK_M,
    record_cache,
    record_render_cells,
    record_stage,
)
from ..obs import costs as obscosts
from ..obs import trace as obstrace
from ..client.drivers import (
    CompiledTemplate,
    InterpDriver,
    Result,
    constraint_match_spec,
    constraint_parameters,
)
from ..target.match import constraint_matches, needs_autoreject
from ..target.target import K8sValidationTarget
from .columns import extract_columns
from .interning import Interner, PredicateTable
from .matchkernel import match_kernel
from .pack import _bucket as _bucket_pow2, pack_constraints, pack_reviews
from .params import pack_params
from .vectorizer import vectorize
from .vexpr import EvalEnv, VProgram, eval_program

log = logging.getLogger("gatekeeper_tpu.driver")


def _tree_sig(tree):
    """Shape/dtype/structure signature of a pytree: two sides with equal
    signatures produce identical traces for the same program structure."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (
        str(treedef),
        tuple(
            (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", "")))
            for l in leaves
        ),
    )


_REDUCTION_BLOCK = 64

# _bound_plans miss sentinel (None is a valid cached "no plan")
_PLAN_MISS = object()


def _constraint_name(constraint: dict) -> str:
    md = constraint.get("metadata")
    if isinstance(md, dict):
        return str(md.get("name", ""))
    return ""


def _packed_reduction(mask, K: int):
    """[C] counts + first-K candidate row indices -> one [C, 1+K] int32.
    lax.top_k is stable (equal elements keep index order), so the K
    largest of the 0/1 mask are the K smallest true indices, ascending —
    exactly the first-k walk order the host renders.

    A flat top_k over the full row axis is a width-R sort per constraint
    — measured as 91% of the on-device sweep at 500x100k (r4 verdict #4,
    the "2.25x roofline gap").  The hierarchical form runs two narrow
    top_ks instead: block-OR the mask into R/W blocks, take the first K
    TRUE blocks (every true block holds >= 1 candidate, so the first K
    candidates live in the first <= K true blocks), gather just those
    K x W segments, and resolve the exact first-K within them.  Every
    shape is static; total traffic approaches the one-pass mask read."""
    C, R = mask.shape
    counts = jnp.sum(mask, axis=1, dtype=jnp.int32)
    k = min(K, R)
    W = _REDUCTION_BLOCK
    if k * W * 2 >= R or R % W != 0:
        # small rows (or huge K): the flat sort is already cheap/cheaper
        vals, idx = jax.lax.top_k(mask.astype(jnp.int8), k)
        idx = jnp.where(vals > 0, idx, -1)
        return jnp.concatenate(
            [counts[:, None], idx.astype(jnp.int32)], axis=1
        )
    B = R // W
    blocks = mask.reshape(C, B, W)
    blk_any = jnp.any(blocks, axis=2)
    bvals, bidx = jax.lax.top_k(blk_any.astype(jnp.int8), k)  # first-k blocks
    # gather the K candidate blocks' segments: [C, k, W]
    segs = jnp.take_along_axis(blocks, bidx[:, :, None], axis=1)
    # blocks beyond the true-block count gather arbitrary (all-false)
    # blocks; mask them out explicitly for clarity
    segs = segs & (bvals > 0)[:, :, None]
    flat = segs.reshape(C, k * W)  # ascending global order (bidx sorted)
    gcol = (bidx[:, :, None] * W
            + jnp.arange(W, dtype=jnp.int32)[None, None, :]).reshape(C, k * W)
    vals, pos = jax.lax.top_k(flat.astype(jnp.int8), k)
    idx = jnp.take_along_axis(gcol, pos, axis=1)
    idx = jnp.where(vals > 0, idx, -1)
    return jnp.concatenate([counts[:, None], idx.astype(jnp.int32)], axis=1)


def _merge_sharded_packed(packed_all: np.ndarray, K: int) -> np.ndarray:
    """[N shards, C, 1+K'] per-shard capped reductions -> global
    [C, 1+K].  Counts sum; candidate indices are already global rows
    (-1 padded) and each shard's list is ascending within its contiguous
    row slab, so shard-major concatenation preserves global ascending
    order — the merge keeps the first K valid entries per constraint.
    K' = min(K, rows per shard) may be smaller than K (each shard then
    contributes its COMPLETE row slab, so the merge is still exact);
    the output is padded back to width K for the single-device shape
    contract."""
    counts = packed_all[:, :, 0].sum(axis=0, dtype=np.int32)
    cand = np.transpose(packed_all[:, :, 1:], (1, 0, 2))
    cand = cand.reshape(cand.shape[0], -1)  # [C, N*K'], shard-major
    if cand.shape[1] < K:
        cand = np.pad(cand, ((0, 0), (0, K - cand.shape[1])),
                      constant_values=-1)
    order = np.argsort(cand == -1, axis=1, kind="stable")[:, :K]
    merged = np.take_along_axis(cand, order, axis=1)
    return np.concatenate([counts[:, None], merged], axis=1)


def _scatter_rows_impl(dev_tree, idx, rows_tree):
    """Patch dirty rows into the device-resident audit input trees in ONE
    dispatch (one RTT behind a network relay, vs one per array leaf)."""
    return jax.tree_util.tree_map(
        lambda d, r: d.at[idx].set(r), dev_tree, rows_tree
    )


_scatter_rows = jax.jit(_scatter_rows_impl)
# Mesh twin: the pre-scatter placement is dead the moment the driver swaps
# its cache entry, and (unlike the single-device path) no lazy MaskSource
# dispatch ever re-reads it — the mesh sweep's mask is an eager co-output.
# Donating lets XLA patch the owning shards' slabs in place instead of
# copying every R-sized buffer per churn sweep.
_scatter_rows_mesh = jax.jit(_scatter_rows_impl, donate_argnums=0)


def _strip_request_meta(frozen_review):
    """The memo key for a review: identical content minus per-request
    metadata (uid), so repeated admissions of the same object hit the
    memo despite fresh uids.  memo_safe policies provably never read
    the stripped fields (engine/interp.py _validate).  ONE implementation
    shared with RowView.memo_frozen — both feed the same _review_memo, so
    the key normalization must never diverge."""
    from .renderplan import strip_request_meta

    return strip_request_meta(frozen_review)


class TpuDriver(InterpDriver):
    """Drop-in Driver with device-side batched evaluation.  Inherits state
    management (templates/constraints/store) and render fallback from
    InterpDriver."""

    def __init__(
        self,
        target: Optional[K8sValidationTarget] = None,
        async_compile: Optional[bool] = None,
        breaker_threshold: Optional[int] = None,
        breaker_cooldown_s: Optional[float] = None,
        mesh_watchdog_s: Optional[float] = None,
    ):
        super().__init__(target)
        # eager native build/load: the g++ compile must happen here, not
        # inside the first admission review under the driver lock
        from ..native import load as _load_native

        _load_native()
        self.interner = Interner()
        self.programs: Dict[str, Optional[VProgram]] = {}
        self.pred_cache: Dict[Tuple[str, str], PredicateTable] = {}
        self._fused = None
        self._fused_key = None
        # bit-packed output wrapper of the fused fn (review path): one
        # [2C, ceil(R/8)] uint8 fetch instead of two R-byte bool fetches
        self._fused_packed = None
        self._fused_packed_src = None
        # multi-chip: data-parallel mesh over every visible device (None on
        # single-chip).  GK_MESH=0 forces the single-device path, GK_MESH=1
        # (the default) meshes every visible device, GK_MESH=<n> for n >= 2
        # pins the mesh to the first n devices (a pinned width of 1 is only
        # reachable via set_mesh(True, width=1), which is the single-device
        # path); tests pin bit-parity across settings.  Mutate via
        # set_mesh(), which invalidates every cache keyed on the topology.
        _mesh_env = os.environ.get("GK_MESH", "1")
        self.mesh_enabled = _mesh_env != "0"
        try:
            _w = int(_mesh_env)
        except ValueError:
            _w = -1
        if _w < 0:
            # fail loudly at construction rather than silently meshing
            # every visible device off a typo'd width
            raise ValueError(
                f"GK_MESH={_mesh_env!r} is not a non-negative integer"
            )
        if _w > 1 and _w > len(jax.devices()):
            # same contract as set_mesh: a width the host cannot satisfy
            # would otherwise error on every sweep and silently degrade
            # the whole mesh family to the interpreter tier via the
            # circuit breaker.  (_w <= 1 skips the check so construction
            # does not force JAX backend initialization.)
            raise ValueError(
                f"GK_MESH={_mesh_env} exceeds visible devices "
                f"({len(jax.devices())})"
            )
        self.mesh_width: Optional[int] = _w if _w > 1 else None
        self._mesh_cache: Optional[tuple] = None
        # device placement of the replicated constraint side (mesh path):
        # re-uploading vocab-sized tables to N chips every call would cost
        # N RTTs behind a network relay; cached on the constraint epoch
        self._cs_device_cache = None
        # resident incremental audit packing (ops/auditpack.py) + rendered
        # cell memo: violations for an unchanged (constraint, row) pair are
        # deterministic unless the template reads data.inventory
        from .auditpack import AuditPackCache

        self._audit_pack = AuditPackCache()
        self._render_memo: Dict[Tuple, Tuple[int, list]] = {}
        self._render_memo_epoch = -1
        # compiled render plans (ops/renderplan.py) bound per constraint:
        # (kind, name) -> BoundPlan | None, valid for one constraint-side
        # epoch.  GK_RENDER_PLAN=0 forces every cell to the interpreter.
        self.render_plan_enabled = os.environ.get("GK_RENDER_PLAN", "1") != "0"
        self._bound_plans: Dict[Tuple[str, str], object] = {}
        self._bound_plans_epoch = -1
        self._uses_inventory_cache: Optional[Tuple[int, bool]] = None
        self._n_constraints_cache: Optional[Tuple[int, int]] = None
        # per-template constraint counts for the cost ledger's dispatch
        # apportioning (obs/costs.py), cached per constraint-side epoch —
        # attribution must never walk 500 kinds per admission batch
        self._cost_kinds_cache: Optional[Tuple[int, Dict[str, int]]] = None
        # per-pass render-tier counters, flushed to
        # render_cells_total{plan=...} at each render-pass boundary so the
        # hot loop pays a dict increment, not a registry record, per cell
        self._tier_counts = {"static": 0, "slots": 0, "interp": 0}
        # per-pass render instrumentation (read by bench.py's render config)
        self.last_render_stats: Dict[str, float] = {}
        # review-path render memo, keyed by CONTENT (kind, constraint name,
        # frozen review): admission streams are full of identical objects
        # (deployment replicas, retried requests), and an unchanged
        # (constraint, object) cell renders identically unless the template
        # reads data.inventory.  FrozenDict caches its hash, so the review
        # is hashed once and each constraint lookup is O(1).
        self._review_memo: Dict[Tuple, list] = {}
        self._review_memo_epoch = -1
        # whole-request memo (see _request_memoable): content ->
        # (epoch, {(kind, name): [(msg, details, action), ...]}, flat
        # replay list).  Entries from older epochs are REPAIRED via the
        # constraint-side change log (only changed constraints
        # re-evaluate) instead of discarded — a template-ingest storm then
        # costs O(changed) per admission, not O(installed templates) —
        # and current-epoch replays walk the flat list, O(violations).
        self._request_memo: Dict[Tuple, tuple] = {}
        self._request_memo_epoch = -1
        self._request_memo_ok = None
        # (kind, name) of constraints whose cells are NOT content-
        # determined; maintained incrementally by the mutators
        self._memoable_false: set = set()
        self._cs_change_log: List[Tuple[int, str, Optional[str]]] = []
        self._cs_log_floor = 0  # entries with epoch > floor are complete
        # constraint-side packing is invalidated on any template/constraint
        # mutation and on vocabulary growth (str-pred tables are vocab-sized)
        self._cs_epoch = 0
        self._cs_cache = None
        self._ordered_cache = None  # (epoch, sorted constraint list)
        self._gvk_cache = None  # (epoch, {(group, kind): entries}, nssel)
        # bumped only when the fused executable is actually rebuilt (its
        # structure signature changed); dependent jits key on this, so
        # shape-stable constraint churn preserves every warm executable
        self._fused_gen = 0
        # audit-side sweep cache: the production audit loop sweeps a
        # mostly-unchanged inventory every interval; the device is
        # dispatched only when the inventory or constraint side changed.
        # Shape: (key, sweep tuple, host-mask memo or None)
        self._audit_cache = None
        # device-resident review-side audit arrays: [layout_gen, tree].
        # Refreshed by one jitted scatter of just the dirty rows per sweep
        # (full re-upload only on pack layout changes) so a steady-state
        # sweep uploads ~KBs, not the whole 100k-row pack, across the link.
        self._audit_dev = None
        # the mesh twin: [layout_gen, mesh id, sharded (rv, cols)]
        self._audit_dev_mesh = None
        # capped-audit fused fns: packed-only (single-device; the mask is
        # a separate lazy dispatch) and two-output (mesh)
        self._fused_audit = None
        self._fused_audit_key = None
        self._fused_audit_mesh = None
        self._fused_audit_mesh_key = None
        # incremental O(changes) sweep (ops/deltasweep.py): steady-state
        # capped audits evaluate only dirty rows on-device and fold them
        # into host-side counts/candidate state; GK_DELTA=0 forces every
        # sweep down the full-dispatch path
        self.delta_enabled = os.environ.get("GK_DELTA", "1") != "0"
        self._delta_state = None
        self._delta_jit = None
        self._delta_jit_key = None
        # referential-policy state (ops/joinkernel.py): the host-side
        # join-group index (key -> provider/reader rows) that gives the
        # delta sweep O(churn) key-group invalidation, the per-epoch
        # unique-plan cache, and the audit-mode mask executable (the
        # review-mode fused fn resolves JoinCmp to unknown and must
        # never back the delta fold's base mask)
        self._join_state = None
        self._join_plans_cache: Optional[tuple] = None
        self._join_safe_cache: Optional[tuple] = None
        self._fused_mask = None
        self._fused_mask_key = None
        # per-sweep instrumentation (read by bench.py): pack/dispatch/fetch/
        # render wall-times, transferred bytes, rendered cells
        self.last_sweep_stats: Dict[str, float] = {}
        # measured routing cost model (calibrate_routing); None -> the
        # static DEVICE_MIN_CELLS prior decides interp-vs-device
        self._route_cal: Optional[Dict[str, float]] = None
        # offered-load hint (reviews/s, monotonic stamp) from the
        # micro-batcher: with it, routing prices sustainable THROUGHPUT
        # under saturation instead of this batch's latency alone
        self._offered_load: Optional[tuple] = None
        # brownout pin (obs/brownout.py level 3): routing locked to the
        # cheapest SUSTAINABLE (max-throughput) tier regardless of
        # per-batch latency or hint freshness — drain the queue first
        self._brownout_pin = False
        # route-decision ledger (obs/routeledger.py): every batch's
        # pricing decision — shape, offered λ, the priced tier table,
        # chosen tier, overriding reason — bounded, serving
        # /debug/routez and route_decisions_total{tier,reason}.
        # GK_ROUTE_LEDGER=0 disables recording (bench overhead arm).
        from ..obs.routeledger import RouteLedger, set_active

        self.route_ledger = RouteLedger().attach(self)
        self.route_ledger.enabled = (
            os.environ.get("GK_ROUTE_LEDGER", "1") != "0"
        )
        set_active(self.route_ledger)
        # incremental host-serving constraint side (ops/npside.py):
        # admission-sized batches evaluate the same VExpr IR in numpy —
        # no dispatch RTT, no compile, O(1) maintenance per mutation.
        # GK_NP_SERVE=0 disables (reviews then interp-walk as before).
        from .npside import NpSide

        self.np_serve_enabled = os.environ.get("GK_NP_SERVE", "1") != "0"
        self._np_side = NpSide()
        # async ingestion (SURVEY §7 hard-part 3): template/constraint
        # mutations hand the XLA re-compile to a background thread and
        # reviews serve from the interpreter until the new fused
        # executable is warm (ops/asynccompile.py)
        self._compiler = None
        if async_compile is None:
            async_compile = os.environ.get("GK_ASYNC_COMPILE", "0") == "1"
        if async_compile:
            from .asynccompile import AsyncCompiler

            self._compiler = AsyncCompiler(self)
        # circuit breaker over the device compile/dispatch seams: after N
        # consecutive backend failures every evaluation trips to the
        # inherited interpreter tier (semantically identical — the device
        # mask only ever prunes the interpreter walk); a background probe
        # re-tries a tiny real dispatch and one success returns evaluation
        # to the device (ops/breaker.py, docs/failure-modes.md)
        from .breaker import CircuitBreaker

        if breaker_threshold is None:
            breaker_threshold = int(os.environ.get("GK_BREAKER_THRESHOLD", "3"))
        if breaker_cooldown_s is None:
            breaker_cooldown_s = float(
                os.environ.get("GK_BREAKER_COOLDOWN_S", "5.0")
            )
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s,
            probe_fn=self._breaker_probe,
            on_transition=self._on_breaker_transition,
        )
        # mesh dispatch watchdog (docs/failure-modes.md): a stuck mesh
        # collective otherwise wedges the sweep thread AND the dispatch
        # gate forever (the breaker trips on exceptions, not on hangs).
        # With a budget set, guarded mesh-audit dispatches run under a
        # bounded join; a timeout raises MeshDispatchStall, which trips
        # the breaker and re-shards the sweep narrower (set_mesh), the
        # abandoned dispatch's gate generation revoked.  0/None disables
        # (the default: no extra thread on the sweep path).  The budget
        # must cover a COLD SPMD trace+compile, not just the dispatch —
        # the first sweep at a new topology compiles inside the guarded
        # region (this jax cannot pre-populate the jit call cache from
        # lower().compile()) — hence the tens-of-seconds production
        # default (main.py --mesh-watchdog-s).
        if mesh_watchdog_s is None:
            mesh_watchdog_s = float(
                os.environ.get("GK_MESH_WATCHDOG_S", "0") or 0
            )
        self.mesh_watchdog_s = mesh_watchdog_s

    # ---- lifecycle --------------------------------------------------------

    def _epoch_bumped(self):
        if self._compiler is not None:
            self._compiler.kick()
            # the async-compile backlog, observable: mutation epoch vs
            # compiled epoch (obs/compilestats.py; compile_epoch_lag)
            from ..obs import compilestats

            compilestats.record_epoch_lag(self._compiler.epoch_lag())

    # ---- circuit breaker ---------------------------------------------------

    # minimal synthetic review the recovery probe dispatches: exercises the
    # real compile + dispatch path without depending on installed templates
    _PROBE_REVIEW = {
        "kind": {"group": "", "version": "v1", "kind": "Pod"},
        "name": "gk-breaker-probe", "namespace": "default",
        "operation": "CREATE",
        "object": {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "gk-breaker-probe",
                         "namespace": "default", "labels": {}},
            "spec": {"containers": [{"name": "c", "image": "probe.io/x:1"}]},
        },
    }

    def _breaker_probe(self):
        """One real device round trip (half-open recovery).  Runs the same
        compile/dispatch seams production traffic does — including any
        installed fault-plane schedule — so the breaker closes exactly when
        the backend actually answers again."""
        with self._lock:
            n = sum(len(v) for v in self.constraints.values())
            if n == 0:
                # nothing to evaluate: the compile seam is the best probe
                self._fused_fn()
                return
            self.compute_masks([copy.deepcopy(self._PROBE_REVIEW)])

    def _on_breaker_transition(self, old: str, new: str):
        # also invoked with old == new by the probe loop as a periodic
        # metrics refresh while degraded — record always, log on change
        if old != new:
            log.warning(
                "tpu circuit breaker %s -> %s%s", old, new,
                " (serving from the interpreter tier)"
                if new != "closed" else "",
            )
            # flight recorder (obs/flightrec.py): the trip/recovery edge
            # lands in the incident ring, and an OPEN edge dumps the ring
            # to disk — the one artifact a post-mortem starts from.
            # Guarded: this runs INSIDE the device-failure handling path,
            # where a recorder defect must degrade, never crash a request
            try:
                from ..obs import flightrec

                flightrec.record(
                    flightrec.BREAKER_TRANSITION, old=old, new=new,
                    trips=self.breaker.trips,
                )
                if new == "open":
                    flightrec.dump("breaker_open")
            except Exception:
                log.debug("flight-recorder feed failed on breaker edge",
                          exc_info=True)
        try:
            from ..metrics.catalog import record_breaker

            record_breaker(self.breaker.status())
        except Exception:
            log.debug("breaker state metric recording failed",
                      exc_info=True)

    def breaker_status(self) -> dict:
        """Health-endpoint view of the degradation ladder."""
        return self.breaker.status()

    # review-memo entry bound: each entry retains a frozen admission object
    # (~KBs); 16k entries keeps worst-case memory in the tens of MB and a
    # wholesale clear in the low ms
    REVIEW_MEMO_MAX = 16_384

    # Audit-path compile wait: long enough that no realistic template storm
    # (bench: 500 templates ≈ tens of seconds) ever falls through to the
    # synchronous compile under the driver lock (advisor r2), but bounded so
    # pathological epoch churn (mutations forever outpacing compiles) cannot
    # wedge the audit loop permanently.
    AUDIT_COMPILE_WAIT_S = 600.0

    def wait_ready(self, timeout: Optional[float] = 120.0) -> bool:
        """Block until the fused executable for the current constraint-side
        epoch is compiled (no-op when async compile is off).  timeout=None
        waits indefinitely."""
        if self._compiler is None:
            return True
        return self._compiler.wait(timeout)

    def _wait_ready_for_audit(self):
        import time

        t0 = time.monotonic()
        if not self.wait_ready(timeout=self.AUDIT_COMPILE_WAIT_S):
            import logging

            waited = time.monotonic() - t0
            stopped = self._compiler is not None and self._compiler._stopped
            logging.getLogger("gatekeeper_tpu.driver").warning(
                "audit waited %.1fs for the background compile without it "
                "becoming ready (%s); proceeding with a synchronous compile "
                "under the driver lock",
                waited,
                "compiler stopped" if stopped
                else "sustained template/constraint churn?",
            )

    # constraint-side change log: (epoch-after-change, kind, name-or-None
    # for kind-wide).  Lets the whole-request memo repair entries by
    # re-evaluating ONLY the constraints that changed since the entry was
    # stored — the fix for interp-served admission latency growing O(N)
    # during a template-ingest storm.
    CS_LOG_MAX = 4096

    def _log_cs_change(self, kind: str, name: Optional[str]):
        self._cs_change_log.append((self._cs_epoch, kind, name))
        if len(self._cs_change_log) > self.CS_LOG_MAX:
            drop = len(self._cs_change_log) // 2
            self._cs_log_floor = self._cs_change_log[drop - 1][0]
            del self._cs_change_log[:drop]

    def _memoable_update(self, kind: str, name: Optional[str]):
        """Incrementally maintain the set of constraints whose cells are
        NOT content-determined — _request_memoable is then O(1) instead
        of an O(installed constraints) all() per epoch bump, which
        measurably taxed every mid-storm admission (caller holds lock)."""
        tmpl = self.templates.get(kind)
        names = (
            [name] if name is not None
            else list(self.constraints.get(kind, {}))
        )
        for n in names:
            c = self.constraints.get(kind, {}).get(n)
            key = (kind, n)
            if c is not None and not self._cell_memoable(tmpl, c):
                self._memoable_false.add(key)
            else:
                self._memoable_false.discard(key)

    def _ordered_update(self, kind: str, name: str):
        """Incrementally maintain the sorted constraint list (bisect):
        template churn must not re-sort 500 constraints per admission."""
        cached = self._ordered_cache
        if cached is None:
            return
        lst = cached[1]
        from bisect import bisect_left

        cur = self.constraints.get(kind, {}).get(name)
        i = bisect_left(lst, (kind, name), key=lambda e: (e[0], e[1]))
        present = i < len(lst) and lst[i][:2] == (kind, name)
        if cur is None:
            if present:
                del lst[i]
        elif present:
            lst[i] = (kind, name, cur)
        else:
            lst.insert(i, (kind, name, cur))
        self._ordered_cache = (self._cs_epoch, lst)

    def put_template(self, kind: str, artifact: CompiledTemplate):
        # all mutators hold the driver lock for their FULL body (the async
        # compiler snapshots under this lock) and bump the epoch last, so a
        # kicked compile never sees half-applied state
        with self._lock:
            super().put_template(kind, artifact)
            self.programs[kind] = vectorize(artifact.policy)
            self._cs_epoch += 1
            self._memoable_update(kind, None)
            if self._ordered_cache is not None:
                self._ordered_cache = (self._cs_epoch, self._ordered_cache[1])
            self._log_cs_change(kind, None)
        self._epoch_bumped()

    def delete_template(self, kind: str) -> bool:
        with self._lock:
            self.programs.pop(kind, None)
            out = super().delete_template(kind)
            self._cs_epoch += 1
            # the base delete cascades the kind's constraints away, so the
            # incremental caches must drop them too (not just re-stamp):
            # stale entries would keep evaluating deleted constraints and
            # permanently disable the request memo (advisor r5)
            self._memoable_false = {
                key for key in self._memoable_false if key[0] != kind
            }
            self._ordered_cache = None
            self._log_cs_change(kind, None)
        self._epoch_bumped()
        return out

    def put_constraint(self, kind: str, name: str, constraint: dict):
        with self._lock:
            stored = self.constraints.get(kind, {}).get(name)
            if stored is not constraint and stored == constraint:
                # identical replay (controller re-list after a restart):
                # every downstream structure keys on constraint CONTENT,
                # so skipping the epoch bump preserves warm state — the
                # restored delta basis and every compiled executable.
                # The identity guard matters: re-putting the SAME dict
                # object after mutating it in place would compare equal
                # to itself and silently skip invalidation.
                return
            super().put_constraint(kind, name, constraint)
            self._cs_epoch += 1
            self._memoable_update(kind, name)
            self._ordered_update(kind, name)
            self._log_cs_change(kind, name)
        self._epoch_bumped()

    def delete_constraint(self, kind: str, name: str) -> bool:
        with self._lock:
            out = super().delete_constraint(kind, name)
            self._cs_epoch += 1
            self._memoable_update(kind, name)
            self._ordered_update(kind, name)
            self._log_cs_change(kind, name)
        self._epoch_bumped()
        return out

    def reset(self):
        with self._lock:
            super().reset()
            self.programs.clear()
            self._cs_cache = None
            self._cs_device_cache = None
            self._fused = None
            self._fused_key = None
            from .auditpack import AuditPackCache

            self._audit_pack = AuditPackCache()
            self._render_memo.clear()
            self._bound_plans.clear()
            self._bound_plans_epoch = -1
            self._audit_cache = None
            self._audit_dev = None  # layout gens restart with the new pack
            self._audit_dev_mesh = None
            self._fused_audit = None
            self._fused_audit_key = None
            self._fused_audit_mesh = None
            self._fused_audit_mesh_key = None
            self._delta_state = None
            self._delta_jit = None
            self._delta_jit_key = None
            self._cs_epoch += 1
            # wholesale wipe: the change log cannot describe a reset
            self._request_memo.clear()
            self._memoable_false.clear()
            self._ordered_cache = None
            self._cs_change_log.clear()
            self._cs_log_floor = self._cs_epoch
        self._epoch_bumped()

    # ---- device evaluation ------------------------------------------------

    def _ordered_constraints(self) -> List[Tuple[str, str, dict]]:
        cached = self._ordered_cache
        if cached is not None and cached[0] == self._cs_epoch:
            return cached[1]
        out = []
        for kind in sorted(self.constraints):
            for name in sorted(self.constraints[kind]):
                out.append((kind, name, self.constraints[kind][name]))
        self._ordered_cache = (self._cs_epoch, out)
        return out

    def _constraint_side(self):
        """Cached constraint-side packing: match pack + violation-program
        groups.  Programs are grouped by STRUCTURE, so template clones (the
        synthetic 500-template config) share one traced subgraph with their
        constraints batched on the C axis.  Rebuilt when constraints or
        templates change, or when the vocabulary has grown (str-pred tables
        are vocab-sized)."""
        ordered = self._ordered_constraints()
        vocab = self.interner.snapshot_size()
        key = (self._cs_epoch, vocab)
        if self._cs_cache and self._cs_cache[0] == key:
            return self._cs_cache[1]

        specs = {}
        by_struct: Dict[str, list] = {}
        ungrouped: List[int] = []
        for i, (kind, _n, _c) in enumerate(ordered):
            prog = self.programs.get(kind)
            if not prog:
                ungrouped.append(i)  # match-only rows (no template program)
                continue
            sk = prog.structure_key()
            by_struct.setdefault(sk, [prog, []])[1].append(i)
        # GROUP-MAJOR constraint layout with per-group padded blocks: each
        # group occupies mask rows [start, start+B) where B buckets the
        # group size, so the fused per-group update is a STATIC SLICE —
        # no dynamic-index gather/scatter (constructs the TPU fusion
        # emitter nondeterministically rejects) — and a template clone
        # added inside an existing bucket keeps every shape, preserving
        # the compiled executable.  Pad rows pack as None (valid=False:
        # the match kernel keeps them all-False, so whatever a group's
        # padded program rows compute is ANDed away).
        # crow[i] = the padded-layout mask row of sorted constraint i, so
        # every host-side gather (masks, counts, topk) lands in sorted
        # (kind, name) order — per-review violation ordering is then
        # identical across the device, interp, memo-replay, and traced
        # paths (advisor r4).
        padded_cs: List[Optional[dict]] = []
        crow: List[int] = [0] * len(ordered)
        groups = []
        for _sk, (prog, idxs) in sorted(by_struct.items()):
            for spec in prog.column_specs:
                specs[spec.key] = spec
            kcs = [ordered[i][2] for i in idxs]
            B = _bucket_pow2(len(kcs))
            start = len(padded_cs)
            for i in idxs:
                crow[i] = len(padded_cs)
                padded_cs.append(ordered[i][2])
            padded_cs.extend([None] * (B - len(kcs)))
            packed = pack_params(kcs, prog, self.interner, self.pred_cache, B)
            groups.append((prog, start, B, packed))
        for i in ungrouped:
            crow[i] = len(padded_cs)
            padded_cs.append(ordered[i][2])
        cp = pack_constraints(padded_cs, self.interner)
        side = (
            ordered, cp, groups, list(specs.values()),
            np.asarray(crow, np.int64),
        )
        # key uses the vocab size BEFORE param packing interned new strings;
        # recompute so the cache stays valid next call
        key = (self._cs_epoch, self.interner.snapshot_size())
        self._cs_cache = (key, side)
        return side

    def _structure_sig(self, side):
        """Trace signature of the fused fn for this constraint side: group
        program structures, block layout, and every constraint-side array
        shape/dtype.  Two sides with equal signatures share one compiled
        executable — group parameters are runtime arguments and the block
        starts/sizes are layout-determined, so adding a template clone
        inside existing shape buckets costs no retrace/recompile (the
        ingest-storm latency fix)."""
        ordered, cp, groups, col_specs, _crow = side
        return (
            _tree_sig(cp.arrays),
            tuple(
                (prog.structure_key(), start, B, _tree_sig(packed))
                for prog, start, B, packed in groups
            ),
            tuple(sorted(s.key for s in col_specs)),
        )

    def _eval_body(self, side, join_mode: Optional[str] = None,
                   axis_name: Optional[str] = None):
        """The one match-kernel + violation-program-groups evaluation,
        parameterized by the JOIN mode (ops/joinkernel.py):

        - ``None`` (the review path): JoinCmp nodes resolve to their
          polarity's unknown_default — sound over-approximation, no extra
          arguments, signature identical to the pre-referential body.
        - ``'trace'`` (full audit sweeps): per-key aggregate tables are
          computed in-trace from the resident columns (segment-reduce
          group-by; per-shard + all_gather merge when ``axis_name`` names
          the mesh axis); the trailing ``joins`` argument carries runtime
          kind ids so interner ids are never baked into a cached
          executable.
        - ``'tables'`` (delta sweeps): the trailing ``joins`` argument
          carries the host join index's (uk, uc) tables — a churn-slice
          dispatch cannot derive the global aggregate from its rows.

        Returns (body, has_joins): ``body(rv, cs, cols, group_params
        [, joins])``."""
        _ordered, _cp, groups, _col_specs, _crow = side
        static = [(prog, start, B) for prog, start, B, _packed in groups]
        plans = self._active_join_plans()
        has_joins = bool(plans) and join_mode is not None
        pidx = {p: i for i, p in enumerate(plans)}

        def body(rv, cs, cols, group_params, joins=None):
            match, autoreject = match_kernel(rv, cs)
            mask = match
            R = match.shape[1]
            # join tables shared ACROSS groups: N template clones of one
            # referential family cost one table build per sweep
            shared_tables: dict = {}
            for (prog, start, B), (params, elems, tables) in zip(
                static, group_params
            ):
                keysets = {
                    spec.key: cols[spec.key]["ids"]
                    for spec in prog.column_specs
                    if spec.kind == "keyset"
                }
                prog_cols = {
                    spec.key: cols[spec.key]
                    for spec in prog.column_specs
                    if spec.kind != "keyset"
                }
                env = EvalEnv(
                    prog_cols, params, elems, tables, keysets, B, R
                )
                if has_joins and prog.join_plans:
                    from .joinkernel import JoinBinding

                    env.joins = JoinBinding(
                        join_mode, prog.join_plans,
                        [joins[pidx[p]] for p in prog.join_plans],
                        rv=rv, axis_name=axis_name, cache=shared_tables,
                    )
                vmask = eval_program(prog, env)  # [B, R], B = block size
                # STATIC SLICE update: the group-major layout gives every
                # group a contiguous [start, start+B) block, so no
                # dynamic-index gather/scatter exists anywhere in this
                # program (dynamic forms nondeterministically crash the
                # TPU fusion emitter); padded block rows are match-False
                # and AND whatever their program rows computed away
                mask = mask.at[start:start + B].set(
                    mask[start:start + B] & vmask
                )
            return mask, autoreject

        return body, has_joins

    def _fused_fn(self):
        """One jitted function for the whole sweep: match kernel + every
        violation-program group, combined into the candidate mask.  ONE
        dispatch and ONE device->host fetch per evaluation — essential when
        the device sits behind a network relay (each fetch is an RTT).

        Keyed on the STRUCTURE signature, not the epoch: params, string
        tables (vocab-bucketed) and group index vectors are all runtime
        arguments, so constraint churn that keeps shapes inside their
        power-of-two buckets reuses the warm executable as-is."""
        side = self._constraint_side()
        sig = self._structure_sig(side)
        if self._fused is not None and self._fused_key == sig:
            return self._fused, side
        if faults.ENABLED:
            faults.fire(faults.TPU_COMPILE)
        body, _has_joins = self._eval_body(side)  # review mode

        def fused(rv, cs, cols, group_params):
            return body(rv, cs, cols, group_params)

        from .aotcache import aot_jit

        self._fused = aot_jit(fused, "fused", sig)
        self._fused_key = sig
        self._fused_gen += 1
        return self._fused, side

    # ---- referential policies (ops/joinkernel.py) -------------------------

    def _active_join_plans(self) -> tuple:
        """Ordered unique JoinPlans across every installed program,
        cached per constraint-side epoch.  Index order is the ``joins``
        runtime-argument order of every join-bearing executable."""
        cached = self._join_plans_cache
        if cached is not None and cached[0] == self._cs_epoch:
            return cached[1]
        plans: List = []
        for kind in sorted(self.programs):
            prog = self.programs.get(kind)
            for p in getattr(prog, "join_plans", ()) or ():
                if p not in plans:
                    plans.append(p)
        out = tuple(plans)
        self._join_plans_cache = (self._cs_epoch, out)
        return out

    def _join_trace_args(self) -> Optional[tuple]:
        """Runtime arguments for 'trace'-mode join executables: the
        interned remote-kind id per plan (runtime, never baked — AOT
        cache entries are shared across processes whose interners
        assigned different ids)."""
        plans = self._active_join_plans()
        if not plans:
            return None
        return tuple(
            {"kind_id": np.asarray(
                self.interner.intern(p.remote_kind), np.int32
            )}
            for p in plans
        )

    def _join_delta_tables(self) -> Optional[tuple]:
        """'tables'-mode runtime arguments from the host join index
        (per-plan uk/uc tables + the kind id JoinCmp.exclude_self
        needs)."""
        js = self._join_state
        if js is None or not js.built:
            return None
        plans = self._active_join_plans()
        out = []
        for p, tab in zip(plans, js.delta_tables()):
            tab = dict(tab)
            tab["kind_id"] = np.asarray(
                self.interner.intern(p.remote_kind), np.int32
            )
            out.append(tab)
        return tuple(out)

    def _ensure_join_state(self):
        """Bring the host join-group index current with the audit pack
        (full-sweep path).  The rebuild DIFFS against the previous index
        and bumps the row generations of readers whose key group
        changed, so the render caches can never replay a message whose
        aggregate (a quota count, a duplicate set) moved underneath it."""
        plans = self._active_join_plans()
        ap = self._audit_pack
        if not plans:
            if self._join_state is not None:
                # the last referential template left: retract the gauge
                # so /metrics never shows phantom active join plans
                self._join_state = None
                from ..metrics.catalog import set_join_plans

                set_join_plans(0)
            return None
        from .joinkernel import JoinState

        js = self._join_state
        sig = tuple(p.sig for p in plans)
        if js is None or js.sig != sig or js.rebuild_gen != ap.rebuild_gen:
            # a pack rebuild reassigned row ids (and reset every row
            # generation with it), so a fresh index starts diff-free
            js = JoinState(plans, ap.rebuild_gen)
            self._join_state = js
        bump = js.rebuild(ap, self.interner)
        if bump:
            ap.bump_row_gen(bump)
        from ..metrics.catalog import set_join_plans

        set_join_plans(len(plans))
        return js

    def _join_safe(self, kind: str) -> bool:
        """True when a referential template's rendered results are
        reusable across sweeps: every inventory read is a classified
        join plan (prog.exact survived compilation), so verdict+message
        depend only on (row content, key-group aggregate) — and the join
        index bumps reader row generations whenever a group changes."""
        cached = self._join_safe_cache
        if cached is None or cached[0] != self._cs_epoch:
            cached = (self._cs_epoch, {})
            self._join_safe_cache = cached
        hit = cached[1].get(kind)
        if hit is None:
            prog = self.programs.get(kind)
            # same determinism bar as the row-local audit memo (which
            # keys on pack row generations, not review content): an
            # EXACT program's clauses compiled entirely from the
            # wall-clock-free vectorized fragment, so the render is a
            # function of (row content, key-group aggregate) — both
            # covered by the generation bumps.  memo_safe is deliberately
            # NOT required: it trips on whole-review aliasing (the
            # `identical(other, input.review)` helper), which is
            # harmless here — the review IS the row content.
            hit = bool(
                prog is not None
                and getattr(prog, "join_plans", ())
                and prog.exact
                and kind in self.templates
            )
            cached[1][kind] = hit
        return hit

    def _join_strict(self, kind: str, constraint: dict) -> bool:
        """A flagged-but-renders-empty cell for this constraint is a
        genuine plan-vs-oracle divergence (not a legitimate match or
        mask over-approximation): exact join program, selector-free
        match (the packed match is exact without label selectors)."""
        prog = self.programs.get(kind)
        if prog is None or not getattr(prog, "join_plans", ()) \
                or not prog.exact:
            return False
        match = constraint_match_spec(constraint)
        return not match.get("labelSelector") and not match.get(
            "namespaceSelector"
        )

    def _note_join_false_positive(self, kind: str, name: str, ri: int):
        """A strict-eligible join cell whose interpreter render came back
        empty: count/raise it as a divergence UNLESS the documented
        groupVersion-twin corner explains it (legitimate filter work —
        raising there would crash armed audits on valid clusters)."""
        from . import joinkernel

        prog = self.programs.get(kind)
        js = self._join_state
        if (
            js is not None and prog is not None
            and joinkernel.gv_twin_corner(
                js, getattr(prog, "join_plans", ()), self._audit_pack, ri
            )
        ):
            return
        joinkernel.note_false_positive(kind, name, ri)

    def _join_render_inventory(self, kind: str, rows) -> Optional[object]:
        """ONE grouped inventory for rendering this kind's flagged join
        cells (the PR 14 REMAINING item, docs/referential.md): the
        interpreter re-runs the Rego body per flagged cell, and its
        ``data.inventory`` iterate walks the FULL provider collection —
        O(R) per cell.  For a join-safe kind (every inventory read is an
        exact classified plan) the verdict and message depend only on
        the provider rows in the flagged readers' key groups, so one
        pass builds a pruned tree holding exactly those rows and every
        flagged cell renders byte-identically against it — total render
        cost O(flagged + union of group sizes), not O(flagged x R).

        Returns the frozen pruned tree, or None when equivalence cannot
        be proven (no current join index, unknown plan, provider row
        outside the pack) — the caller then falls back to the full
        inventory.  Soundness backstop: a pruning defect surfaces as a
        flagged-but-renders-empty cell, which the GK_JOIN_ASSERT-armed
        divergence assertion (and tools/check_join_parity.py, tier-1)
        turns into a loud failure, never a silent wrong message."""
        js = self._join_state
        prog = self.programs.get(kind)
        if js is None or not js.built or prog is None:
            return None
        plans = getattr(prog, "join_plans", ()) or ()
        if not plans:
            return None
        ap = self._audit_pack
        reviews = ap.reviews
        by_sig = {p.sig: i for i, p in enumerate(js.plans)}
        provider_rows: set = set()
        for plan in plans:
            i = by_sig.get(plan.sig)
            if i is None:
                return None  # index predates this plan set: rebase path
            row_rkeys = js.row_rkeys[i]
            providers = js.providers[i]
            keys: set = set()
            for r in rows:
                keys.update(row_rkeys.get(int(r), ()))
            for k in keys:
                provider_rows |= providers.get(k, set())
        tree: Dict[str, dict] = {}
        for ri in sorted(provider_rows):
            if ri >= len(reviews):
                return None  # index/pack drift: never render against it
            rev = reviews[ri]
            if rev is None:
                continue  # tombstoned provider: contributes nothing
            obj = rev.get("object")
            if not isinstance(obj, (dict,)) and not hasattr(obj, "get"):
                return None
            meta = obj.get("metadata") or {}
            api = obj.get("apiVersion") or ""
            okind = obj.get("kind") or ""
            name = meta.get("name") or ""
            # placement mirrors target.py inventory_segments: the
            # OBJECT's namespace decides cluster- vs namespace-scope
            ns = meta.get("namespace") or ""
            if ns:
                node = (
                    tree.setdefault("namespace", {})
                    .setdefault(ns, {})
                    .setdefault(api, {})
                    .setdefault(okind, {})
                )
            else:
                node = (
                    tree.setdefault("cluster", {})
                    .setdefault(api, {})
                    .setdefault(okind, {})
                )
            node[name] = obj
        from ..engine.value import freeze

        return freeze(tree)

    def _lazy_join_inventory(self, kind: str, rows, full_inventory):
        """Thunk form of _join_render_inventory, memoized on first call:
        the grouped-tree build runs only when a cell actually MISSES the
        render memo — a steady-state sweep whose join cells all replay
        cached renders never pays it.  Falls back to the full inventory
        when pruning cannot be proven equivalent."""
        box: list = []

        def get():
            if not box:
                pruned = self._join_render_inventory(kind, rows)
                box.append(full_inventory if pruned is None else pruned)
            return box[0]

        return get

    def join_plan_shapes(self) -> List[dict]:
        """Join-plan observability summary (served by /debug/routez via
        the route ledger, obs/routeledger.py)."""
        js = self._join_state
        if js is not None and js.built:
            return js.shapes()
        return [
            {
                "agg": p.agg, "kind": p.remote_kind,
                "scope": p.remote_scope, "slot_key": p.local_slot,
                "groups": None, "provider_rows": None, "reader_rows": None,
            }
            for p in self._active_join_plans()
        ]

    def _fused_mask_fn(self):
        """Audit-mode [C, R] mask executable (single-device path), or
        None when no join plans exist (the plain fused fn is then
        byte-identical and its warm executable serves).  The lazy
        MaskSource dispatch must compute join verdicts exactly like the
        capped reduction it backs: the review-mode fused fn resolves
        JoinCmp to unknown_default and would corrupt the delta fold's
        before-columns."""
        fused, side = self._fused_fn()
        if self._fused_mask is not None and \
                self._fused_mask_key == self._fused_gen:
            return self._fused_mask
        body, has_joins = self._eval_body(side, join_mode="trace")
        if not has_joins:
            self._fused_mask = None
            self._fused_mask_key = self._fused_gen
            return None

        def fused_mask(rv, cs, cols, gp, joins):
            return body(rv, cs, cols, gp, joins)[0]

        from .aotcache import aot_jit

        self._fused_mask = aot_jit(
            fused_mask, "fused-mask", self._fused_key
        )
        self._fused_mask_key = self._fused_gen
        return self._fused_mask

    def _repack_if_vocab_grew(self, fn, side):
        """Row packing may have interned new strings; constraint-side string
        predicate tables are vocab-sized, so re-pack them if so.  Shared by
        the review and audit input paths — the invalidation rule must stay
        identical between them."""
        if self.interner.snapshot_size() > self._cs_cache[0][1]:
            return self._fused_fn()
        return fn, side

    def _device_inputs(self, reviews: List[dict]):
        """Pack review-side arrays + columns; rebuild the constraint side if
        these reviews interned new strings (pred tables are vocab-sized)."""
        fn, side = self._fused_fn()
        col_specs = side[3]
        rp = pack_reviews(reviews, self.interner, self.store.cached_namespace)
        rows = len(rp.arrays["valid"])
        cols = extract_columns(reviews, col_specs, self.interner, rows)
        fn, side = self._repack_if_vocab_grew(fn, side)
        ordered, cp, groups, _col_specs, crow = side
        group_params = [packed for *_s, packed in groups]
        return fn, ordered, rp, cp, cols, group_params, crow

    def _mesh(self):
        """The production device mesh: all visible devices (or the pinned
        mesh_width), data-parallel on the resource axis (parallel/mesh.py).
        None on single-chip, width 1, or when mesh_enabled is off."""
        if not self.mesh_enabled:
            return None
        if self._mesh_cache is None:
            from ..parallel.mesh import audit_mesh, maybe_audit_mesh

            if self.mesh_width is not None:
                mesh = (
                    audit_mesh(self.mesh_width) if self.mesh_width > 1
                    else None
                )
            else:
                mesh = maybe_audit_mesh()
            self._mesh_cache = (mesh,)
        return self._mesh_cache[0]

    def set_mesh(self, enabled: bool, width: Optional[int] = None):
        """Switch the mesh topology (on/off, or a pinned device count) and
        invalidate EVERY cache keyed on it: the mesh object itself, the
        device-resident constraint side and sharded audit inputs, the
        compiled mesh audit executable, the delta-sweep basis (its resident
        base mask carries the old topology's layout), the sweep cache and
        the delta executable (its compiled entries pin the old mask
        sharding).  This replaces the ad-hoc `_mesh_cache = None` /
        `mesh_enabled = False` pokes — partial pokes left stale
        device placements serving the new topology.

        width=None uses every visible device; width=1 forces the
        single-device path even when enabled."""
        if enabled and width is not None and width > len(jax.devices()):
            raise ValueError(
                f"mesh width {width} exceeds visible devices "
                f"({len(jax.devices())})"
            )
        with self._lock:
            self.mesh_enabled = bool(enabled)
            self.mesh_width = width
            self._mesh_cache = None
            self._cs_device_cache = None
            self._audit_dev = None
            self._audit_dev_mesh = None
            self._audit_cache = None
            self._delta_state = None
            self._delta_jit = None
            self._delta_jit_key = None
            self._fused_audit_mesh = None
            self._fused_audit_mesh_key = None
        from ..metrics.catalog import record_mesh_width

        # outside the driver lock (the gauge is advisory); mesh_layout()
        # resolves the new topology, initializing it on first use
        record_mesh_width(self.mesh_layout() if enabled else 1)

    def mesh_layout(self) -> int:
        """The row-sharding width serving production sweeps: device count
        of the active mesh, 1 on the single-device path.  Persisted in the
        snapshot sweep basis; a restore whose live layout differs drops
        the basis (width drift invalidation, gatekeeper_tpu/snapshot/)."""
        mesh = self._mesh()
        return 1 if mesh is None else int(mesh.devices.size)

    def _guarded_mesh_dispatch(self, mesh, thunk, enter: bool = True):
        """Run one mesh-collective enqueue under the dispatch gate with
        the stall watchdog (docs/failure-modes.md).  Without a watchdog
        budget this is exactly `with DISPATCH_LOCK, mesh: thunk()`.  With
        one, the guarded enqueue runs on a worker thread the caller joins
        with the budget; a timeout (the gate never freed, or the enqueue
        itself wedged — a stuck collective rendezvous) revokes the gate's
        generation (abandoning the wedged holder so narrower-topology
        dispatches can proceed) and raises MeshDispatchStall, which the
        audit paths convert into breaker trip + re-shard.

        Cost model: each guarded dispatch pays one worker-thread spawn
        (microseconds against a sweep's ms-to-s dispatch), and an
        ABANDONED worker necessarily pins its operand buffers until the
        wedged collective ever returns — they are live inputs of the
        in-flight call, not freeable from outside.  Acceptable because
        abandonment coincides with the breaker tripping and the mesh
        narrowing: the degraded state the pinned memory rides out."""
        from ..parallel.mesh import DISPATCH_LOCK, MeshDispatchStall

        import contextlib

        # `enter` mirrors each pre-watchdog call site exactly: the fused
        # audit dispatch ran inside `with mesh:`, the delta dispatch did
        # not (its executable was traced without the ambient mesh, and
        # entering it here would miss the background-warmed jit cache)
        mesh_ctx = mesh if enter else contextlib.nullcontext()
        timeout = self.mesh_watchdog_s or 0.0
        if timeout <= 0:
            with DISPATCH_LOCK, mesh_ctx:
                if faults.ENABLED:
                    faults.fire(faults.MESH_DISPATCH_STALL)
                return thunk()

        def _stall(where: str) -> MeshDispatchStall:
            DISPATCH_LOCK.revoke()
            from ..metrics.catalog import record_mesh_stall

            record_mesh_stall()
            log.warning(
                "mesh dispatch watchdog: %s exceeded %.3fs at width %d",
                where, timeout, self.mesh_layout(),
            )
            return MeshDispatchStall(
                f"mesh dispatch {where} exceeded the {timeout:.3f}s "
                f"watchdog budget"
            )

        token = DISPATCH_LOCK.acquire(timeout=timeout)
        if token is None:
            # a previous dispatch is wedged holding the gate
            raise _stall("gate wait")
        done = _threading_mod.Event()
        box: dict = {}

        def run():
            try:
                with mesh_ctx:
                    if faults.ENABLED:
                        faults.fire(faults.MESH_DISPATCH_STALL)
                    box["out"] = thunk()
            except BaseException as e:  # surfaced on the caller's side
                box["err"] = e
            finally:
                done.set()
                # released from the worker: a late (post-revoke) release
                # of an abandoned generation is harmless by design
                DISPATCH_LOCK.release(token)

        t = _threading_mod.Thread(
            target=run, name="gk-mesh-dispatch", daemon=True
        )
        t.start()
        if not done.wait(timeout):
            raise _stall("collective enqueue")
        if "err" in box:
            raise box["err"]
        return box["out"]

    def _record_device_failure(self, e: BaseException):
        """Feed one device-path failure to the breaker.  A MeshDispatchStall
        is decisive — a wedged collective will wedge every subsequent mesh
        dispatch too, so it trips the breaker immediately (no
        threshold-counting through repeated watchdog budgets) and
        re-shards the sweep narrower; the rebasing full sweep runs at the
        new width once the breaker's recovery probe closes it."""
        from ..parallel.mesh import MeshDispatchStall

        self.breaker.record_failure(e)
        if isinstance(e, MeshDispatchStall):
            self.breaker.trip()
            try:
                self.degrade_mesh()
            except Exception:
                log.exception("mesh degradation after a stall failed")

    def degrade_mesh(self) -> int:
        """Re-shard the audit sweep one step narrower after a stalled
        collective: width w -> w // 2, bottoming out at the single-device
        path.  set_mesh() drops every topology-keyed cache including the
        delta basis, so the next device sweep is one full dispatch that
        rebases the incremental state — parity preserved by construction
        (the narrower sweep computes the identical [C, R] masks).
        Returns the new width (1 = single-device)."""
        width = self.mesh_layout()
        new = width // 2
        if new >= 2:
            self.set_mesh(True, width=new)
        else:
            new = 1
            self.set_mesh(True, width=1)
        log.warning(
            "mesh degraded after dispatch stall: width %d -> %d%s",
            width, new,
            " (single-device path)" if new == 1 else "",
        )
        try:  # guarded: degradation must proceed even recorder-less
            from ..obs import flightrec

            flightrec.record(
                flightrec.MESH_DEGRADE, from_width=width, to_width=new,
            )
        except Exception:
            log.debug("flight-recorder feed failed on mesh degrade",
                      exc_info=True)
        return new

    def _dispatch(self, fn, rv_arrays, cp_arrays, cols, group_params, rows,
                  cs_key=None):
        """Call a fused device function with mesh-aware placement: on a
        multi-chip mesh the review side is padded + sharded on "data" and
        the replicated constraint side is served from the epoch-keyed device
        cache (re-uploading vocab-sized tables to N chips every call would
        cost N RTTs behind a network relay).

        cs_key: (cs_epoch, vocab) the inputs were packed for, captured under
        the driver lock.  The async compile thread dispatches UNLOCKED, so
        reading self._cs_epoch here could key stale constraint arrays under
        a newer epoch (advisor r2); callers that hold the lock may omit it."""
        if faults.ENABLED:
            faults.fire(faults.TPU_DISPATCH)
        from .aotcache import aot_jit

        mesh = self._mesh()
        cs_p, gp_p = self._constraint_device_side(
            cp_arrays, group_params, cs_key, mesh
        )
        if mesh is None:
            return fn(rv_arrays, cs_p, cols, gp_p)
        if isinstance(fn, aot_jit):
            # serialized executables pin a single-device layout; the mesh
            # path must go through the jit machinery's SPMD compile
            fn = fn._jitted
        from ..parallel.mesh import shard_review_side

        from ..parallel.mesh import DISPATCH_LOCK

        rv_p, cols_p, _target = shard_review_side(
            mesh, rows, rv_arrays, cols,
            record_shard=self._record_shard("review"),
        )
        with DISPATCH_LOCK, mesh:
            return fn(rv_p, cs_p, cols_p, gp_p)

    def _constraint_device_side(self, cp_arrays, group_params, cs_key, mesh):
        """The constraint-side trees committed on-device (replicated across
        the mesh when one exists), cached on (epoch, vocab): vocab-sized
        predicate tables dominate the constraint side, and re-uploading them
        every call costs an RTT per array behind a network relay."""
        if cs_key is None:
            cs_key = (self._cs_epoch, self.interner.snapshot_size())
        key = (cs_key[0], cs_key[1], id(mesh) if mesh is not None else 0)
        # single read: the compile thread runs unlocked, and a concurrent
        # reset() may None the cache between a check and a re-read
        cache = self._cs_device_cache
        if cache and cache[0] == key:
            return cache[1]
        if mesh is None:
            placed = jax.device_put((cp_arrays, group_params))
        else:
            from ..parallel.mesh import replicate_tree

            placed = replicate_tree(mesh, (cp_arrays, group_params))
        # device-memory accounting (obs/compilestats.py): the replicated
        # constraint side's footprint, refreshed per placement (cache
        # misses only — epoch/vocab churn, not the hot path)
        from ..obs import compilestats

        compilestats.record_device_bytes(
            "constraint_side",
            compilestats.tree_nbytes((cp_arrays, group_params)),
            replicas=1 if mesh is None else int(mesh.devices.size),
        )
        # never cache under a key the live epoch has moved past: a later
        # eval with an unchanged vocab would hit misaligned mask rows
        if cs_key[0] == self._cs_epoch:
            self._cs_device_cache = (key, placed)
        return placed

    def _packed_variant(self, fn):
        """Wrap the fused fn so mask+autoreject leave the device as ONE
        bit-packed uint8 array: behind the network relay every fetched
        array costs an RTT, and packing cuts the payload 8x besides.  The
        packing runs inside the same jitted dispatch (no separate stack
        op crossing the relay)."""
        if self._fused_packed is not None and self._fused_packed_src is fn:
            return self._fused_packed
        raw = fn.__wrapped__

        def fused_packed(rv, cs, cols, gp):
            mask, autoreject = raw(rv, cs, cols, gp)
            return jnp.packbits(
                jnp.concatenate([mask, autoreject], axis=0), axis=1
            )

        from .aotcache import aot_jit

        self._fused_packed = aot_jit(
            fused_packed, "fused-packed", self._fused_key
        )
        self._fused_packed_src = fn
        return self._fused_packed

    def compute_masks(self, reviews: List[dict]):
        """-> (ordered constraints, match&violation candidate mask [C, R],
        autoreject mask [C, R]) as numpy arrays.

        Multi-chip: when a mesh is available the row axis is padded to a
        mesh multiple and committed sharded (input placement drives the
        SPMD compile of the SAME fused jit); results come back trimmed so
        callers see identical shapes on 1 or N devices."""
        import time as _time

        t0 = _time.perf_counter()
        fn, ordered, rp, cp, cols, group_params, crow = self._device_inputs(
            reviews
        )
        rows = len(rp.arrays["valid"])
        t1 = _time.perf_counter()
        packed = self._dispatch(
            self._packed_variant(fn), rp.arrays, cp.arrays, cols,
            group_params, rows,
        )
        both = np.unpackbits(np.asarray(packed), axis=1)
        t2 = _time.perf_counter()
        # stage telemetry: spans mirror into every request trace this
        # batch serves; the histograms double-record the same intervals
        obstrace.record_span("tpu.pack", t0, t1, stage=obstrace.PACK,
                             reviews=len(reviews))
        obstrace.record_span(
            "tpu.dispatch", t1, t2, stage=obstrace.DISPATCH,
            tier="tpu", breaker=self.breaker.state, rows=rows,
        )
        record_stage(PACK_M, t1 - t0, {"path": "review"})
        record_stage(DISPATCH_M, t2 - t1, {"path": "review", "tier": "tpu"})
        if obscosts.enabled():
            obscosts.record_dispatch(
                self._cost_kind_counts(), t2 - t1, len(reviews),
                path="review",
            )
        c = both.shape[0] // 2
        # crow maps each ordered constraint to its group-major mask row
        # (pad block rows drop out here)
        return (
            ordered,
            both[:c][crow][:, :rows].astype(bool),
            both[c:][crow][:, :rows].astype(bool),
        )

    # ---- render (exactness filter) ---------------------------------------

    def _render_plan_for(self, kind: str, name: str, constraint: dict):
        """The constraint's bound render plan (ops/renderplan.py), or None
        when the template is plan-ineligible.  Cached per constraint-side
        epoch (binding is cheap but not free; rendering a drifted cluster
        touches every constraint).  Caller holds the lock."""
        if not self.render_plan_enabled:
            return None
        if self._bound_plans_epoch != self._cs_epoch:
            self._bound_plans.clear()
            self._bound_plans_epoch = self._cs_epoch
        key = (kind, name)
        got = self._bound_plans.get(key, _PLAN_MISS)
        if got is not _PLAN_MISS:
            return got
        plan = None
        tmpl = self.templates.get(kind)
        prog = self.programs.get(kind)
        if tmpl is not None and prog is not None:
            from . import renderplan

            try:
                plan = renderplan.bind(prog, tmpl.policy, constraint)
            except Exception:  # a plan bug must degrade, never fail a cell
                log.exception("render-plan bind failed for %s/%s", kind, name)
                plan = None
        self._bound_plans[key] = plan
        return plan

    def _render_plan_tiers(self) -> Dict[str, str]:
        """Per-constraint render-plan classification ("kind/name" ->
        tier), shared by the snapshot writer (persists it in the sweep
        basis) and loader (validates the rebuilt classification against
        it).  Caller holds the lock."""
        out: Dict[str, str] = {}
        for kind, name, constraint in self._ordered_constraints():
            try:
                plan = self._render_plan_for(kind, name, constraint)
            except Exception:
                plan = None
            out[f"{kind}/{name}"] = (
                plan.tier if plan is not None else "interp"
            )
        return out

    def _cost_kind_counts(self) -> Dict[str, int]:
        """{template kind: live constraint count} for cost-ledger
        dispatch apportioning, cached per constraint-side epoch."""
        cached = self._cost_kinds_cache
        if cached is not None and cached[0] == self._cs_epoch:
            return cached[1]
        counts = {k: len(v) for k, v in self.constraints.items() if v}
        self._cost_kinds_cache = (self._cs_epoch, counts)
        return counts

    def _flush_render_counts(self):
        """Export the pass's per-tier cell counts to
        render_cells_total{plan=...} (one registry record per tier per
        pass, not per cell)."""
        counts = self._tier_counts
        if counts["static"] or counts["slots"] or counts["interp"]:
            record_render_cells(counts)
            self._tier_counts = {"static": 0, "slots": 0, "interp": 0}

    def _eval_cell(
        self, constraint: dict, kind: str, review: dict, frozen_review,
        inventory, rowview=None, allow_plan: bool = True,
        count: bool = True,
    ) -> list:
        """Exact evaluation of one (constraint, review) cell: native match
        re-check + violation rendering — via the compiled render plan when
        this constraint has one (byte-identical to the interpreter by
        construction, tests/test_render_parity.py), else the interpreter.
        Returns the violation dicts ([] when the device mask
        over-approximated)."""
        from ..engine.value import freeze

        tmpl = self.templates.get(kind)
        if tmpl is None:
            return []
        if not constraint_matches(constraint, review, self.store.cached_namespace):
            return []  # device over-approximation filtered here
        if allow_plan:
            plan = self._render_plan_for(
                kind, _constraint_name(constraint), constraint
            )
            if plan is not None:
                if rowview is None:
                    from .renderplan import RowView

                    rowview = RowView(review, frozen_review)
                if count:
                    self._tier_counts[plan.tier] += 1
                return plan.apply(rowview)
        if count:
            self._tier_counts["interp"] += 1
        params = constraint_parameters(constraint)
        if frozen_review is None:
            frozen_review = (
                rowview.frozen() if rowview is not None else freeze(review)
            )
        return tmpl.policy.eval_violations(
            frozen_review, freeze(params), inventory
        )

    @staticmethod
    def _cell_memoable(tmpl, constraint: dict) -> bool:
        """A (constraint, object) verdict is content-determined iff the
        template's policy is memo-safe and inventory-free and the match
        spec carries no namespaceSelector — PRESENCE check, not truthiness:
        an empty selector ({}) still consults the mutable namespace cache
        (target/match.py presence semantics), so a memoized verdict could
        outlive a namespace sync."""
        if tmpl is None:
            return False
        if not getattr(tmpl.policy, "memo_safe", False):
            return False
        if getattr(tmpl.policy, "uses_inventory", True):
            return False
        match = constraint_match_spec(constraint)
        return "namespaceSelector" not in match

    def _cell_violations(
        self, constraint: dict, kind: str, review: dict, frozen_review,
        inventory, memo_review=None, rowview=None,
    ) -> list:
        # content-keyed memo: identical (constraint, object) cells render
        # identically while the constraint side is unchanged, PROVIDED the
        # cell depends only on its inputs: excluded are templates reading
        # data.inventory, policies that are not memo_safe (wall-clock
        # builtins or per-request metadata reads, engine/interp.py), and
        # constraints with a namespaceSelector (whose match consults the
        # MUTABLE cached-namespace store, target/match.py) — a memoized
        # verdict must never outlive a namespace relabel.  The key strips
        # per-request metadata (uid) so real admission traffic, where every
        # request has a fresh uid, still hits.
        tmpl = self.templates.get(kind)
        if self._cell_memoable(tmpl, constraint):
            if self._review_memo_epoch != self._cs_epoch:
                self._review_memo.clear()
                self._review_memo_epoch = self._cs_epoch
            if memo_review is None:
                if frozen_review is None:
                    frozen_review = rowview.frozen()
                memo_review = _strip_request_meta(frozen_review)
            mkey = (kind, _constraint_name(constraint), memo_review)
            violations = self._review_memo.get(mkey)
            if violations is None:
                violations = self._eval_cell(
                    constraint, kind, review, frozen_review, inventory,
                    rowview,
                )
                # bounded: unique objects (pod names) make keys unbounded
                # on a busy cluster; clearing 16k entries is ~ms, far below
                # the interp evals the memo saves
                if len(self._review_memo) >= self.REVIEW_MEMO_MAX:
                    self._review_memo.clear()
                self._review_memo[mkey] = violations
        else:
            violations = self._eval_cell(
                constraint, kind, review, frozen_review, inventory, rowview
            )
        return violations

    def _render_cell(
        self,
        results: List[Result],
        constraint: dict,
        kind: str,
        review: dict,
        frozen_review,
        inventory,
        tracing_log,
        memo_review=None,
        rowview=None,
    ):
        violations = self._cell_violations(
            constraint, kind, review, frozen_review, inventory,
            memo_review=memo_review, rowview=rowview,
        )
        self._append_violation_results(
            results, violations, constraint, kind, review, tracing_log
        )

    def _append_violation_results(self, results, violations, constraint,
                                  kind, review, tracing_log=None):
        """The ONE violation-dict -> Result shaping (msg/str coercion,
        details default, per-constraint enforcement action), shared by
        the per-cell and bulk masked render paths."""
        if not violations:
            return
        action = self._enforcement_action(constraint)
        for v in violations:
            results.append(
                Result(
                    msg=str(v.get("msg", "")),
                    metadata={"details": v.get("details", {})},
                    constraint=constraint,
                    review=review,
                    enforcement_action=action,
                )
            )
            if tracing_log is not None:
                tracing_log.append(
                    f"violation {kind}/{constraint['metadata']['name']}: {v.get('msg')}"
                )

    def review(self, review: dict, tracing: bool = False):
        return self.review_batch([review], tracing=tracing)[0]

    # whole-request memo size bound (entries are per unique object content)
    REQUEST_MEMO_MAX = 8192

    def _request_memoable(self) -> bool:
        """True when a whole request's verdict depends ONLY on its content:
        every installed template's policy is memo-safe and inventory-free,
        and no constraint carries a namespaceSelector (whose match — and
        autoreject — consult the mutable namespace cache).  Then the entire
        C-constraint walk can be served from one dict hit, which is what
        keeps p50 flat for replica/retry storms at large constraint counts
        (the reference re-runs the full Rego scan per request,
        target_template_source.go:27-44).  O(1): the mutators maintain
        _memoable_false incrementally (_memoable_update)."""
        flag = not self._memoable_false
        self._request_memo_ok = flag
        return flag

    def _gvk_walk_list(self, review: dict) -> List[Tuple[str, str, dict]]:
        """The sorted constraint subset an interp walk must visit for this
        review: constraints whose match.kinds could possibly hit the
        review's (group, kind) — exact pairs plus wildcard buckets — and
        every namespaceSelector-carrying constraint (autoreject is kind-
        independent, target_template_source.go:12-25).  The index mirrors
        pack_constraints' kind-pair semantics (an entry with empty
        apiGroups or kinds contributes no pairs and never matches).
        This is the reference's matching_constraints linear scan replaced
        by a GVK index so a 500-template install does not tax reviews of
        unrelated kinds (audit already kind-pre-filters)."""
        idx = self._gvk_cache
        if idx is None or idx[0] != self._cs_epoch:
            by_pair: Dict[Tuple[str, str], list] = {}
            nssel: list = []
            for entry in self._ordered_constraints():
                _kind, _name, c = entry
                # non-dict spec/match degrade to {} (constraint_match_spec
                # mirrors target/match.py _get): one malformed constraint
                # must not fail every interp-path review
                match = constraint_match_spec(c)
                if "namespaceSelector" in match:
                    nssel.append(entry)
                kinds = match.get("kinds")
                if kinds is None:
                    # missing OR explicit null both mean wildcard — the
                    # oracle's _get and pack.py:298 treat them identically
                    kinds = [{"apiGroups": ["*"], "kinds": ["*"]}]
                if isinstance(kinds, list):
                    for ks in kinds:
                        if not isinstance(ks, dict):
                            continue
                        for g in ks.get("apiGroups") or []:
                            for k in ks.get("kinds") or []:
                                by_pair.setdefault(
                                    (str(g), str(k)), []
                                ).append(entry)
            idx = (self._cs_epoch, by_pair, nssel)
            self._gvk_cache = idx
        _epoch, by_pair, nssel = idx
        rk = review.get("kind")
        g = rk.get("group") if isinstance(rk, dict) else None
        k = rk.get("kind") if isinstance(rk, dict) else None
        probes = [("*", "*")]
        if isinstance(k, str):
            probes.append(("*", k))
        if isinstance(g, str):
            probes.append((g, "*"))
            if isinstance(k, str):
                probes.append((g, k))
        out: Dict[Tuple[str, str], Tuple[str, str, dict]] = {}
        for p in probes:
            for entry in by_pair.get(p, ()):
                out[entry[:2]] = entry
        for entry in nssel:
            out[entry[:2]] = entry
        return [out[key] for key in sorted(out)]


    def _inventory_for_render(self):
        """The frozen inventory handed to render paths, or an empty
        FrozenDict when NO installed template reads data.inventory: the
        exact render can then never touch it, and a restart's first
        sweep skips freezing the whole cluster tree (O(cluster), ~5s at
        20k objects — the dominant share of warm-restart time for
        inventory-free corpora).  Templates that do read inventory keep
        the full (incrementally re-spined) snapshot.  The any-template
        scan is cached per constraint-side epoch: it ran per np-served
        review, which at 500 installed templates was a measurable slice
        of the admission path."""
        cached = self._uses_inventory_cache
        if cached is not None and cached[0] == self._cs_epoch:
            uses = cached[1]
        else:
            uses = any(
                getattr(t.policy, "uses_inventory", True)
                for t in self.templates.values()
            )
            self._uses_inventory_cache = (self._cs_epoch, uses)
        if uses:
            return self.store.frozen()
        from ..engine.value import freeze

        return freeze({})

    def _interp_review_memo(self, review: dict, memo_key=None):
        """InterpDriver.review semantics served through the content-keyed
        render memos: the hybrid small-batch path and the async-compile
        fallback — i.e. ordinary single admission requests — skip
        re-evaluating (constraint, object) cells they have seen before,
        and when every cell is content-determined the whole constraint
        walk collapses to one request-level memo hit.
        Traced reviews go to the oracle directly (drivers.py review)."""
        import time as _time

        from ..engine.value import freeze

        t_enter = _time.perf_counter()
        with self._lock:
            t_locked = _time.perf_counter()
            # lock-wait vs evaluation breakdown (read by bench.py's ingest
            # config): distinguishes queueing behind a concurrent template
            # compile from actual interp evaluation cost
            self.last_review_stats = {
                "lock_wait_ms": (t_locked - t_enter) * 1e3,
            }
            # the interp walk has no masked render pass: stale stats from
            # a previous _render_masked must not be re-read by bench
            self.last_render_stats = {}
            inventory = self._inventory_for_render()
            cached_ns = self.store.cached_namespace
            if memo_key is not None:
                frozen_review, memo_review = memo_key
            else:
                frozen_review = freeze(review)
                memo_review = _strip_request_meta(frozen_review)
            # synced under THIS lock hold: the store below must never run
            # on a memoable verdict from a pre-epoch-bump constraint side
            memoable = self._memoable_synced()
            from .renderplan import RowView

            rowview = RowView(review, frozen_review)
            results: List[Result] = []
            for kind, name, constraint in self._gvk_walk_list(review):
                if needs_autoreject(constraint, review, cached_ns):
                    results.append(
                        Result(
                            msg="Namespace is not cached in OPA.",
                            metadata={"details": {}},
                            constraint=constraint,
                            review=review,
                            enforcement_action=self._enforcement_action(
                                constraint
                            ),
                        )
                    )
                # _render_cell re-checks the match and returns nothing
                # for non-matching constraints or missing templates —
                # identical semantics to the oracle's walk
                self._render_cell(
                    results, constraint, kind, review, frozen_review,
                    inventory, None, memo_review=memo_review,
                    rowview=rowview,
                )
            if memoable:
                self._store_request_memo(review, results, memo_review)
            self._flush_render_counts()
            self.last_review_stats["eval_ms"] = (
                _time.perf_counter() - t_locked) * 1e3
            return results, None

    def _request_memo_hit(self, review: dict):
        """Serve a review wholly from the request memo — repairing a
        stale entry through the constraint-side change log — or (None,
        memo key) on miss, (None, None) when unmemoable.  review_batch
        consults this BEFORE routing, so repeat-content admissions
        (replica/retry storms) stay at memo speed regardless of which
        path unique content would take; the (frozen review, stripped memo
        key) pair travels to the miss path so the review is frozen
        exactly once whichever path serves it."""
        import time as _time

        from ..engine.value import freeze

        t_enter = _time.perf_counter()
        with self._lock:
            t_locked = _time.perf_counter()
            if not self._memoable_synced():
                return None, None
            frozen_review = freeze(review)
            memo_review = _strip_request_meta(frozen_review)
            memo_key = (frozen_review, memo_review)
            hit = self._request_memo.get(memo_review)
            if hit is None:
                return None, memo_key
            if hit[0] != self._cs_epoch:
                per_key = self._repair_memo_entry(
                    hit[0], hit[1], review, frozen_review, memo_review,
                    self._inventory_for_render(),
                    self.store.cached_namespace,
                )
                if per_key is None:
                    return None, memo_key  # log overran: full re-eval
                # flatten ONCE per repair (O(C)); every replay at this
                # epoch is then O(violations)
                flat = [
                    (kind, name, entry)
                    for kind in sorted(self.constraints)
                    for name in sorted(self.constraints[kind])
                    for entry in per_key.get((kind, name), ())
                ]
                hit = (self._cs_epoch, per_key, flat)
                self._request_memo[memo_review] = hit
            # rebuilt per hit down to the details object: handing out any
            # cached mutable by reference would let a consumer's mutation
            # corrupt every later replay
            out = [
                Result(
                    msg=msg,
                    metadata={"details": copy.deepcopy(details)},
                    constraint=self.constraints[kind][name],
                    review=review,
                    enforcement_action=action,
                )
                for kind, name, (msg, details, action) in hit[2]
            ]
            self.last_review_stats = {
                "lock_wait_ms": (t_locked - t_enter) * 1e3,
                "eval_ms": (_time.perf_counter() - t_locked) * 1e3,
            }
            return out, memo_key

    def _eval_one_key(self, kind, name, review, frozen_review, memo_review,
                      inventory, cached_ns, rowview=None):
        """Evaluate a single constraint for the request memo's repair
        path: the same autoreject + render walk _interp_review_memo runs
        per key, returning the memoized tuple list (None when the
        constraint no longer exists)."""
        constraint = self.constraints.get(kind, {}).get(name)
        if constraint is None:
            return None
        out: List[Result] = []
        if needs_autoreject(constraint, review, cached_ns):
            out.append(
                Result(
                    msg="Namespace is not cached in OPA.",
                    metadata={"details": {}},
                    constraint=constraint, review=review,
                    enforcement_action=self._enforcement_action(constraint),
                )
            )
        self._render_cell(
            out, constraint, kind, review, frozen_review, inventory, None,
            memo_review=memo_review, rowview=rowview,
        )
        return [
            (r.msg, copy.deepcopy((r.metadata or {}).get("details", {})),
             r.enforcement_action)
            for r in out
        ]

    def _repair_memo_entry(self, entry_epoch, per_key, review,
                           frozen_review, memo_review, inventory,
                           cached_ns):
        """Bring a stale request-memo entry current by re-evaluating ONLY
        the constraints the change log records after entry_epoch.  Returns
        the repaired per-key dict, or None when the log no longer covers
        the entry (caller falls back to a full evaluation)."""
        if entry_epoch < self._cs_log_floor:
            return None
        from .renderplan import RowView

        rowview = RowView(review, frozen_review)
        changed_kinds = set()
        changed_keys = set()
        for ep, kind, name in reversed(self._cs_change_log):
            if ep <= entry_epoch:
                break
            if name is None:
                changed_kinds.add(kind)
            else:
                changed_keys.add((kind, name))
        per_key = dict(per_key)
        for kind in changed_kinds:
            for k in [k for k in per_key if k[0] == kind]:
                del per_key[k]
            for name in self.constraints.get(kind, {}):
                res = self._eval_one_key(
                    kind, name, review, frozen_review, memo_review,
                    inventory, cached_ns, rowview=rowview,
                )
                if res:
                    per_key[(kind, name)] = res
        for kind, name in changed_keys:
            if kind in changed_kinds:
                continue
            res = self._eval_one_key(
                kind, name, review, frozen_review, memo_review, inventory,
                cached_ns, rowview=rowview,
            )
            if res:
                per_key[(kind, name)] = res
            else:
                per_key.pop((kind, name), None)
        self._flush_render_counts()
        return per_key

    # Below this many constraint x review cells the device dispatch costs
    # more than it saves (kernel launch + host<->device transfer — or a
    # full network RTT when the chip sits behind a relay); small batches
    # evaluate host-side with the exact native matcher + interpreter.
    # This static threshold is the PRIOR: calibrate_routing() replaces it
    # with a measured cost model (dispatch RTT + per-cell device rate vs
    # per-cell interp rate), so the crossover adapts to the attachment —
    # ~1k cells behind a network relay, tens of cells on local silicon.
    DEVICE_MIN_CELLS = int(os.environ.get("GK_DEVICE_MIN_CELLS", "4096"))

    def calibrate_routing(self, runs: int = 3) -> Optional[dict]:
        """Measure once: affine cost models for all THREE evaluation paths
        — device (dispatch floor + per-cell rate, fitted from the REAL
        compute_masks path at a 1-review probe — the admission shape —
        and a large batch; a synthetic ping would be served from a relay's
        content cache and lie), host numpy serving (floor + per-cell), and
        the per-cell interpreter rate.  review_batch then routes each
        request by predicted cost instead of static priors.  Explicit call
        (main.py startup / bench): never triggered implicitly, so test
        paths stay deterministic.  Returns the calibration dict, or None
        when no constraints are installed."""
        import time as _time

        with self._lock:
            n_constraints = sum(len(v) for v in self.constraints.values())
            if n_constraints == 0:
                return None

        seq = [0]

        def cal_review():
            seq[0] += 1
            i = seq[0]
            return {
                "kind": {"group": "", "version": "v1", "kind": "Pod"},
                "name": f"gk-route-cal-{i}", "namespace": "default",
                "operation": "CREATE",
                "object": {
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"gk-route-cal-{i}",
                                 "namespace": "default",
                                 "labels": {"cal": str(i)}},
                    "spec": {"containers": [
                        {"name": "c", "image": f"cal.io/x:{i}"}]},
                },
            }

        def device_ms(batch):
            ts = []
            for _ in range(runs + 1):  # first run absorbs compiles/warmup
                reviews = [cal_review() for _ in range(batch)]
                with self._lock:
                    t0 = _time.perf_counter()
                    self.compute_masks(reviews)
                    ts.append(_time.perf_counter() - t0)
            # median, deliberately asymmetric with the host paths' min:
            # a dispatch's run-to-run variance (relay/interconnect RTT) is
            # intrinsic cost every real request pays, so the route should
            # price its expectation; host-path variance is scheduler noise
            # a real request mostly does NOT pay
            return float(np.median(ts[1:])) * 1e3

        def affine(ms_small, ms_large, cells_small, cells_large):
            per_cell = max(
                (ms_large - ms_small) / max(cells_large - cells_small, 1),
                1e-9,
            )
            floor = max(ms_small - per_cell * cells_small, 1e-3)
            return floor, per_cell

        # device: 1-review probe (the admission shape the r4 routing model
        # extrapolated to, badly) + a large batch for the slope
        b_large = 128
        dev_floor, dev_per_cell = affine(
            device_ms(1), device_ms(b_large),
            n_constraints, b_large * n_constraints,
        )

        np_floor = np_per_cell = None
        if self.np_serve_enabled:
            def np_ms(batch):
                ts = []
                for _ in range(runs + 1):
                    reviews = [cal_review() for _ in range(batch)]
                    t0 = _time.perf_counter()
                    self._np_review(reviews)
                    ts.append(_time.perf_counter() - t0)
                # min, not median: pure host work — the minimum is the
                # true cost, everything above it is scheduler noise that
                # would bias the route away from the numpy path
                return float(min(ts[1:])) * 1e3

            np_floor, np_per_cell = affine(
                np_ms(1), np_ms(8), n_constraints, 8 * n_constraints,
            )

        # warm first, then MEDIAN of the warm samples — the r05 curve
        # misrouted N=50 to interp (6.28ms measured vs np's 2.11ms)
        # because min() over three samples that include cold parser/
        # freeze caches prices the interpreter at its best-case rate,
        # which real unique-content requests do not pay.  Like the
        # device probe above, the route should price the expectation;
        # the np path keeps min() deliberately (its floor is what the
        # route must not be biased away from).
        self._interp_review_memo(cal_review())  # warm: parser/freeze/caches
        interp_ts = []
        for _ in range(max(runs, 3) + 2):
            rv = cal_review()  # unique: the request memo cannot serve it
            t0 = _time.perf_counter()
            self._interp_review_memo(rv)
            interp_ts.append(_time.perf_counter() - t0)
        interp_ms = float(np.median(interp_ts)) * 1e3
        interp_cells_per_ms = n_constraints / max(interp_ms, 1e-3)

        cal = {
            "rtt_ms": dev_floor,  # affine intercept: dispatch+fetch floor
            "device_cells_per_ms": 1.0 / dev_per_cell,
            "interp_cells_per_ms": interp_cells_per_ms,
        }
        if np_floor is not None:
            cal["np_floor_ms"] = np_floor
            cal["np_cells_per_ms"] = 1.0 / np_per_cell
        self._route_cal = cal
        return cal

    # uncalibrated prior for np-vs-interp: the numpy serve has a ~1-2ms
    # floor (pack + mats + mask), the interpreter walks ~10-20 cells/ms —
    # below this many cells the walk wins
    NP_MIN_CELLS = int(os.environ.get("GK_NP_MIN_CELLS", "24"))

    # a load hint older than this is stale (the batcher refreshes every
    # dispatch; a gone batcher must not pin throughput routing forever)
    LOAD_HINT_TTL_S = 5.0
    # feasibility margin: a tier must sustain the offered load with this
    # much headroom before latency-routing may pick it — running a tier
    # at 100% of its measured capacity queues unboundedly
    LOAD_HEADROOM = 1.25

    def set_offered_load(self, rps: Optional[float]):
        """Offered-load hint from the micro-batcher (reviews/s).  With a
        fresh hint and a calibration, _route_eval prices SUSTAINABLE
        throughput: the latency-optimal tier is only chosen while it can
        actually carry the offered rate (docs/fleet.md)."""
        import time as _time

        if rps and rps > 0:
            self._offered_load = (float(rps), _time.monotonic())
        else:
            self._offered_load = None

    def _load_hint(self) -> Optional[float]:
        h = self._offered_load
        if h is None:
            return None
        import time as _time

        rps, t = h
        return rps if _time.monotonic() - t <= self.LOAD_HINT_TTL_S else None

    def set_brownout_pin(self, active: bool):
        """Brownout ladder level 3 (obs/brownout.py): pin routing to the
        cheapest sustainable tier — the max-throughput choice the
        saturated branch of _route_eval makes, but unconditionally, so
        the pin holds even between batcher dispatches (a stale load
        hint must not un-pin a declared brownout)."""
        self._brownout_pin = bool(active)

    def _tier_models(self, per_review_cells: int):
        """[(tier, floor_ms, per_review_ms)] from the calibration — the
        affine service model shared by latency routing, load-aware
        routing, and the batcher's adaptation loop."""
        cal = self._route_cal
        if cal is None:
            return []
        out = [
            ("interp", 0.0, per_review_cells / cal["interp_cells_per_ms"]),
            ("device", cal["rtt_ms"],
             per_review_cells / cal["device_cells_per_ms"]),
        ]
        if self.np_serve_enabled and "np_floor_ms" in cal:
            out.append(
                ("np", cal["np_floor_ms"],
                 per_review_cells / cal["np_cells_per_ms"])
            )
        return out

    # the largest batch the serving layer coalesces (MicroBatcher
    # max_batch default): tier capacity is measured at this batch size
    ROUTE_MAX_BATCH = 256.0

    def _route_decision(self, cells: int, n_reviews: int = 1,
                        want_priced: bool = True):
        """The pricing behind :meth:`_route_eval` -> (route, reason,
        lam, priced): the chosen tier, the reason that decided it
        (obs/routeledger.py REASONS), the offered-load hint consulted,
        and the priced tier table [{tier, floor_ms, per_review_ms,
        predicted_ms, mu_rps}] — what `/debug/routez` explains a
        decision with.  Pure: recording is the caller's job, so the
        breaker/compile overrides in _review_batch_eval can amend the
        effective tier before one ledger entry lands.

        want_priced=False (a disabled ledger) skips the table build, and
        the service models/mu are computed lazily — the calibrated
        latency fast path then pays exactly what it did pre-ledger."""
        if self.DEVICE_MIN_CELLS == 0:
            return "device", "forced_device", None, []
        cal = self._route_cal
        np_on = self.np_serve_enabled
        if cal is None:
            if cells >= self.DEVICE_MIN_CELLS:
                return "device", "uncalibrated_prior", None, []
            route = (
                "np" if np_on and cells >= self.NP_MIN_CELLS else "interp"
            )
            return route, "uncalibrated_prior", None, []
        device_ms = cal["rtt_ms"] + cells / cal["device_cells_per_ms"]
        interp_ms = cells / cal["interp_cells_per_ms"]
        costs = [(interp_ms, "interp"), (device_ms, "device")]
        if np_on and "np_floor_ms" in cal:
            costs.append(
                (cal["np_floor_ms"] + cells / cal["np_cells_per_ms"], "np")
            )
        per_review = max(cells // max(n_reviews, 1), 1)
        B = self.ROUTE_MAX_BATCH
        state: dict = {}

        def tier_mu():
            if "mu" not in state:
                state["models"] = self._tier_models(per_review)
                state["mu"] = {
                    tier: B / max(floor + B * per_ms, 1e-9)
                    for tier, floor, per_ms in state["models"]
                }
            return state["mu"]

        def priced():
            if not want_priced:
                return []
            mu = tier_mu()
            predicted = {tier: ms for ms, tier in costs}
            return [
                {
                    "tier": tier,
                    "floor_ms": round(floor, 4),
                    "per_review_ms": round(per_ms, 6),
                    "predicted_ms": round(predicted.get(tier, 0.0), 4),
                    # mu is per-ms; export reviews/s for readability
                    "mu_rps": round(mu[tier] * 1e3, 1),
                }
                for tier, floor, per_ms in state["models"]
            ]

        if self._brownout_pin:
            # brownout pin: the max-throughput tier at the coalesced
            # batch size, unconditionally — the queue drains fastest
            # there, which is the only latency that matters mid-brownout
            mu = tier_mu()
            if mu:
                chosen = max(mu.items(), key=lambda kv: kv[1])[0]
                return chosen, "brownout_pin", self._load_hint(), priced()
        lam = self._load_hint()
        if lam:
            mu = tier_mu()
            lam_pms = lam / 1e3  # reviews per ms
            sustainable = [
                (ms, tier) for ms, tier in costs
                if mu.get(tier, 0.0) >= lam_pms * self.LOAD_HEADROOM
            ]
            if sustainable:
                return min(sustainable)[1], "load_aware", lam, priced()
            if mu:  # saturated everywhere: drain via max throughput
                chosen = max(mu.items(), key=lambda kv: kv[1])[0]
                return chosen, "saturated", lam, priced()
        return min(costs)[1], "latency", lam, priced()

    def _route_eval(self, cells: int, n_reviews: int = 1) -> str:
        """Predicted-cheapest path for a request of `cells` =
        reviews x constraints: "device" | "np" | "interp".
        DEVICE_MIN_CELLS = 0 always forces the device (tests rely on it);
        uncalibrated, the static DEVICE_MIN_CELLS / NP_MIN_CELLS priors
        decide.

        With a fresh offered-load hint (set_offered_load) the choice is
        LOAD-aware, not size-only: each tier's sustainable throughput is
        mu = B / (floor + B*per_review_ms) at the max coalesced batch B;
        tiers that cannot carry the offered rate (with headroom) are
        excluded even when they'd win this batch's latency, and when no
        tier sustains it the highest-throughput tier is chosen so the
        queue drains fastest.

        Every decision lands in the route ledger (obs/routeledger.py —
        /debug/routez, route_decisions_total)."""
        route, reason, lam, priced = self._route_decision(
            cells, n_reviews, want_priced=self.route_ledger.enabled
        )
        self.route_ledger.record(
            route, reason, cells, n_reviews, lam, priced
        )
        return route


    # batches up to this size are admission traffic: they probe and feed
    # the whole-request memo; larger (streaming) chunks skip both so the
    # sparse render keeps its zero-per-review host cost
    REQUEST_MEMO_BATCH_MAX = 64

    def review_batch(self, reviews: List[dict], tracing: bool = False):
        """N concurrent admission reviews in ONE device dispatch: the mask
        is [C, N], then each review's positive cells render host-side.
        This is the micro-batching seam the webhook server drives.

        Hybrid dispatch: batches too small to amortize a device call run
        through the interpreter path (identical semantics — the device mask
        is only ever a pruning over-approximation of it)."""
        if not reviews:
            return []
        if tracing or len(reviews) > self.REQUEST_MEMO_BATCH_MAX:
            return self._review_batch_eval(reviews, tracing)
        # repeat-content fast path BEFORE routing: a memoized request must
        # never pay a device dispatch (or an interp walk); misses are
        # evaluated as one sub-batch while the hits replay as-is.  The
        # frozen memo keys computed by the probe ride along so the miss
        # path never re-freezes the same review (freeze is ~0.5ms on a
        # real Pod — pure waste twice per unique admission).
        import time as _time

        t0 = _time.perf_counter()
        probed = [self._request_memo_hit(r) for r in reviews]
        served: List = [p[0] for p in probed]
        misses = [i for i, s in enumerate(served) if s is None]
        obstrace.record_span(
            "memo.lookup", t0, _time.perf_counter(),
            stage=obstrace.CACHE_LOOKUP,
            hits=len(reviews) - len(misses), misses=len(misses),
        )
        record_cache("request_memo", True, len(reviews) - len(misses))
        record_cache("request_memo", False, len(misses))
        if misses:
            evaled = self._review_batch_eval(
                [reviews[i] for i in misses], tracing,
                memo_reviews=[probed[i][1] for i in misses],
            )
            for j, i in enumerate(misses):
                served[i] = evaled[j]
        return [s if isinstance(s, tuple) else (s, None) for s in served]

    def _n_constraints_total(self) -> int:
        """Installed constraint count, cached per epoch (summing 500
        kinds per admission is real).  Caller need not hold the lock."""
        with self._lock:  # concurrent ingest may resize the dicts (RLock)
            cached = self._n_constraints_cache
            if cached is not None and cached[0] == self._cs_epoch:
                return cached[1]
            n_constraints = sum(
                len(v) for v in self.constraints.values()
            )
            self._n_constraints_cache = (self._cs_epoch, n_constraints)
            return n_constraints

    def predicted_batch_ms(self, n_reviews: int) -> Optional[float]:
        """Predicted service time (ms) of an n-review coalesced batch on
        its cheapest tier — the micro-batcher's adaptation model.  None
        until calibrate_routing has run."""
        if self._route_cal is None:
            return None
        per_review = max(self._n_constraints_total(), 1)
        models = self._tier_models(per_review)
        if not models:
            return None
        return min(
            floor + n_reviews * per_ms for _t, floor, per_ms in models
        )

    def _review_batch_eval(self, reviews: List[dict], tracing: bool,
                           memo_reviews: Optional[list] = None):
        """Route and evaluate (no memo probe: review_batch already served
        the hits)."""
        n_constraints = self._n_constraints_total()
        cells = len(reviews) * max(n_constraints, 1)
        route, reason, lam, priced = self._route_decision(
            cells, n_reviews=len(reviews),
            want_priced=self.route_ledger.enabled,
        )
        effective = route
        if route == "device":
            if self._compiler is not None and not self._compiler.ready():
                # async ingestion: while the background XLA compile for
                # the latest template/constraint epoch is in flight,
                # admission reviews serve from the host paths instead of
                # blocking
                effective = "np" if self.np_serve_enabled else "interp"
                reason = "compile_pending"
            elif not self.breaker.allow():
                # circuit breaker: while open, every evaluation serves
                # from the host tiers below — the degradation ladder's
                # middle rung (docs/failure-modes.md); the background
                # probe brings the device back without real traffic
                # paying failed dispatches.  Checked LAST (and only for a
                # device route) so a granted half-open trial is always
                # followed by the device attempt below (which records its
                # outcome) — an earlier divert would leak the trial token
                effective = "np" if self.np_serve_enabled else "interp"
                reason = "breaker_open"
        # one ledger entry per batch, recorded at the SERVE site so the
        # entry names the tier that actually evaluated — override
        # reasons (breaker_open/compile_pending) explain why a priced
        # device win served host-side, and an np-ineligible batch that
        # falls through to the interpreter is attributed to interp, not
        # to the tier the pricing predicted (obs/routeledger.py)
        def _record(tier):
            self.route_ledger.record(
                tier, reason, cells, len(reviews), lam, priced
            )

        if effective != "device":
            if tracing:
                _record("interp")  # traced runs take the interp walk
                return [
                    InterpDriver.review(self, r, tracing=True)
                    for r in reviews
                ]
            if effective != "interp":  # np predicted cheaper or diverted
                out = self._np_review(reviews, memo_reviews)
                if out is not None:
                    _record("np")
                    return out
            _record("interp")
            return self._interp_serve(reviews, memo_reviews)
        _record("device")
        with self._lock:
            try:
                ordered, mask, autoreject = self.compute_masks(reviews)
            except Exception as e:
                # backend failure: feed the breaker and degrade THIS batch
                # to the interpreter tier instead of poisoning the whole
                # window — callers always get an answer or a deadline.
                # Only the flagging happens under the lock; the fallback
                # walk below runs OUTSIDE it (per-review locking, like the
                # normal interp divert path) so concurrent ingest and the
                # audit thread don't stall behind a failed batch's render
                self.breaker.record_failure(e)
                log.warning(
                    "device evaluation failed (%s: %s); serving %d "
                    "review(s) from the interpreter tier",
                    type(e).__name__, e, len(reviews),
                )
                device_failed = True
            else:
                device_failed = False
                self.breaker.record_success()
            if not device_failed:
                inventory = self._inventory_for_render()
                mask_np = np.asarray(mask)
                rej_np = np.asarray(autoreject)
                if tracing:
                    return self._review_batch_traced(
                        reviews, ordered, mask_np, rej_np, inventory
                    )
                with obstrace.span("render", stage=obstrace.RENDER,
                                   tier="tpu"):
                    out = self._render_masked(
                        reviews, ordered, mask_np, rej_np, inventory,
                        memo_keys=memo_reviews,
                    )
                # admission-sized batches feed the request memo from the
                # device path too, so repeat content (replica/retry
                # storms — including repeat ALLOWS, the common case)
                # replays at memo speed next time; the 1M-review
                # streaming path (large chunks) never reaches here
                # (review_batch routes them straight to
                # _review_batch_eval)
                if (
                    len(reviews) <= self.REQUEST_MEMO_BATCH_MAX
                    and self._memoable_synced()
                ):
                    for ri, review in enumerate(reviews):
                        mk = memo_reviews[ri] if memo_reviews else None
                        self._store_request_memo(
                            review, out[ri][0], mk[1] if mk else None,
                        )
                return out
        # device failed: interpreter-tier fallback, lock released.  The
        # amended, SERVE-SITE ledger entry makes the fallback
        # attributable — a breaker-trip flight recording shows device ->
        # device_failed -> breaker_open in causal order — and names the
        # tier that actually evaluated (np may be ineligible for this
        # batch).  No entry lands when the deadline check below raises:
        # nothing served.
        reason = "device_failed"
        # The budget check covers SAME-THREAD callers (embedders using
        # deadline.budget() around client.review); webhook traffic is
        # bounded upstream — the micro-batcher's event-wait timeout and
        # its per-request fallback deadline checks (webhook/server.py),
        # since the batcher thread does not carry the handler thread's
        # deadline ContextVar
        if _deadline.expired():
            raise _deadline.DeadlineExceeded(
                "deadline exhausted during device-failure fallback"
            )
        if tracing:
            # traced runs must still emit their trace lines
            _record("interp")
            return [
                InterpDriver.review(self, r, tracing=True) for r in reviews
            ]
        # prefer the vectorized numpy host tier (same preference order as
        # the breaker-open divert above) — the degraded window is exactly
        # when fallback latency matters most
        out = self._np_review(reviews, memo_reviews)
        if out is not None:
            _record("np")
            return out
        _record("interp")
        return self._interp_serve(reviews, memo_reviews)

    def _interp_serve(self, reviews: List[dict],
                      memo_reviews: Optional[list] = None):
        """Interpreter-tier serving with the stage span every evaluation
        path emits: tier + breaker state make degraded traffic (breaker
        open, compile in flight) attributable in the trace."""
        with obstrace.span("eval.interp", stage=obstrace.RENDER,
                           tier="interp", breaker=self.breaker.state):
            return [
                self._interp_review_memo(
                    r, memo_reviews[i] if memo_reviews else None
                )
                for i, r in enumerate(reviews)
            ]

    def _render_masked(self, reviews, ordered, mask_np, rej_np, inventory,
                       memo_keys=None):
        """Bulk sparse render shared by the device and host (numpy) mask
        paths: iterate only (review, constraint) cells the mask marked
        positive, review-major so per-review result ordering matches the
        dense loop.  Reviews with no positive cell (the common admission
        case) cost zero host work — in particular no freeze().

        Three sub-passes, assembled back in mask order (caller holds the
        lock):
          1. plan pass — review-memo probes and compiled render plans
             (ops/renderplan.py) resolve cells without the interpreter;
             one RowView per flagged review shares every walked path
             across its constraints
          2. interp tail — the remaining cells evaluate through the
             bounded render pool
          3. assembly — Results built in the original cell order
             (autoreject entries first per cell), memo stores applied on
             this (lock-holding) thread only"""
        import time as _time

        from .renderplan import RenderPool, RowView

        # reset up front: an early return (no flagged cells) must not
        # leave the previous pass's stats for bench/telemetry readers
        self.last_render_stats = {}
        out: List = [([], None) for _ in reviews]
        ris, iis = np.nonzero((mask_np | rej_np).T)
        cells = list(zip(ris.tolist(), iis.tolist()))
        if not cells:
            return out
        # one vectorized gather instead of two scalar numpy indexings per
        # cell (each is ~300ns of fancy-indexing machinery)
        mfl = mask_np[iis, ris]
        mflags = mfl.tolist()
        rflags = rej_np[iis, ris].tolist()
        # cost-ledger attribution (obs/costs.py): flagged cells per
        # constraint come from one vectorized bincount; the loops below
        # only pay a dict add on the RARE events (violations, memo hits)
        cost_on = obscosts.enabled()
        if cost_on:
            cells_by_i = np.bincount(iis[mfl], minlength=len(ordered))
            attv: Dict[int, int] = {}
            attm: Dict[int, int] = {}
        t0 = _time.perf_counter()
        cached_ns = self.store.cached_namespace
        rows: Dict[int, RowView] = {}
        resolved: Dict[int, list] = {}
        stores: List[Tuple] = []  # (mkey, cell idx) review-memo writes
        deferred: List[Tuple] = []  # (cell idx, ri, i, mkey)
        # intra-batch dedup: a micro-batch of identical replica pods must
        # evaluate each memoable (constraint, content) cell ONCE even
        # though memo stores land only after the render passes
        seen_mkey: Dict[Tuple, int] = {}
        aliases: Dict[int, int] = {}
        memo_hits = 0
        if self._review_memo_epoch != self._cs_epoch:
            self._review_memo.clear()
            self._review_memo_epoch = self._cs_epoch
        for idx, (ri, i) in enumerate(cells):
            if not mflags[idx]:
                continue  # autoreject-only cell: handled at assembly
            kind, name, constraint = ordered[i]
            review = reviews[ri]
            row = rows.get(ri)
            if row is None:
                # seed from the request-memo probe's frozen forms when the
                # caller already paid for them (freeze is ~0.5ms per pod)
                mk = memo_keys[ri] if memo_keys else None
                row = RowView(review, mk[0] if mk else None)
                if mk is not None:
                    row._memo_frozen = mk[1]
                rows[ri] = row
            mkey = None
            # memoability via the incrementally-maintained complement set
            # (_memoable_update): O(1) per cell vs the getattr chain of
            # _cell_memoable
            if (kind, name) not in self._memoable_false and (
                kind in self.templates
            ):
                mkey = (kind, name, row.memo_frozen())
                hit = self._review_memo.get(mkey)
                if hit is not None:
                    resolved[idx] = hit
                    memo_hits += 1
                    if cost_on:
                        attm[i] = attm.get(i, 0) + 1
                        if hit:
                            attv[i] = attv.get(i, 0) + len(hit)
                    continue
                src = seen_mkey.get(mkey)
                if src is not None:
                    aliases[idx] = src  # same batch, same content cell
                    memo_hits += 1
                    if cost_on:
                        attm[i] = attm.get(i, 0) + 1
                    continue
                seen_mkey[mkey] = idx
            plan = self._render_plan_for(kind, name, constraint)
            if plan is not None:
                # the mask cell already includes the packed match; the
                # native re-check is only needed where packing can
                # over-approximate it (label/namespace selectors)
                if plan.match_exact or constraint_matches(
                    constraint, review, cached_ns
                ):
                    self._tier_counts[plan.tier] += 1
                    violations = plan.apply(row)
                else:
                    violations = []  # device over-approximated the match
                resolved[idx] = violations
                if cost_on and violations:
                    attv[i] = attv.get(i, 0) + len(violations)
                if mkey is not None:
                    stores.append((mkey, idx))
                continue
            deferred.append((idx, ri, i, mkey))
        t1 = _time.perf_counter()
        if deferred:
            thunks = [
                (lambda c=ordered[i][2], k=ordered[i][0], r=reviews[ri],
                        f=rows[ri].frozen():
                 self._eval_cell(c, k, r, f, inventory,
                                 allow_plan=False, count=False))
                for _idx, ri, i, _mkey in deferred
            ]
            evaled = RenderPool.map_ordered(thunks)
            self._tier_counts["interp"] += len(deferred)
            for (idx, _ri, i, mkey), violations in zip(deferred, evaled):
                resolved[idx] = violations
                if cost_on and violations:
                    attv[i] = attv.get(i, 0) + len(violations)
                if mkey is not None:
                    stores.append((mkey, idx))
        t2 = _time.perf_counter()
        for idx, src in aliases.items():
            resolved[idx] = resolved[src]
            if cost_on and resolved[src]:
                i = cells[idx][1]
                attv[i] = attv.get(i, 0) + len(resolved[src])
        for mkey, idx in stores:
            if len(self._review_memo) >= self.REVIEW_MEMO_MAX:
                self._review_memo.clear()
            self._review_memo[mkey] = resolved[idx]
        for idx, (ri, i) in enumerate(cells):
            kind, _name, constraint = ordered[i]
            review = reviews[ri]
            results = out[ri][0]
            if rflags[idx] and needs_autoreject(
                constraint, review, cached_ns
            ):
                results.append(
                    Result(
                        msg="Namespace is not cached in OPA.",
                        metadata={"details": {}},
                        constraint=constraint,
                        review=review,
                        enforcement_action=self._enforcement_action(constraint),
                    )
                )
            self._append_violation_results(
                results, resolved.get(idx), constraint, kind, review
            )
        t3 = _time.perf_counter()
        n_interp = len(deferred)
        n_plan = len(resolved) - n_interp - memo_hits
        obstrace.record_span(
            "render.plan", t0, t1, stage=obstrace.RENDER, plan="compiled",
            cells=n_plan, memo_hits=memo_hits,
        )
        if n_interp:
            obstrace.record_span(
                "render.interp", t1, t2, stage=obstrace.RENDER,
                plan="interp", cells=n_interp,
            )
        self.last_render_stats = {
            "cells": float(len(resolved)),
            "plan_cells": float(n_plan),
            "interp_cells": float(n_interp),
            "memo_hits": float(memo_hits),
            "plan_ms": (t1 - t0) * 1e3,
            "interp_ms": (t2 - t1) * 1e3,
            "assemble_ms": (t3 - t2) * 1e3,
        }
        if cost_on:
            # one ledger record per pass: per-constraint flagged cells,
            # bound plan tier, violation + memo counts; render seconds
            # apportioned by cells inside the ledger
            entries = []
            for i in np.nonzero(cells_by_i)[0].tolist():
                kind, name, _constraint = ordered[i]
                plan = self._bound_plans.get((kind, name))
                entries.append((
                    kind, name, int(cells_by_i[i]),
                    getattr(plan, "tier", None) or "interp",
                    attv.get(i, 0), attm.get(i, 0),
                ))
            obscosts.record_render(entries, t1 - t0, t2 - t1)
        self._flush_render_counts()
        return out

    def _np_review(self, reviews: List[dict],
                   memo_reviews: Optional[list] = None):
        """Serve an admission batch from the incremental host-side numpy
        constraint side (ops/npside.py): the same over-approximating mask
        + exact render as the device path, with no dispatch RTT and no
        compile anywhere — in particular not during template-ingest
        storms, where the device executable is perpetually behind.
        Returns None when disabled or empty (caller falls back)."""
        if not self.np_serve_enabled:
            return None
        import time as _time

        t_enter = _time.perf_counter()
        with self._lock:
            t_locked = _time.perf_counter()
            ns = self._np_side
            ns.sync(self)
            t_synced = _time.perf_counter()
            got = ns.serve(self, reviews)
            if got is None:
                return None
            t_served = _time.perf_counter()
            obstrace.record_span("np.pack", t_locked, t_synced,
                                 stage=obstrace.PACK)
            obstrace.record_span(
                "np.eval", t_synced, t_served, stage=obstrace.DISPATCH,
                tier="numpy", breaker=self.breaker.state,
            )
            record_stage(PACK_M, t_synced - t_locked, {"path": "review"})
            record_stage(
                DISPATCH_M, t_served - t_synced,
                {"path": "review", "tier": "numpy"},
            )
            if obscosts.enabled():
                obscosts.record_dispatch(
                    self._cost_kind_counts(), t_served - t_synced,
                    len(reviews), path="review",
                )
            ordered, mask, rej = got
            inventory = self._inventory_for_render()
            with obstrace.span("render", stage=obstrace.RENDER,
                               tier="numpy"):
                out = self._render_masked(
                    reviews, ordered, mask, rej, inventory,
                    memo_keys=memo_reviews,
                )
            if (
                len(reviews) <= self.REQUEST_MEMO_BATCH_MAX
                and self._memoable_synced()
            ):
                for ri, review in enumerate(reviews):
                    mk = memo_reviews[ri] if memo_reviews else None
                    self._store_request_memo(
                        review, out[ri][0], mk[1] if mk else None,
                    )
            self.last_review_stats = {
                "lock_wait_ms": (t_locked - t_enter) * 1e3,
                "eval_ms": (_time.perf_counter() - t_locked) * 1e3,
                "path": "np",
            }
            return out

    def _memoable_synced(self) -> bool:
        """Epoch-sync the request-memo bookkeeping, then answer whether
        the CURRENT constraint side is memoable.  Must run under the SAME
        lock hold as the evaluation whose results will be stored: a
        concurrent epoch bump between an earlier sync and the store would
        otherwise let a stale memoable=True verdict bless entries whose
        results depend on mutable state (advisor race)."""
        if self._request_memo_epoch != self._cs_epoch:
            # do NOT clear the memo: stale entries repair incrementally
            self._request_memo_ok = None
            self._request_memo_epoch = self._cs_epoch
        return self._request_memoable()

    def _store_request_memo(self, review: dict, results: List[Result],
                            memo_review=None):
        """Store one review's exact results as a request-memo entry
        (caller holds the lock and has verified memoability via
        _memoable_synced).  The flat replay list is sorted by
        (kind, name) so replays order identically whichever evaluation
        path populated or repaired the entry.  memo_review: the frozen
        uid-stripped key when a caller already computed it."""
        from ..engine.value import freeze

        if len(self._request_memo) >= self.REQUEST_MEMO_MAX:
            self._request_memo.clear()
        if memo_review is None:
            memo_review = _strip_request_meta(freeze(review))
        per_key: Dict[Tuple[str, str], list] = {}
        for r in results:
            key = (r.constraint.get("kind", ""),
                   (r.constraint.get("metadata") or {}).get("name", ""))
            entry = (r.msg,
                     copy.deepcopy((r.metadata or {}).get("details", {})),
                     r.enforcement_action)
            per_key.setdefault(key, []).append(entry)
        flat = [
            (kind, name, entry)
            for kind, name in sorted(per_key)
            for entry in per_key[(kind, name)]
        ]
        self._request_memo[memo_review] = (self._cs_epoch, per_key, flat)

    def _review_batch_traced(self, reviews, ordered, mask_np, rej_np, inventory):
        """Dense per-cell walk kept for tracing runs: trace lines must name
        every constraint in order, including non-matching ones."""
        from ..engine.value import freeze

        from .renderplan import RowView

        out = []
        for ri, review in enumerate(reviews):
            frozen_review = freeze(review)
            memo_review = _strip_request_meta(frozen_review)
            rowview = RowView(review, frozen_review)
            results: List[Result] = []
            trace: List[str] = []
            for i, (kind, name, constraint) in enumerate(ordered):
                if rej_np[i, ri]:
                    if needs_autoreject(constraint, review, self.store.cached_namespace):
                        results.append(
                            Result(
                                msg="Namespace is not cached in OPA.",
                                metadata={"details": {}},
                                constraint=constraint,
                                review=review,
                                enforcement_action=self._enforcement_action(constraint),
                            )
                        )
                        trace.append(f"autoreject {kind}/{name}")
                if mask_np[i, ri]:
                    self._render_cell(
                        results, constraint, kind, review, frozen_review,
                        inventory, trace, memo_review=memo_review,
                        rowview=rowview,
                    )
            out.append((results, "\n".join(trace)))
        self._flush_render_counts()
        return out

    # Fetched candidate indices per constraint for the capped audit: at
    # least this many, and at least 2x the cap (oversampling absorbs device
    # over-approximation without a fallback row fetch).  Power-of-two so the
    # fused executable's output shape stays stable across cap settings.
    AUDIT_TOPK_MIN = 32

    def _audit_topk(self, cap: int) -> int:
        k = self.AUDIT_TOPK_MIN
        while k < 2 * cap:
            k *= 2
        return min(k, 4096)

    def _fused_audit_fn(self, K: int):
        """The capped-audit fused function: the full evaluation step PLUS
        the per-constraint reduction on-device — violation-candidate counts
        and the first K candidate row indices, packed into one [C, 1+K]
        int32 array.  ONLY that small array is an output: the [C, R] mask
        stays an XLA-internal intermediate, because a relay-attached device
        charges large co-OUTPUTS against the small fetch (~30MB/s measured
        — r3's 2.8s full-resweep regression).  The mask the delta path and
        the uncapped audit need is a separate lazy dispatch of the plain
        fused fn over the same committed device buffers (MaskSource).  This
        is what keeps the 500x100k sweep's device->host traffic under the
        BASELINE <1s budget behind a network relay (reference cap contract:
        pkg/audit/manager.go:49)."""
        fused, side = self._fused_fn()
        if (
            self._fused_audit is not None
            and self._fused_audit_key == (self._fused_gen, K)
        ):
            return self._fused_audit, side
        body, has_joins = self._eval_body(side, join_mode="trace")
        if has_joins:
            # join-bearing corpora take a trailing `joins` runtime arg
            # (kind ids) and compute the per-key aggregate tables
            # in-trace (ops/joinkernel.py)
            def fused_audit(rv, cs, cols, gp, joins):
                mask, _autoreject = body(rv, cs, cols, gp, joins)
                return _packed_reduction(mask, K)
        else:
            raw = fused.__wrapped__

            def fused_audit(rv, cs, cols, gp):
                mask, _autoreject = raw(rv, cs, cols, gp)
                return _packed_reduction(mask, K)

        from .aotcache import aot_jit

        self._fused_audit = aot_jit(
            fused_audit, "fused-audit", (self._fused_key, K)
        )
        self._fused_audit_key = (self._fused_gen, K)
        return self._fused_audit, side

    def _fused_audit_mesh_fn(self, K: int, mesh=None):
        """Two-output (mask, per-shard packed) capped-audit variant for
        the mesh path, built with shard_map: each shard evaluates ONLY
        its row slab and reduces it locally to [C, 1+K] (counts + first-K
        candidates translated to GLOBAL row indices); the host merges the
        N small per-shard reductions (_merge_sharded_packed).  Letting
        GSPMD partition the naive jit instead all-gathers the mask for
        the order-dependent top-k — every device then re-reduces the FULL
        row axis, which measured as ~8x single-device time on an
        8-virtual-device mesh (r4 verdict weak #5).  The mask output
        stays device-resident and row-sharded."""
        from jax.sharding import PartitionSpec as _P

        fused, side = self._fused_fn()
        key_now = self._fused_audit_mesh_key
        if (
            self._fused_audit_mesh is not None
            and key_now is not None
            and key_now[0] == self._fused_gen
            and key_now[1] == K
            and key_now[2] is mesh  # identity-is-liveness, not id()
        ):
            return self._fused_audit_mesh
        # join-bearing corpora evaluate in 'trace' mode with the mesh
        # axis named: each shard segment-reduces its own row slab to a
        # compact per-key table and an all_gather merges them — the
        # [C, 1+K]-reduce-then-merge idiom applied to join groups, so a
        # key spanning shards counts once per provider row at any width
        eval_body, has_joins = self._eval_body(
            side, join_mode="trace", axis_name="data"
        )
        raw = fused.__wrapped__

        def body(rv, cs, cols, gp, joins=None):
            if has_joins:
                mask, _autoreject = eval_body(rv, cs, cols, gp, joins)
            else:
                mask, _autoreject = raw(rv, cs, cols, gp)
            packed = _packed_reduction(mask, K)
            shard = jax.lax.axis_index("data")
            idx = packed[:, 1:]
            idx = jnp.where(idx >= 0, idx + shard * mask.shape[1], -1)
            packed = jnp.concatenate([packed[:, :1], idx], axis=1)
            return mask, packed[None]  # leading shard axis for out_specs

        sharded = [None]  # built on first call: specs follow arg trees

        def _build(rv, cs, cols, gp, joins=None):
            def row_spec(a):
                return _P("data", *([None] * (a.ndim - 1)))

            repl = _P()
            in_specs = (
                jax.tree_util.tree_map(row_spec, rv),
                jax.tree_util.tree_map(lambda a: repl, cs),
                jax.tree_util.tree_map(row_spec, cols),
                jax.tree_util.tree_map(lambda a: repl, gp),
            )
            if has_joins:
                in_specs = in_specs + (
                    jax.tree_util.tree_map(lambda a: repl, joins),
                )
            out_specs = (_P(None, "data"), _P("data", None, None))
            from ..util.jaxcompat import shard_map as _shard_map

            if has_joins:
                inner = body
            else:
                def inner(rv, cs, cols, gp):
                    return body(rv, cs, cols, gp)
            sharded[0] = jax.jit(_shard_map(
                inner, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs, check_vma=False,
            ))

        def fused_audit_mesh(rv, cs, cols, gp, joins=None):
            if sharded[0] is None:
                _build(rv, cs, cols, gp, joins)
            if has_joins:
                return sharded[0](rv, cs, cols, gp, joins)
            return sharded[0](rv, cs, cols, gp)

        self._fused_audit_mesh = fused_audit_mesh
        self._fused_audit_mesh_key = (self._fused_gen, K, mesh)
        return self._fused_audit_mesh

    def _audit_inputs(self, K: int):
        """Sync the resident incremental audit pack (ops/auditpack.py) and
        return the current fused audit fn + constraint side aligned with
        it."""
        fn, side = self._fused_audit_fn(K)
        self._audit_pack.sync(self, side[3])
        if self.interner.snapshot_size() > self._cs_cache[0][1]:
            # row packing interned new strings; constraint-side string
            # predicate tables are vocab-sized, so re-pack them
            fn, side = self._fused_audit_fn(K)
        ordered, cp, groups, _col_specs, crow = side
        group_params = [packed for *_s, packed in groups]
        return fn, ordered, cp, group_params, crow

    # Scatter width buckets: one executable covers every dirty count up to
    # 256 (then powers of 4).  A per-power-of-two bucket recompiles the
    # many-leaf scatter (~3-5s XLA) on the first full sweep after each new
    # churn magnitude — measured as the dominant cost of r3's warm full
    # resweep.  The wider bucket trades a few hundred KB of inline row
    # upload (rare: full sweeps only) for compile stability.
    SCATTER_WIDTH_MIN = 256

    def _scatter_width(self, n: int) -> int:
        width = self.SCATTER_WIDTH_MIN
        while width < n:
            width *= 4
        return width

    def _warm_scatter(self, placed):
        """Compile+dispatch the width-SCATTER_WIDTH_MIN scatter in the
        background right after a full upload (result discarded; writes row
        0's own values).  The first timed full resweep then finds the
        executable warm instead of paying its XLA compile."""
        ap = self._audit_pack
        if ap.capacity == 0:
            return
        rows = np.zeros(self.SCATTER_WIDTH_MIN, np.int32)
        host_rows = jax.tree_util.tree_map(
            lambda a: a[rows], (ap.rp, ap.cols)
        )

        def warm():
            try:
                _scatter_rows(placed, rows, host_rows)
            except Exception:  # pragma: no cover - warm-up is best-effort
                log.debug("scatter warm-up failed; first churn patch "
                          "pays the compile instead", exc_info=True)

        from .deltasweep import spawn_bg

        spawn_bg("gk-scatter-warm", warm)

    def _audit_device_inputs(self):
        """Device-resident review-side audit arrays (single-device path).
        Full upload when the pack layout changed (rebuild, growth, new
        leaf); otherwise ONE jitted scatter patches just the dirty rows, so
        a steady-state sweep's host->device traffic is proportional to the
        number of changed objects, not the inventory size."""
        ap = self._audit_pack
        dirty = ap.take_dirty()
        if dirty:
            # the dirty set is consumed HERE; the mesh twin can no longer
            # patch itself and must re-place on its next use
            self._audit_dev_mesh = None
        cache = self._audit_dev
        if cache is None or cache[0] != ap.layout_gen:
            tree = (ap.rp, ap.cols)
            if jax.default_backend() == "cpu":
                # CPU jax.device_put may be ZERO-COPY: the "device"
                # buffers then alias these numpy arrays, and later
                # in-place row packs would silently mutate the captured
                # base state the lazy mask dispatch reads (observed as a
                # per-allocation-alignment-dependent delta under-count).
                # Real devices always copy across the transfer.
                tree = jax.tree_util.tree_map(np.array, tree)
            placed = jax.device_put(tree)
            self._audit_dev = [ap.layout_gen, placed]
            from ..obs import compilestats

            compilestats.record_device_bytes(
                "audit_pack", compilestats.tree_nbytes(tree),
                rows=int(ap.capacity),
            )
            self._warm_scatter(placed)
            return placed
        if dirty:
            rows = np.fromiter(sorted(dirty), np.int32, len(dirty))
            # bucket the scatter width (repeat the last row; duplicate
            # indices write identical values) so the jitted updater does
            # not recompile per distinct dirty count
            width = self._scatter_width(len(rows))
            rows = np.pad(rows, (0, width - len(rows)), mode="edge")
            host_rows = jax.tree_util.tree_map(
                lambda a: a[rows], (ap.rp, ap.cols)
            )
            placed = _scatter_rows(cache[1], rows, host_rows)
            self._audit_dev = [ap.layout_gen, placed]
        return self._audit_dev[1]

    def _record_shard(self, path: str):
        """Per-shard pipeline telemetry hook for pipelined_shard_commit:
        a pack + dispatch span per shard (they overlap by design — the
        packer thread works one slab ahead of the transfers) and the
        audit_shard_* stage histograms."""
        from ..metrics.catalog import record_audit_shard

        def record(shard, rows, pt0, pt1, ct0, ct1):
            # NOT stage-tagged: stage_breakdown's contract is disjoint
            # stage spans summing toward the root duration, and these are
            # sub-intervals of the enclosing pack/dispatch stages (they
            # also overlap each other by design — the pipeline packs
            # shard i+1 while shard i's transfer is in flight)
            obstrace.record_span(
                "audit.shard_pack", pt0, pt1,
                shard=int(shard), rows=int(rows), path=path,
            )
            obstrace.record_span(
                "audit.shard_dispatch", ct0, ct1,
                shard=int(shard), rows=int(rows), path=path,
            )
            record_audit_shard(int(rows), pt1 - pt0, ct1 - ct0, path=path)

        return record

    def _audit_device_inputs_mesh(self, mesh):
        """Shard-resident review-side audit arrays (mesh path): the
        padded, row-sharded placement is committed once per pack layout —
        slab by slab through the double-buffered pipeline (packing shard
        i+1 overlaps the transfer of shard i, parallel/mesh.py) — and
        steady-state sweeps patch just the dirty rows with one jitted
        scatter (donating the dead pre-scatter placement), so host->device
        traffic is proportional to churn on every topology."""
        from ..parallel.mesh import shard_review_side

        ap = self._audit_pack
        dirty = ap.take_dirty()
        if dirty:
            # consumed here; the single-device twin must re-place next use
            self._audit_dev = None
        cache = self._audit_dev_mesh
        if cache is None or cache[0] != ap.layout_gen or cache[1] is not mesh:
            tree = (ap.rp, ap.cols)
            if jax.default_backend() == "cpu":
                # CPU device_put may be zero-copy (see the single-device
                # path): copy so later in-place row packs cannot mutate
                # the committed base state
                tree = jax.tree_util.tree_map(np.array, tree)
            rv_p, cols_p, _target = shard_review_side(
                mesh, ap.capacity, tree[0], tree[1],
                record_shard=self._record_shard("audit"),
            )
            # the mesh OBJECT rides in the cache: identity-is-liveness (a
            # recycled id() could alias a dead mesh, advisor r5)
            self._audit_dev_mesh = [ap.layout_gen, mesh, (rv_p, cols_p)]
            from ..obs import compilestats

            width = int(mesh.devices.size)
            total = compilestats.tree_nbytes(tree)
            compilestats.record_device_bytes(
                "audit_pack_mesh", total, shards=width,
                per_shard_bytes=total // max(width, 1),
                rows=int(ap.capacity),
            )
            return rv_p, cols_p
        if dirty:
            rows = np.fromiter(sorted(dirty), np.int32, len(dirty))
            width = self._scatter_width(len(rows))
            rows = np.pad(rows, (0, width - len(rows)), mode="edge")
            host_rows = jax.tree_util.tree_map(
                lambda a: a[rows], (ap.rp, ap.cols)
            )
            from ..parallel.mesh import DISPATCH_LOCK

            # the pre-scatter placement is donated (dead after the swap);
            # drop the cache first so a failed dispatch cannot leave a
            # consumed tree serving the next sweep
            self._audit_dev_mesh = None
            with DISPATCH_LOCK, mesh:
                placed = _scatter_rows_mesh(cache[2], rows, host_rows)
            self._audit_dev_mesh = [ap.layout_gen, mesh, placed]
        return self._audit_dev_mesh[2]

    def _audit_sweep(self, K: int, reuse_any_k: bool = False):
        """One device sweep over the resident audit pack ->
        (reviews, ordered, mask_src MaskSource for the device-resident
        [C, R'] mask, counts [C] int64, topk [C, K] int32 with -1 padding),
        or None when the inventory is empty.  Cached on (store epoch,
        constraint epoch, K): the device is dispatched only when the
        inventory or the constraint side actually changed.  reuse_any_k
        accepts a cached sweep of any K (the uncapped path only needs the
        mask)."""
        from .deltasweep import DeltaState, MaskSource

        key = (self.store.epoch, self._cs_epoch, K)
        if self._audit_cache is not None:
            ckey = self._audit_cache[0]
            if ckey == key or (reuse_any_k and ckey[:2] == key[:2]):
                self.last_sweep_stats = {
                    "pack_ms": 0.0, "device_ms": 0.0, "fetch_ms": 0.0,
                    "fetch_bytes": 0.0, "cached": 1.0,
                }
                return self._audit_cache[1]
        import time as _time

        if faults.ENABLED:
            faults.fire(faults.TPU_DISPATCH, path="audit")
        t0 = _time.perf_counter()
        fn, ordered, cp, group_params, crow = self._audit_inputs(K)
        ap = self._audit_pack
        if ap.n_rows == 0:
            return None
        # referential policies: bring the host join-group index current
        # (diff-bumps reader row generations for changed key groups) and
        # build the trace-mode runtime args the join-bearing executables
        # take (ops/joinkernel.py)
        self._ensure_join_state()
        jargs = self._join_trace_args()
        mesh = self._mesh()
        t1 = _time.perf_counter()
        if mesh is None:
            rv_d, cols_d = self._audit_device_inputs()
            cs_d, gp_d = self._constraint_device_side(
                cp.arrays, group_params, None, None
            )
            if jargs is None:
                packed_dev = fn(rv_d, cs_d, cols_d, gp_d)
                # lazy: the [C, R] mask is its own (never-fetched)
                # dispatch against the SAME committed buffers, issued
                # only when the delta path or the uncapped audit first
                # needs it — keeping it out of the capped fetch avoids
                # the relay's big-co-output transfer charge (the r3
                # full-resweep regression)
                fused = self._fused  # this epoch's compiled plain fused fn
                mask_src = MaskSource(
                    lambda: fused(rv_d, cs_d, cols_d, gp_d)[0]
                )
            else:
                packed_dev = fn(rv_d, cs_d, cols_d, gp_d, jargs)
                # the mask dispatch must be AUDIT-mode too: the review
                # fused fn resolves JoinCmp to unknown and would corrupt
                # the delta fold's before-columns
                mask_fn = self._fused_mask_fn()
                mask_src = MaskSource(
                    lambda: mask_fn(rv_d, cs_d, cols_d, gp_d, jargs)
                )
            # background-resolve the mask, then warm the width-8 delta
            # executable against it: both trace/compiles happen off the
            # sweep path, so neither this sweep's fetch nor the first
            # delta sweep pays them (delta falls back to a full sweep
            # while this runs — peek/BUSY in _try_delta)
            self._warm_delta_async(mask_src, cs_d, gp_d)
        else:
            # mesh path: ONE two-output dispatch (mask stays device-
            # resident, only packed is fetched) over SHARD-RESIDENT audit
            # inputs: like the single-device path, the padded+sharded
            # review side is committed once per pack layout and patched
            # by a jitted scatter of just the dirty rows — re-placing the
            # full row pack across N shards every sweep was the measured
            # ~4x sharded-path overhead (r4 verdict weak #5)
            rv_p, cols_p = self._audit_device_inputs_mesh(mesh)
            cs_p, gp_p = self._constraint_device_side(
                cp.arrays, group_params, None, mesh
            )
            fn_mesh = self._fused_audit_mesh_fn(K, mesh)
            if jargs is None:
                mask_dev, packed_dev = self._guarded_mesh_dispatch(
                    mesh, lambda: fn_mesh(rv_p, cs_p, cols_p, gp_p)
                )
            else:
                from ..parallel.mesh import replicate_tree

                j_p = replicate_tree(mesh, jargs)
                mask_dev, packed_dev = self._guarded_mesh_dispatch(
                    mesh, lambda: fn_mesh(rv_p, cs_p, cols_p, gp_p, j_p)
                )
            mask_src = MaskSource.resolved(mask_dev)
            # warm the mesh-specialized delta executable off the sweep
            # path (the mask is already resolved; only the trace/compile
            # rides the background thread) so the first O(churn) delta
            # sweep under the mesh pays a dispatch, not an SPMD compile
            self._warm_delta_async(mask_src, cs_p, gp_p, mesh)
        packed_dev.block_until_ready()
        t2 = _time.perf_counter()
        # the ONE small fetch per sweep; crow folds the group-major pad
        # rows out so all host-side state is per ordered constraint
        if mesh is None:
            packed = np.asarray(packed_dev)[crow]
        else:
            # merge to the SAME width K the single-device reduction
            # produces (per-shard lists may be narrower when a shard's
            # row slab is smaller than K)
            packed = _merge_sharded_packed(np.asarray(packed_dev), K)[crow]
        t3 = _time.perf_counter()
        counts = packed[:, 0].astype(np.int64)
        sweep = (ap.reviews, ordered, mask_src, counts, packed[:, 1:])
        # re-read the epochs: packing may have interned new strings and
        # bumped the constraint-side cache, but the INPUTS are these epochs'
        self._audit_cache = (key, sweep, None)
        # a full sweep (re)bases the incremental state: its inputs include
        # every dirty row the scatter just applied
        # the mesh_width stamp pins the basis to the sweep sharding that
        # produced it: _try_delta refuses a drifted basis, so even code
        # that pokes mesh_enabled directly (instead of set_mesh, which
        # clears the state) rebases via a full sweep rather than
        # dispatching across topologies.
        self._delta_state = DeltaState(
            counts, packed[:, 1:], K, mask_src,
            cs_epoch=self._cs_epoch, layout_gen=ap.layout_gen,
            store_epoch=self.store.epoch, crow=crow,
            mesh_width=1 if mesh is None else int(mesh.devices.size),
        )
        # the full sweep's inputs already reflect every pending change;
        # drop the delta channel so those rows aren't re-applied
        ap.delta_dirty.clear()
        self.last_sweep_stats = {
            "pack_ms": (t1 - t0) * 1e3,
            "device_ms": (t2 - t1) * 1e3,
            "fetch_ms": (t3 - t2) * 1e3,
            "fetch_bytes": float(packed.nbytes),
            "rows": float(ap.n_rows),
            "cells": float(len(ordered) * ap.n_rows),
            "shards": 1.0 if mesh is None else float(mesh.devices.size),
        }
        from ..parallel.mesh import slab_rows

        # capacity-slab based at EVERY width (width 1 included) so the
        # bench scaling curve compares like with like across widths
        self.last_sweep_stats["rows_per_shard"] = float(
            slab_rows(
                ap.capacity, 1 if mesh is None else int(mesh.devices.size)
            )[1]
        )
        if jargs is not None:
            # a join-bearing sweep is its own routing event: without the
            # explicit reason the dispatch would read as an ordinary
            # row-local device sweep in route_decisions_total/routez
            self.last_sweep_stats["join_plans"] = float(len(jargs))
            # tier "device" (the documented taxonomy), flip-exempt: an
            # audit-class dispatch interleaved with np/interp review
            # traffic is not a serving-tier change
            self.route_ledger.record(
                "device", "join_plan", cells=len(ordered) * ap.n_rows,
                n_reviews=int(ap.n_rows), lam=None, track_flips=False,
            )
        obstrace.record_span("audit.pack", t0, t1, stage=obstrace.PACK,
                             rows=ap.n_rows)
        obstrace.record_span(
            "audit.dispatch", t1, t2, stage=obstrace.DISPATCH,
            tier="tpu", breaker=self.breaker.state,
            shards=(1 if mesh is None else int(mesh.devices.size)),
        )
        obstrace.record_span("audit.fetch", t2, t3, stage=obstrace.FETCH,
                             fetch_bytes=float(packed.nbytes))
        record_stage(PACK_M, t1 - t0, {"path": "audit"})
        record_stage(DISPATCH_M, t2 - t1, {"path": "audit", "tier": "tpu"})
        if obscosts.enabled():
            obscosts.record_dispatch(
                self._cost_kind_counts(), t2 - t1, int(ap.n_rows),
                path="audit",
            )
        return sweep

    def _audit_masks(self):
        """Full host candidate mask for the uncapped audit path.

        Steady state is incremental like the capped path: the base mask is
        fetched ONCE per full sweep, and subsequent audits overwrite just
        the columns of rows the delta sweep re-evaluated (absolute values,
        so reapplying is idempotent) — no full-mask transfer and no full
        device re-execution per store change."""
        got = self._try_delta(self.AUDIT_TOPK_MIN)
        if got is not None:
            reviews, ordered, st = got
            ap = self._audit_pack
            if st.host_mask is None:
                # capacity cannot have changed while the state is valid
                # (a capacity change bumps layout_gen, invalidating it);
                # copy: np.asarray of a jax array is a read-only view
                st.host_mask = np.asarray(
                    st.mask_src.get()
                )[st.crow][:, : ap.capacity]
                st.pending_mask_rows = set(st.row_cols)
            for r in st.pending_mask_rows:
                st.host_mask[:, r] = st.row_cols[r][: st.host_mask.shape[0]]
            st.pending_mask_rows = set()
            return reviews, ordered, st.host_mask
        sweep = self._audit_sweep(self.AUDIT_TOPK_MIN, reuse_any_k=True)
        if sweep is None:
            return [], [], None
        reviews, ordered, mask_src, _counts, _topk = sweep
        key, cached_sweep, host = self._audit_cache
        if host is None:
            st0 = self._delta_state
            crow0 = st0.crow if st0 is not None and st0.mask_src is mask_src \
                else self._constraint_side()[4]
            host = np.asarray(
                mask_src.get()
            )[crow0][:, : self._audit_pack.capacity]
            self._audit_cache = (key, cached_sweep, host)
        # a full sweep just rebased the incremental state; seed its host
        # mask from this fetch so the next delta-path audit doesn't
        # transfer the identical [C, R] mask a second time
        st = self._delta_state
        if (
            st is not None
            and st.host_mask is None
            and st.mask_src is mask_src
        ):
            st.host_mask = host.copy()
            st.pending_mask_rows = set(st.row_cols)
        return reviews, ordered, host

    def audit(self, tracing: bool = False):
        if not self.breaker.allow():
            # breaker open: the inherited interpreter sweep is slower but
            # always answers — the audit loop must not die with the device
            return InterpDriver.audit(self, tracing=tracing)
        self.last_sweep_stats = {}  # stale stats must not decide `cached`
        try:
            out = self._audit_device(tracing)
        except Exception as e:
            from .joinkernel import JoinDivergence

            if isinstance(e, JoinDivergence):
                # the armed (GK_JOIN_ASSERT) join-parity assertion is a
                # diagnostic, not a device failure: serving the interp
                # fallback here would hide exactly the divergence the
                # caller armed the flag to catch
                raise
            self._record_device_failure(e)
            log.warning(
                "device audit failed (%s: %s); serving from the "
                "interpreter tier", type(e).__name__, e,
            )
            return InterpDriver.audit(self, tracing=tracing)
        # only a sweep that actually dispatched resets the breaker's
        # failure streak: a cache-served sweep (cached=1.0) or an
        # empty-inventory sweep (stats left empty — cleared before the
        # call) never contacted the device, and in a quiet cluster either
        # would otherwise keep a failing device's breaker from tripping
        # while admission traffic pays failed dispatches
        stats = self.last_sweep_stats
        if stats and not stats.get("cached"):
            self.breaker.record_success()
        return out

    def _audit_device(self, tracing: bool = False):
        from .renderplan import RowView

        # audit is the throughput path: prefer waiting for the background
        # compile (which holds the driver lock only for host packing) over
        # an interpreter sweep of the whole inventory (advisor r2)
        self._wait_ready_for_audit()
        with self._lock:
            # gklint: disable=blocking-under-lock -- the audit sweep is
            # the exclusive device owner by design: the driver lock holds
            # for the [C,R] dispatch+fetch so admissions route to the
            # np/interp tier instead of interleaving device work; a
            # wedged dispatch is bounded by the mesh watchdog
            reviews, ordered, mask = self._audit_masks()
            if not reviews:
                return [], ("" if tracing else None)
            inventory = self._inventory_for_render()
            results: List[Result] = []
            trace: List[str] = [] if tracing else None
            # grouped join renders (docs/referential.md): per join-safe
            # kind, ONE pruned inventory over the union of its flagged
            # rows — the interpreter's per-cell O(R) inventory walk
            # becomes O(group); built lazily on the kind's first cell
            kind_cis: Dict[str, list] = {}
            for i, (k, _n, _c) in enumerate(ordered):
                kind_cis.setdefault(k, []).append(i)
            join_inv: Dict[str, object] = {}

            def _inv_for(kind):
                got = join_inv.get(kind)
                if got is None:
                    got = inventory
                    if self._join_safe(kind):
                        rows = np.nonzero(
                            mask[kind_cis[kind]].any(axis=0)
                        )[0]
                        pruned = self._join_render_inventory(kind, rows)
                        if pruned is not None:
                            got = pruned
                    join_inv[kind] = got
                return got

            # resource-major order, matching InterpDriver.audit; only
            # reviews with a positive cell pay any render cost (plan
            # cells skip even the freeze — the RowView freezes lazily,
            # only when a cell falls back to the interpreter or memo)
            hot_reviews = np.nonzero(mask.any(axis=0))[0]
            for ri in hot_reviews:
                review = reviews[ri] if ri < len(reviews) else None
                if review is None:  # tombstoned row (valid=False anyway)
                    continue
                rowview = RowView(review)
                for i in np.nonzero(mask[:, ri])[0]:
                    kind, name, constraint = ordered[i]
                    violations = self._cell_violations(
                        constraint, kind, review, None, _inv_for(kind),
                        rowview=rowview,
                    )
                    if not violations and self._join_strict(
                        kind, constraint
                    ):
                        self._note_join_false_positive(kind, name, int(ri))
                    self._append_violation_results(
                        results, violations, constraint, kind, review,
                        trace,
                    )
            self._flush_render_counts()
            return results, ("\n".join(trace) if tracing else None)

    # render-memo bound + eviction chunk: at the cap, the OLDEST 1/16 of
    # entries (dict insertion order) are deleted instead of a wholesale
    # clear() — the clear was a guaranteed latency cliff (one sweep
    # suddenly re-rendering 2M cells) exactly on the largest clusters.
    # Segmented FIFO, not LRU: hits don't reorder, so eviction is by
    # insertion age; epoch invalidation (below) is unchanged.
    RENDER_MEMO_MAX = 2_000_000

    def _evict_render_memo(self):
        from itertools import islice

        drop = max(1, self.RENDER_MEMO_MAX // 16)
        for k in list(islice(iter(self._render_memo), drop)):
            del self._render_memo[k]

    def _memo_cell(
        self, kind, name, ri, constraint, review, rowviews, inventory,
        uses_inv, row_gen,
    ) -> list:
        """Violations for one cell, memoized across sweeps: an unchanged
        (constraint side, packed row) pair renders identically unless the
        template reads data.inventory (then any store write invalidates)."""
        mkey = (kind, name, ri)
        if not uses_inv:
            hit = self._render_memo.get(mkey)
            if hit is not None and hit[0] == row_gen:
                return hit[1]
        if callable(inventory):
            # lazy grouped join inventory (_lazy_join_inventory):
            # resolved only on this miss path, never on a memo hit
            inventory = inventory()
        row = rowviews.get(ri)
        if row is None:
            from .renderplan import RowView

            row = RowView(review)
            rowviews[ri] = row
        violations = self._eval_cell(
            constraint, kind, review, None, inventory, rowview=row
        )
        if not uses_inv:
            if len(self._render_memo) >= self.RENDER_MEMO_MAX:
                self._evict_render_memo()
            self._render_memo[mkey] = (row_gen, violations)
        return violations

    def _count_exact(self, kind: str, constraint: dict) -> bool:
        """True when the device-counted violating resources provably equal
        the reference's totalViolations for this constraint: the vectorized
        program is exact with a single non-iterating clause (so a violating
        resource yields exactly one violation), and the match spec uses no
        label selectors (the packed match can only over-approximate through
        non-string labels, ops/pack.py:7-10)."""
        prog = self.programs.get(kind)
        if prog is None or not prog.exact:
            return False
        if getattr(prog, "join_plans", ()):
            # the distinct-provider-row aggregate can over-approximate in
            # one documented corner (same kind/ns/name under two
            # groupVersions, docs/referential.md) — never report its
            # device count as the reference-exact total past the cap
            return False
        if len(prog.clauses) != 1 or prog.clauses[0].slot_iter is not None:
            return False
        match = constraint_match_spec(constraint)
        return not match.get("labelSelector") and not match.get(
            "namespaceSelector"
        )

    # dirty rows per steady-state sweep beyond which a full device sweep
    # is cheaper than the delta evaluation + host merge
    DELTA_MAX_ROWS = 256
    # cumulative rows tracked since the last full sweep beyond which the
    # state is rebased (bounds row_cols host memory at ~ROWS_MAX x C bytes)
    DELTA_ROW_COLS_MAX = 8192
    # how long a delta sweep waits for the background base-mask resolution
    # before falling back to a full sweep.  This wait happens UNDER the
    # driver lock (admission reviews queue behind it), so production keeps
    # it near zero — a sub-second full sweep beats any stall; the test
    # conftest raises it for CPU-backend determinism.
    DELTA_MASK_WAIT_S = 0.05

    def _delta_dispatch_fn(self, mesh):
        """The delta executable for this topology: the AOT wrapper on a
        single device; its plain jit twin under a mesh (serialized
        executables pin a single-device layout — the sharded base mask
        must go through the jit machinery's SPMD compile)."""
        from .aotcache import aot_jit

        dfn = self._delta_fn()
        if mesh is not None and isinstance(dfn, aot_jit):
            return dfn._jitted
        return dfn

    def _warm_delta_async(self, mask_src, cs_d, gp_d, mesh=None):
        """Resolve the base mask, then compile+dispatch the width-8 delta
        executable against it, on the MaskSource's resolver thread.  All
        state it needs is captured here under the driver lock; the thread
        itself only calls thread-safe jax entry points.  On the mesh path
        the mask is already resolved — the prefetch then only warms the
        mesh-specialized delta executable off the sweep path."""
        ap = self._audit_pack
        if not self.delta_enabled or ap.n_rows == 0:
            # no delta path will consume the mask: leave it lazy (the
            # uncapped audit resolves it on demand) instead of paying a
            # background full evaluation nobody may read
            return
        delta_jit = self._delta_dispatch_fn(mesh)  # cached per epoch
        rows_pad = np.zeros(8, np.int32)
        rv_slice = {k: a[rows_pad] for k, a in ap.rp.items()}
        cols_slice = {
            ck: {leaf: a[rows_pad] for leaf, a in leaves.items()}
            for ck, leaves in ap.cols.items()
        }
        jt = self._join_delta_tables()
        jtail = (jt,) if jt is not None else ()
        if mesh is not None:
            from ..parallel.mesh import DISPATCH_LOCK

            def _warm(m):
                # collective-bearing executable dispatched off-thread:
                # take the mesh dispatch lock AND drain the result before
                # releasing it, so the warm's psums can never interleave
                # with a foreground sweep's on any device.  The first warm
                # per (epoch, topology) holds the lock across the SPMD
                # trace+compile too — jit's call cache cannot be populated
                # from a lock-free lower().compile() (measured: the next
                # call still recompiles) — a bounded one-time stall the
                # foreground delta sweep would otherwise pay itself.
                with DISPATCH_LOCK:
                    # gklint: disable=blocking-under-lock -- PR 6 design:
                    # the background warm must drain INSIDE the gate so
                    # its collective launch order can never interleave
                    # with a foreground sweep (the AllReduce rendezvous
                    # deadlock this gate exists to prevent); the stall is
                    # one bounded cold compile
                    delta_jit(
                        m, rows_pad, rv_slice, cs_d, cols_slice, gp_d,
                        *jtail
                    ).block_until_ready()
        else:
            def _warm(m):
                delta_jit(m, rows_pad, rv_slice, cs_d, cols_slice, gp_d,
                          *jtail)

        mask_src.prefetch(after=_warm)

    def _delta_fn(self):
        """Jitted fused evaluation restricted to a [d]-row slice of the
        audit pack, plus the gather of the same rows' BEFORE-columns from
        the resident full-sweep mask, in ONE dispatch ->
        [C, 2d] (old | new) int8.  Same traced computation as the full
        sweep, tiny intermediates, one round trip."""
        fused, side = self._fused_fn()
        if self._delta_jit is not None and self._delta_jit_key == self._fused_gen:
            return self._delta_jit
        body, has_joins = self._eval_body(side, join_mode="tables")
        if has_joins:
            # a churn-slice dispatch cannot derive the global join
            # aggregate from its own rows: the host join index supplies
            # the per-key tables as a trailing runtime argument
            def delta(mask_dev, idx, rv, cs, cols, gp, joins):
                new = body(rv, cs, cols, gp, joins)[0]
                old = mask_dev[:, idx]
                return jnp.concatenate(
                    [old.astype(jnp.int8), new.astype(jnp.int8)], axis=1
                )
        else:
            raw = fused.__wrapped__

            def delta(mask_dev, idx, rv, cs, cols, gp):
                new = raw(rv, cs, cols, gp)[0]
                old = mask_dev[:, idx]
                return jnp.concatenate(
                    [old.astype(jnp.int8), new.astype(jnp.int8)], axis=1
                )

        from .aotcache import aot_jit

        self._delta_jit = aot_jit(delta, "delta", self._fused_key)
        self._delta_jit_key = self._fused_gen
        return self._delta_jit

    def _try_delta(self, K: int):
        """Bring the incremental sweep state current with an O(dirty-rows)
        device evaluation (ops/deltasweep.py).  Returns
        (reviews, ordered, state) or None when the delta path is
        ineligible (disabled, no base state, layout changed, or too many
        dirty rows — then the caller runs a full sweep).  Runs under the
        mesh too: the [C, d] dirty-row evaluation is dispatched against
        the shard-resident base mask, so steady-state cost stays O(churn)
        on every topology and only the owning shards' slabs see traffic."""
        if not self.delta_enabled:
            return None
        st = self._delta_state
        if st is None or st.cs_epoch != self._cs_epoch:
            return None
        if st.mesh_width != self.mesh_layout():
            # the basis was produced under a different sweep sharding
            # (someone poked mesh_enabled/_mesh_cache directly instead of
            # set_mesh): its mask placement belongs to the old topology —
            # dispatching against it raises, so rebase via a full sweep.
            # The sweep cache rides the same topology and must go too, or
            # _audit_sweep would serve it without recreating the state.
            self._delta_state = None
            self._audit_cache = None
            return None
        import time as _time

        t0 = _time.perf_counter()
        side = self._constraint_side()
        self._audit_pack.sync(self, side[3])
        if self.interner.snapshot_size() > self._cs_cache[0][1]:
            side = self._constraint_side()  # vocab grew: re-pack tables
        ordered, cp, groups, _col_specs, _crow = side
        ap = self._audit_pack
        if st.layout_gen != ap.layout_gen or ap.n_rows == 0:
            return None
        if len(st.row_cols) > self.DELTA_ROW_COLS_MAX:
            return None  # too much cumulative churn: rebase via full sweep
        if not ap.delta_dirty:
            st.store_epoch = self.store.epoch
            self.last_sweep_stats = {
                "pack_ms": (_time.perf_counter() - t0) * 1e3,
                "device_ms": 0.0, "fetch_ms": 0.0, "fetch_bytes": 0.0,
                "cached": 1.0,
            }
            return ap.reviews, ordered, st
        if len(ap.delta_dirty) > self.DELTA_MAX_ROWS:
            return None
        # referential policies: the delta dispatch must also re-evaluate
        # the READERS of every key group the churn touched (a churn row
        # invalidates only its key group — never the cluster).  Without a
        # current join index the aggregate cannot be maintained
        # incrementally, so rebase via a full sweep.
        js = None
        if self._active_join_plans():
            js = self._join_state
            if (
                js is None or not js.built
                or js.sig != tuple(
                    p.sig for p in self._active_join_plans()
                )
                or js.rebuild_gen != ap.rebuild_gen
            ):
                return None
            affected = js.affected(ap, self.interner, ap.delta_dirty)
            if len(ap.delta_dirty) + len(affected) > self.DELTA_MAX_ROWS:
                return None
        from .deltasweep import MaskSource

        got = st.mask_src.peek(wait_s=self.DELTA_MASK_WAIT_S)
        if got is MaskSource.BUSY:
            # the base mask is still tracing/compiling in the prefetch
            # thread: a full sweep (sub-second now) beats blocking the
            # audit behind that compile; the delta path resumes once it
            # lands (the full sweep rebases state with a resolved-or-
            # prefetching source either way)
            return None
        if got is None:
            # no resolver running (prefetch crashed or was never kicked):
            # resolve here, with the same failure containment as
            # _apply_delta — a dispatch error must degrade to a full
            # sweep, not crash the audit
            try:
                st.mask_src.get()
            except Exception:
                import logging

                logging.getLogger("gatekeeper_tpu.driver").exception(
                    "base-mask resolution failed; rebasing via a full sweep"
                )
                self._delta_state = None
                return None
        # drained only once eligibility is certain; any failure past this
        # point must invalidate the state (the caller then runs a full
        # sweep, which rebases knowledge and clears both dirty channels)
        rows = sorted(ap.take_delta_dirty())
        join_rows = 0
        if js is not None:
            # commit the churn to the join index: updates provider/reader
            # maps, bumps affected readers' row generations (stale render
            # reuse), and returns the key-group rows to co-dispatch
            extra = js.commit(ap, self.interner, rows)
            if extra:
                join_rows = len(extra)
                rows = sorted(set(rows) | extra)
                from ..metrics.catalog import record_join_affected

                record_join_affected(join_rows)
        try:
            return self._apply_delta(st, ap, rows, ordered, cp, groups, t0,
                                     join_rows=join_rows)
        except Exception:
            import logging

            logging.getLogger("gatekeeper_tpu.driver").exception(
                "delta sweep failed for %d rows; rebasing via a full sweep",
                len(rows),
            )
            self._delta_state = None
            return None

    def _apply_delta(self, st, ap, rows, ordered, cp, groups, t0,
                     join_rows: int = 0):
        import time as _time
        t1 = _time.perf_counter()
        # ONE dispatch: the fused evaluation on the dirty-row slice AND the
        # gather of the same rows' before-columns from the resident
        # full-sweep mask; one [C, 2d] int8 fetch
        width = 8
        while width < len(rows):
            width *= 2
        rows_pad = np.asarray(rows + [rows[-1]] * (width - len(rows)), np.int32)
        rv_slice = {k: a[rows_pad] for k, a in ap.rp.items()}
        cols_slice = {
            ck: {leaf: a[rows_pad] for leaf, a in leaves.items()}
            for ck, leaves in ap.cols.items()
        }
        group_params = [p for *_s, p in groups]
        mesh = self._mesh()
        cs_d, gp_d = self._constraint_device_side(
            cp.arrays, group_params, None, mesh
        )
        # post-commit join tables: the [C, d] dispatch evaluates the
        # churned rows AND the affected key-group readers against the
        # UPDATED global aggregate (ops/joinkernel.py 'tables' mode)
        jt = self._join_delta_tables()
        jtail = (jt,) if jt is not None else ()
        # [C_total, 2d] from the device; crow folds pad rows out so the
        # incremental state stays per ordered constraint
        if mesh is not None:
            delta_fn = self._delta_dispatch_fn(mesh)
            mask_in = st.mask_src.get()
            both_dev = self._guarded_mesh_dispatch(
                mesh,
                lambda: delta_fn(
                    mask_in, rows_pad, rv_slice, cs_d, cols_slice, gp_d,
                    *jtail
                ),
                enter=False,
            )
        else:
            both_dev = self._delta_dispatch_fn(mesh)(
                st.mask_src.get(), rows_pad, rv_slice, cs_d, cols_slice,
                gp_d, *jtail
            )
        both = np.asarray(both_dev).astype(bool)[st.crow]
        fetch_bytes = both.nbytes
        base_old, dmask = both[:, :width], both[:, width:]
        t2 = _time.perf_counter()
        for j, r in enumerate(rows):
            # rows dirtied since the base sweep carry their current column
            # in the state cache; the device gather serves the rest
            old = st.old_column(r)
            if old is None:
                old = base_old[:, j]
            st.apply_row(r, old, dmask[:, j])
        st.store_epoch = self.store.epoch
        self.last_sweep_stats = {
            "pack_ms": (t1 - t0) * 1e3,
            "device_ms": (t2 - t1) * 1e3,
            "fetch_ms": 0.0,
            "fetch_bytes": float(fetch_bytes),
            "delta_rows": float(len(rows)),
            "rows": float(ap.n_rows),
            "cells": float(len(ordered) * len(rows)),
            "shards": 1.0 if mesh is None else float(mesh.devices.size),
        }
        if jt is not None:
            # key-group locality: how many of the dispatched rows were
            # affected readers rather than content churn (the quantity
            # tools/check_join_parity.py pins to the exact group size)
            self.last_sweep_stats["join_affected_rows"] = float(join_rows)
        if mesh is not None:
            # churn locality: the dirty rows' slabs are the only shards
            # whose resident state the next full placement must touch
            from ..parallel.mesh import owning_shards

            self.last_sweep_stats["delta_shards"] = float(
                len(owning_shards(rows, ap.capacity, mesh.devices.size))
            )
        return ap.reviews, ordered, st

    def audit_capped(self, cap: int, tracing: bool = False):
        """Cap-aware end-to-end audit: the status write-back keeps at most
        `cap` violations per constraint (--constraint-violations-limit,
        reference manager.go:49).

        Steady state is INCREMENTAL: only rows whose packed content changed
        since the last sweep are re-evaluated on device ([C, d] delta), and
        the per-constraint counts + first-K candidate lists are maintained
        host-side (ops/deltasweep.py) — per-sweep cost is O(churn), not
        O(cluster), on the single-device path AND under the mesh (the
        delta dispatch runs against the shard-resident base mask).  The
        first sweep (and any sweep after a template or
        layout change, or with too much churn) is a FULL
        device sweep whose on-device reduction ships only [C] counts +
        [C, K] candidate indices to the host (never the [C, R] mask).
        When capped rendering needs candidates beyond the known horizon it
        fetches that one constraint's mask row (base state fresh) or falls
        back to one full sweep (NeedsFullSweep).

        Returns (results, totals, trace) with totals
        {(kind, name): (count, how)}: "exact" when the count equals the
        reference's totalViolations semantics — every candidate rendered,
        or the cap was hit but the program is provably count-exact
        (_count_exact); "resources" when the cap cut rendering short and
        the count is device-candidate resources, an over-approximation."""
        if cap is None or cap <= 0:
            return InterpDriver.audit_capped(self, cap or 0, tracing=tracing)
        if not self.breaker.allow():
            return InterpDriver.audit_capped(self, cap, tracing=tracing)
        self.last_sweep_stats = {}  # stale stats must not decide `cached`
        try:
            out = self._audit_capped_device(cap, tracing)
        except Exception as e:
            from .joinkernel import JoinDivergence

            if isinstance(e, JoinDivergence):
                # armed join-parity assertion: surface it (see audit())
                raise
            self._record_device_failure(e)
            log.warning(
                "device capped audit failed (%s: %s); serving from the "
                "interpreter tier", type(e).__name__, e,
            )
            return InterpDriver.audit_capped(self, cap, tracing=tracing)
        # see audit(): only a sweep that actually dispatched counts as a
        # breaker success (cache-served and empty-inventory sweeps don't)
        stats = self.last_sweep_stats
        if stats and not stats.get("cached"):
            self.breaker.record_success()
        return out

    def _audit_capped_device(self, cap: int, tracing: bool = False):
        from .deltasweep import NeedsFullSweep

        self._wait_ready_for_audit()
        with self._lock:
            K = self._audit_topk(cap)
            trace: List[str] = [] if tracing else None
            for _attempt in (0, 1):
                got = self._try_delta(K)
                if got is None:
                    # gklint: disable=blocking-under-lock -- same audit
                    # exclusive-device-ownership contract as
                    # _audit_device above (watchdog-bounded)
                    sweep = self._audit_sweep(K)
                    if sweep is None:
                        # same contract as InterpDriver: every registered
                        # constraint reports an exact zero on an empty
                        # inventory
                        empty = {
                            (kind, cname): (0, "exact")
                            for kind in self.constraints
                            for cname in self.constraints[kind]
                        }
                        return [], empty, (
                            "\n".join(trace) if tracing else None
                        )
                    got = (self._audit_pack.reviews, sweep[1],
                           self._delta_state)
                try:
                    return self._render_capped(
                        got[0], got[1], got[2], cap, trace
                    )
                except NeedsFullSweep:
                    # the state's known candidates ran out while unknown
                    # ones exist and the base mask is stale: rebase
                    self._delta_state = None
                    self._audit_cache = None
            raise AssertionError("fresh full sweep cannot need another")

    def _render_capped(self, reviews, ordered, st, cap, trace):
        """Render up to `cap` violations per constraint from the
        incremental state's candidate lists (identical for a
        fresh-from-full-sweep state and a delta-updated one).

        Per-constraint result reuse: a constraint whose walked candidates
        and their row generations are unchanged since the last sweep
        renders the identical Result slice; with 1-object churn, ~all
        constraints reuse wholesale and the render cost is O(changed)."""
        from .deltasweep import NeedsFullSweep

        import time as _time

        t0 = _time.perf_counter()
        ap = self._audit_pack
        if self._render_memo_epoch != self._cs_epoch:
            self._render_memo.clear()
            self._render_memo_epoch = self._cs_epoch
        reuse = st.render_cache if trace is None else {}
        new_cache: Dict[Tuple, Tuple] = {}
        inventory = self._inventory_for_render()
        rowviews: Dict[int, object] = {}
        results: List[Result] = []
        totals: Dict[Tuple[str, str], Tuple[int, str]] = {}
        R = len(reviews)
        rendered_cells = 0
        fallback_rows = 0
        fallback_bytes = 0
        tiers0 = dict(self._tier_counts)
        cost_on = obscosts.enabled()
        cost_entries: List[Tuple] = []

        def render(ri, kind, name, constraint, uses_inv, action,
                   join_strict=False, inv=None):
            violations = self._memo_cell(
                kind, name, ri, constraint, reviews[ri], rowviews,
                inventory if inv is None else inv, uses_inv,
                ap.row_gen[ri],
            )
            if join_strict and not violations:
                # an exact join plan flagged this cell but the oracle
                # renders nothing: interned-key/aggregate divergence
                # (counted always; raises under GK_JOIN_ASSERT=1), with
                # the documented gv-twin corner filtered out
                self._note_join_false_positive(kind, name, int(ri))
            for v in violations:
                results.append(
                    Result(
                        msg=str(v.get("msg", "")),
                        metadata={"details": v.get("details", {})},
                        constraint=constraint,
                        review=reviews[ri],
                        enforcement_action=action,
                    )
                )
                if trace is not None:
                    trace.append(f"violation {kind}/{name}: {v.get('msg')}")

        def candidates(ci, n_cand):
            """Known candidate rows ascending; beyond the horizon, fetch
            the constraint's mask row when the base mask is still fresh
            (no delta applied), else escalate to a full sweep."""
            nonlocal fallback_rows, fallback_bytes
            lst = st.cand[ci]
            yield from lst
            if st.horizon[ci] is None or n_cand <= len(lst):
                return
            if st.row_cols:
                raise NeedsFullSweep(ci)
            row = np.asarray(st.mask_src.get()[int(st.crow[ci])])[:R]
            fallback_rows += 1
            fallback_bytes += row.nbytes
            full = [int(x) for x in np.nonzero(row)[0]]
            st.cand[ci] = full  # complete knowledge for future sweeps
            st.horizon[ci] = None
            for ri in full[len(lst):]:
                yield ri

        def _join_complete(ci):
            # complete candidate knowledge: the union below must cover
            # the constraint's readers, and candidates() never extends
            # st.cand past this exact condition
            return (st.horizon[ci] is None
                    or int(st.counts[ci]) <= len(st.cand[ci]))

        # ONE pruned join inventory per kind, shared by its constraints
        # (the full-sweep path's _inv_for argument: a provider SUPERSET
        # is equivalence-safe, so the union of the kind's candidate
        # rows serves every constraint) — K same-kind constraints
        # missing the memo in one sweep build one tree, not K
        join_union: Dict[str, set] = {}
        for ci, (kind, _name, _c) in enumerate(ordered):
            if (int(st.counts[ci]) == 0 or not self._join_safe(kind)
                    or not _join_complete(ci)):
                continue
            tmpl = self.templates.get(kind)
            if tmpl is None or getattr(tmpl.policy, "uses_inventory",
                                       True):
                join_union.setdefault(kind, set()).update(st.cand[ci])
        join_inv_by_kind: Dict[str, object] = {}

        for ci, (kind, name, constraint) in enumerate(ordered):
            ckey = (kind, name)
            n_cand = int(st.counts[ci])
            if n_cand == 0:
                totals[ckey] = (0, "exact")
                continue
            tmpl = self.templates.get(kind)
            uses_inv = (
                True if tmpl is None
                else getattr(tmpl.policy, "uses_inventory", True)
            )
            join_strict = False
            join_inv = None
            if uses_inv and self._join_safe(kind):
                # every inventory read is a classified join plan: the
                # join index bumps reader row generations when a key
                # group changes, so rendered results are content-keyed
                # like inventory-free templates — O(churn) rendering
                uses_inv = False
                join_strict = self._join_strict(kind, constraint)
                if _join_complete(ci):
                    # grouped interpreter pass (docs/referential.md):
                    # every flagged cell renders against ONE pruned
                    # inventory holding the kind's key groups' provider
                    # rows — the interp's O(R) per-cell inventory walk
                    # becomes O(group).  LAZY: built on the first
                    # render MISS, so steady-state memo-hit sweeps
                    # never pay it.  Candidate knowledge must be
                    # complete; the horizon-fetch fallback keeps the
                    # full tree.
                    join_inv = join_inv_by_kind.get(kind)
                    if join_inv is None:
                        join_inv = self._lazy_join_inventory(
                            kind, sorted(join_union.get(kind, ())),
                            inventory,
                        )
                        join_inv_by_kind[kind] = join_inv
            lst = st.cand[ci]
            sig = None
            if trace is None and not uses_inv and len(lst) <= 512:
                # unchanged candidates + row generations (and the same cap)
                # render identically; cap is per-call, so it keys the entry
                sig = (
                    cap, n_cand, tuple(lst),
                    tuple(ap.row_gen[r] for r in lst if r < R),
                )
                hit = reuse.get(ckey)
                if hit is not None and hit[0] == sig:
                    results.extend(hit[1])
                    totals[ckey] = hit[2]
                    new_cache[ckey] = hit
                    if cost_on:
                        # wholesale render-cache reuse: zero cells walked,
                        # one memo hit, the cached violations replayed
                        cost_entries.append((
                            kind, name, 0, "interp", len(hit[1]), 1,
                        ))
                    continue
            action = self._enforcement_action(constraint)
            start = len(results)
            r_start = rendered_cells
            capped = False
            for ri in candidates(ci, n_cand):
                if len(results) - start >= cap:
                    capped = True
                    break
                if ri >= R or reviews[ri] is None:
                    continue  # tombstoned row (valid=False on device too)
                render(ri, kind, name, constraint, uses_inv, action,
                       join_strict=join_strict, inv=join_inv)
                rendered_cells += 1
            if not capped:
                totals[ckey] = (len(results) - start, "exact")
            elif self._count_exact(kind, constraint):
                # device count == violation count, provably: report the
                # full total past the cap (manager.go:188 semantics)
                totals[ckey] = (n_cand, "exact")
            else:
                totals[ckey] = (
                    max(n_cand, len(results) - start), "resources"
                )
            if sig is not None:
                new_cache[ckey] = (sig, tuple(results[start:]), totals[ckey])
            if cost_on:
                plan = self._render_plan_for(kind, name, constraint)
                cost_entries.append((
                    kind, name, rendered_cells - r_start,
                    getattr(plan, "tier", None) or "interp",
                    len(results) - start, 0,
                ))
        if trace is None:
            st.render_cache = new_cache
        tiers = {
            k: self._tier_counts[k] - tiers0.get(k, 0)
            for k in self._tier_counts
        }
        obstrace.record_span(
            "audit.render", t0, _time.perf_counter(),
            stage=obstrace.RENDER, tier="tpu",
            rendered_cells=rendered_cells,
            plan_static=tiers["static"], plan_slots=tiers["slots"],
            plan_interp=tiers["interp"],
        )
        self.last_sweep_stats.update(
            render_ms=(_time.perf_counter() - t0) * 1e3,
            rendered_cells=float(rendered_cells),
            render_plan_cells=float(tiers["static"] + tiers["slots"]),
            render_interp_cells=float(tiers["interp"]),
            fallback_rows=float(fallback_rows),
            fallback_bytes=float(fallback_bytes),
            results=float(len(results)),
        )
        if cost_on and cost_entries:
            obscosts.record_render(
                cost_entries, _time.perf_counter() - t0, 0.0
            )
        self._flush_render_counts()
        return results, totals, ("\n".join(trace) if trace is not None else None)
