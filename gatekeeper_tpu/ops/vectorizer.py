"""Rego AST -> VProgram compiler.

Compiles a template's `violation` rules into vectorized predicates over the
VExpr IR (ops/vexpr.py) under the over-approximation contract:

- A recognized condition compiles to an exact VExpr node.
- An unrecognized condition in POSITIVE position is DROPPED (widens the
  predicate; sound) and the program is marked inexact.
- Under `not`, the negated expression must compile EXACTLY (otherwise
  negating an approximation would narrow); if it cannot, the whole `not`
  statement is dropped instead (widens; sound).

Recognized fragment (derived from the reference's policy corpus — PSP
family, required-labels family, allowed-repos family; see SURVEY.md 2.3):
iteration over (possibly nested, unioned) array paths incl. helper partial
sets; truthiness/negation of paths; cross-type comparisons; string
predicates vs parameters (startswith/endswith/contains/re_match) incl. the
`[good | p = params[_]; good = pred(x, p)]` + `not any(...)` idiom; boolean
helper functions (inlined as clause disjunctions); key-set comprehensions
with set difference and count comparisons.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..engine.interp import CompiledModule, TemplatePolicy
from ..rego.ast import (
    ArrayCompr,
    BinOp,
    Call,
    Expr,
    Node,
    Ref,
    Rule,
    Scalar,
    SetCompr,
    Var,
)
from .columns import ColumnSpec, Path
from .vexpr import (
    AnyParam,
    BoolOp,
    Clause,
    ColRef,
    Cmp,
    Const,
    Lit,
    ParamElemRef,
    ParamRef,
    SetCountCmp,
    StrPred,
    Truthy,
    VProgram,
)

_STR_PREDS = {"startswith", "endswith", "contains", "re_match"}
_BENIGN_CALLS = {"sprintf", "concat", "json.marshal", "format_int", "lower", "upper"}
_CMP_OPS = {"==", "!=", "<", "<=", ">", ">="}


# ---- symbolic values ------------------------------------------------------


@dataclass(frozen=True)
class SPath:
    """root: 'review' | 'params' | ('slot', iter_paths); segs: []-free."""

    root: Any
    segs: Tuple[str, ...]


@dataclass(frozen=True)
class SConst:
    value: Any


@dataclass(frozen=True)
class SKeySet:
    iter_paths: Tuple[Path, ...]
    rel: Tuple[str, ...]
    exclude: Tuple[str, ...]


@dataclass(frozen=True)
class SParamIds:
    ppath: Tuple[str, ...]
    subpath: Tuple[str, ...] = ()


@dataclass(frozen=True)
class SSetDiff:
    left: Any
    right: Any


@dataclass(frozen=True)
class SPredAny:
    node: AnyParam


@dataclass(frozen=True)
class SUnknown:
    pass


class _Unsupported(Exception):
    pass


class Vectorizer:
    def __init__(self, policy: TemplatePolicy):
        self.policy = policy
        self.cm: CompiledModule = policy.main
        self.columns: Dict[Tuple, ColumnSpec] = {}
        self.param_scalars: set = set()
        self.param_arrays: Dict[Tuple[str, ...], set] = {}
        self.literals: set = set()
        self.str_preds: List[StrPred] = []
        self.exact = True
        # classified cross-resource aggregates (ops/joinkernel.py),
        # indexed by JoinCmp.plan_id
        self.join_plans: List = []

    # ---- public ----------------------------------------------------------

    def compile(self) -> Optional[VProgram]:
        clauses: List[Clause] = []
        plans: List[Optional[object]] = []
        for rule in self.cm.rules.get("violation", []):
            if not rule.is_partial_set:
                return None
            clause, plan = self._compile_clause(rule)
            if clause is None:
                # nothing recognized: all-true for this clause
                clauses.append(Clause(conds=(Const(True),), slot_iter=None))
                plans.append(None)
                self.exact = False
            else:
                clauses.append(clause)
                plans.append(plan)
        return VProgram(
            clauses=clauses,
            column_specs=list(self.columns.values()),
            param_scalars=sorted(self.param_scalars),
            param_arrays=[
                (p, tuple(sorted(subs))) for p, subs in sorted(self.param_arrays.items())
            ],
            str_preds=self.str_preds,
            literals=sorted(self.literals),
            exact=self.exact,
            clause_plans=tuple(plans),
            join_plans=tuple(self.join_plans),
        )

    # ---- clause compilation ----------------------------------------------

    def _compile_clause(self, rule: Rule):
        # referential (cross-resource) bodies classify into join plans
        # FIRST: the generic path below would drop every data.inventory
        # statement (sound but inexact, and O(inventory) to render).
        # An unclassified referential clause still falls through to the
        # generic over-approximation, so recognition failures only cost
        # performance, never correctness.
        from .joinkernel import classify_join_clause

        jc = classify_join_clause(self, rule)
        if jc is not None:
            return jc, None  # rendered by the interpreter (inventory)
        env: Dict[str, Any] = {}
        conds: List = []
        # guards: rhs terms of recognized non-iteration assignments.  The
        # MASK may ignore their definedness (dropping them only widens),
        # but the render plan replaces the interpreter as the exactness
        # filter, so an assignment whose rhs is undefined (missing field,
        # failed benign call) must fail the clause there exactly as it
        # fails the interpreted body (ops/renderplan.py guard plans).
        # helper_guards is ONE shared list: dict(state) copies in nested
        # helper inlining alias it, so guards surface from any depth
        state = {"slot": None, "guards": [], "helper_guards": []}
        # AST-level assignment environment for the message-plan compiler:
        # the rule key typically references body-assigned vars
        # (`msg := sprintf(...)`) whose AST the symbolic env discards
        ast_env: Dict[str, Node] = {}
        recognized = 0
        for stmt in rule.body:
            if (
                stmt.kind in ("assign", "unify")
                and len(stmt.terms) == 2
                and isinstance(stmt.terms[0], Var)
            ):
                ast_env.setdefault(stmt.terms[0].name, stmt.terms[1])
            ok = self._compile_stmt(stmt, env, conds, state, exact_required=False)
            if ok:
                recognized += 1
            else:
                self.exact = False
        if recognized == 0 and not conds and state["slot"] is None:
            return None, None
        clause = Clause(conds=tuple(conds), slot_iter=state["slot"])
        from .renderplan import compile_clause_plan

        plan = compile_clause_plan(
            self, rule, env, ast_env, state["slot"], state["guards"],
            state["helper_guards"],
        )
        return clause, plan

    def _compile_stmt(self, stmt: Expr, env, conds, state, exact_required: bool) -> bool:
        """Compile one statement into zero or more conds.  Returns False when
        the statement was dropped (only allowed when not exact_required)."""
        try:
            if stmt.withs:
                # document patching is interpreter-only
                raise _Unsupported()
            if stmt.kind == "some":
                return True
            if stmt.kind == "not":
                inner = stmt.terms[0]
                node = self._compile_cond_expr(inner, env, state, exact_required=True)
                conds.append(BoolOp("not", (node,)))
                return True
            if stmt.kind in ("assign", "unify"):
                return self._compile_assign(stmt, env, conds, state, exact_required)
            # plain term condition
            node = self._compile_cond_expr(stmt, env, state, exact_required)
            conds.append(node)
            return True
        except _Unsupported:
            if exact_required:
                raise
            return False

    # ---- assignments ------------------------------------------------------

    def _compile_assign(self, stmt: Expr, env, conds, state, exact_required) -> bool:
        lhs, rhs = stmt.terms
        if not isinstance(lhs, Var):
            raise _Unsupported()
        # iteration?
        it = self._try_iteration(rhs, env, state)
        if it is not None:
            env[lhs.name] = it
            return True
        sym = self._resolve(rhs, env, state, allow_compr=True)
        # non-iteration assignment: its rhs definedness fails the body in
        # the interpreter, so the render plan must guard on it
        state.setdefault("guards", []).append(rhs)
        if isinstance(sym, SUnknown):
            env[lhs.name] = sym
            if self._benign_rhs(rhs):
                return True
            raise _Unsupported()
        env[lhs.name] = sym
        return True

    def _benign_rhs(self, rhs: Node) -> bool:
        return isinstance(rhs, Call) and ".".join(rhs.path) in _BENIGN_CALLS

    # ---- iteration recognition -------------------------------------------

    def _try_iteration(self, t: Node, env, state):
        """Recognize `<ref with wildcard(s)>` producing a slot entity or a
        slot-relative scalar; registers the clause slot axis."""
        if not isinstance(t, Ref) or not isinstance(t.head, Var):
            return None
        has_wild = any(isinstance(o, Var) and o.is_wildcard for o in t.operands)
        if not has_wild:
            return None
        base_paths, strip_review, skip_first_wild = self._iter_base(t.head, env)
        if base_paths is None:
            return None
        # walk operands: strings extend; wildcards flatten array levels —
        # except a helper partial set's first wildcard, which is the set
        # membership selector (the entity itself), not another level.
        segs: List[str] = []
        first_wild = True
        for op in t.operands:
            if isinstance(op, Scalar) and isinstance(op.value, str):
                segs.append(op.value)
            elif isinstance(op, Var) and op.is_wildcard:
                if first_wild and skip_first_wild:
                    first_wild = False
                    continue
                first_wild = False
                segs.append("[]")
            else:
                raise _Unsupported()
        if strip_review:
            if segs[:1] != ["review"]:
                raise _Unsupported()
            segs = segs[1:]
        if segs and "[]" in segs:
            last = len(segs) - 1 - segs[::-1].index("[]")
            iter_paths = tuple(p + tuple(segs[: last + 1]) for p in base_paths)
            rel = tuple(segs[last + 1 :])
        else:
            # all flattening lives in the base paths (helper membership)
            iter_paths = tuple(base_paths)
            rel = tuple(segs)
        if state["slot"] is None:
            state["slot"] = iter_paths
        elif state["slot"] != iter_paths:
            raise _Unsupported()  # second iteration axis in one clause
        # Always register the entity-presence column so the slot mask exists
        # even when no per-slot condition survives compilation.
        base_spec = ColumnSpec("slot", iter_paths, ())
        self.columns[base_spec.key] = base_spec
        return SPath(("slot", iter_paths), rel)

    def _iter_base(self, head: Var, env):
        """Resolve an iteration head -> (review-rooted base paths,
        strip_review_prefix, skip_first_wildcard)."""
        if head.name in env:
            v = env[head.name]
            if isinstance(v, SPath) and v.root == "review":
                return (v.segs,), False, False
            return None, False, False
        if head.name == "input":
            return ((),), True, False
        # helper partial-set rule that unions plain iterations
        rules = self.cm.rules.get(head.name)
        if rules and all(r.is_partial_set for r in rules):
            paths: List[Path] = []
            for r in rules:
                p = self._helper_source(r)
                if p is None:
                    return None, False, False
                paths.append(p)
            return tuple(paths), False, True
        return None, False, False

    def _helper_source(self, rule: Rule) -> Optional[Path]:
        """A helper like `input_containers[c] { c := input...containers[_] }`:
        single body statement assigning the key var from an iteration."""
        if len(rule.body) != 1 or not isinstance(rule.key, Var):
            return None
        stmt = rule.body[0]
        if stmt.kind not in ("assign", "unify"):
            return None
        lhs, rhs = stmt.terms
        if not (isinstance(lhs, Var) and lhs.name == rule.key.name):
            return None
        if not (isinstance(rhs, Ref) and isinstance(rhs.head, Var) and rhs.head.name == "input"):
            return None
        segs: List[str] = []
        for op in rhs.operands:
            if isinstance(op, Scalar) and isinstance(op.value, str):
                segs.append(op.value)
            elif isinstance(op, Var) and op.is_wildcard:
                segs.append("[]")
            else:
                return None
        if not segs or segs[-1] != "[]" or segs[0] != "review":
            return None
        return tuple(segs[1:])  # review-rooted

    # ---- term resolution --------------------------------------------------

    def _resolve(self, t: Node, env, state, allow_compr=False):
        if isinstance(t, Scalar):
            return SConst(t.value)
        if isinstance(t, Var):
            if t.name in env:
                return env[t.name]
            raise _Unsupported()
        if isinstance(t, Ref):
            return self._resolve_ref(t, env, state)
        if isinstance(t, SetCompr) and allow_compr:
            return self._resolve_setcompr(t, env, state)
        if isinstance(t, ArrayCompr) and allow_compr:
            return self._resolve_satisfied_compr(t, env, state)
        if isinstance(t, BinOp) and t.op == "-" and allow_compr:
            left = self._resolve(t.lhs, env, state)
            right = self._resolve(t.rhs, env, state)
            if isinstance(left, (SKeySet, SParamIds)) and isinstance(
                right, (SKeySet, SParamIds)
            ):
                return SSetDiff(left, right)
            return SUnknown()
        if isinstance(t, Call):
            return SUnknown()
        return SUnknown()

    def _resolve_ref(self, t: Ref, env, state):
        if not isinstance(t.head, Var):
            raise _Unsupported()
        segs: List[str] = []
        for op in t.operands:
            if isinstance(op, Scalar) and isinstance(op.value, str):
                segs.append(op.value)
            elif isinstance(op, Var) and not op.is_wildcard and isinstance(env.get(op.name), SConst):
                v = env[op.name].value
                if not isinstance(v, str):
                    raise _Unsupported()
                segs.append(v)
            else:
                raise _Unsupported()
        name = t.head.name
        if name == "input":
            if segs[:1] == ["review"]:
                rest = tuple(segs[1:])
                return SPath("review", rest)
            if segs[:1] == ["parameters"]:
                return SPath("params", tuple(segs[1:]))
            raise _Unsupported()
        if name in env:
            base = env[name]
            if isinstance(base, SPath):
                return SPath(base.root, base.segs + tuple(segs))
            raise _Unsupported()
        raise _Unsupported()

    def _resolve_setcompr(self, t: SetCompr, env, state):
        """{x | PATH[x]} -> key set; {x | x = params.P[_]} -> param id set;
        extra `x != "lit"` conditions become excludes."""
        if not isinstance(t.head, Var):
            return SUnknown()
        var = t.head.name
        key_source = None
        param_source = None
        excludes: List[str] = []
        if any(stmt.withs for stmt in t.body):
            return SUnknown()  # document patching is interpreter-only
        for stmt in t.body:
            if stmt.kind == "term" and isinstance(stmt.terms[0], Ref):
                ref = stmt.terms[0]
                ops = ref.operands
                if ops and isinstance(ops[-1], Var) and ops[-1].name == var:
                    base = Ref(ref.head, ops[:-1])
                    try:
                        sym = self._resolve_ref_allow_arrays(base, env)
                    except _Unsupported:
                        return SUnknown()
                    key_source = sym
                    continue
                return SUnknown()
            if stmt.kind in ("assign", "unify"):
                lhs, rhs = stmt.terms
                if isinstance(lhs, Var) and lhs.name == var and isinstance(rhs, Ref):
                    # input.parameters.<pp>[_](.<subpath>)*
                    src = self._param_array_elem_path(rhs)
                    if src is not None:
                        param_source = src
                        continue
                return SUnknown()
            if stmt.kind == "term" and isinstance(stmt.terms[0], BinOp):
                b = stmt.terms[0]
                if (
                    b.op == "!="
                    and isinstance(b.lhs, Var)
                    and b.lhs.name == var
                    and isinstance(b.rhs, Scalar)
                    and isinstance(b.rhs.value, str)
                ):
                    excludes.append(b.rhs.value)
                    continue
                return SUnknown()
            return SUnknown()
        if param_source is not None:
            pp, sub = param_source
            self.param_arrays.setdefault(pp, set()).add(sub)
            return SParamIds(pp, sub)
        if key_source is not None:
            iter_paths, rel = key_source
            return SKeySet(iter_paths, rel, tuple(excludes))
        return SUnknown()

    def _resolve_ref_allow_arrays(self, t: Ref, env):
        """Resolve a ref that may traverse arrays ([]) — used for key-set
        sources like spec.volumes[_] or metadata.labels.  Returns
        (iter_paths, rel_segs) review-rooted."""
        if not isinstance(t.head, Var):
            raise _Unsupported()
        segs: List[str] = []
        name = t.head.name
        if name in env:
            base = env[name]
            if isinstance(base, SPath) and base.root == "review":
                segs.extend(base.segs)
            elif isinstance(base, SPath) and isinstance(base.root, tuple):
                # slot-entity-relative key set: unsupported for now
                raise _Unsupported()
            else:
                raise _Unsupported()
        elif name == "input":
            pass
        else:
            raise _Unsupported()
        for op in t.operands:
            if isinstance(op, Scalar) and isinstance(op.value, str):
                segs.append(op.value)
            elif isinstance(op, Var) and op.is_wildcard:
                segs.append("[]")
            else:
                raise _Unsupported()
        if name == "input":
            if segs[:1] != ["review"]:
                raise _Unsupported()
            segs = segs[1:]
        if "[]" in segs:
            last = len(segs) - 1 - segs[::-1].index("[]")
            return (tuple(segs[: last + 1]),), tuple(segs[last + 1 :])
        return (tuple(segs),), ()

    def _resolve_satisfied_compr(self, t: ArrayCompr, env, state):
        """[good | p = input.parameters.X[_]; good = pred(col, p)] ->
        SPredAny(AnyParam(X, [StrPred...]))."""
        if not isinstance(t.head, Var):
            return SUnknown()
        good = t.head.name
        param_path = None
        param_var = None
        pred_node = None
        if any(stmt.withs for stmt in t.body):
            return SUnknown()  # document patching is interpreter-only
        for stmt in t.body:
            if stmt.kind not in ("assign", "unify"):
                return SUnknown()
            lhs, rhs = stmt.terms
            if isinstance(lhs, Var) and isinstance(rhs, Ref):
                if (
                    isinstance(rhs.head, Var)
                    and rhs.head.name == "input"
                    and rhs.operands
                    and isinstance(rhs.operands[0], Scalar)
                    and rhs.operands[0].value == "parameters"
                    and isinstance(rhs.operands[-1], Var)
                    and rhs.operands[-1].is_wildcard
                ):
                    pp = []
                    for op in rhs.operands[1:-1]:
                        if isinstance(op, Scalar) and isinstance(op.value, str):
                            pp.append(op.value)
                        else:
                            return SUnknown()
                    param_path = tuple(pp)
                    param_var = lhs.name
                    continue
            if (
                isinstance(lhs, Var)
                and lhs.name == good
                and isinstance(rhs, Call)
                and len(rhs.path) == 1
                and rhs.path[0] in _STR_PREDS
                and param_path is not None
            ):
                pred_node = self._make_strpred(
                    rhs, env, state, param_elem=(param_var, param_path)
                )
                continue
            return SUnknown()
        if pred_node is None or param_path is None:
            return SUnknown()
        self.param_arrays.setdefault(param_path, set()).add(())
        return SPredAny(AnyParam(param_path, (pred_node,)))

    # ---- conditions -------------------------------------------------------

    def _compile_cond_expr(self, stmt: Expr, env, state, exact_required):
        if stmt.kind == "not":
            inner = self._compile_cond_expr(stmt.terms[0], env, state, True)
            return BoolOp("not", (_flip_unknown_defaults(inner),))
        if stmt.kind in ("assign", "unify"):
            raise _Unsupported()
        t = stmt.terms[0]
        return self._compile_cond_term(t, env, state, exact_required)

    def _compile_cond_term(self, t: Node, env, state, exact_required):
        if isinstance(t, Ref):
            # `banned[tag]`-style membership on a param id set
            if (
                isinstance(t.head, Var)
                and t.head.name in env
                and isinstance(env[t.head.name], SParamIds)
                and len(t.operands) == 1
            ):
                elem = self._operand(self._resolve(t.operands[0], env, state), state)
                s = env[t.head.name]
                self.param_arrays.setdefault(s.ppath, set()).add(s.subpath)
                return AnyParam(
                    s.ppath, (Cmp("==", ParamElemRef(s.ppath, s.subpath), elem),)
                )
            sym = self._resolve(t, env, state)
            return Truthy(self._operand(sym, state))
        if isinstance(t, Var):
            sym = self._resolve(t, env, state)
            if isinstance(sym, SPredAny):
                raise _Unsupported()
            return Truthy(self._operand(sym, state))
        if isinstance(t, BinOp):
            if t.op not in _CMP_OPS:
                raise _Unsupported()
            return self._compile_cmp(t, env, state)
        if isinstance(t, Call):
            return self._compile_call_cond(t, env, state, exact_required)
        raise _Unsupported()

    def _compile_cmp(self, t: BinOp, env, state):
        # count(x) cmp n with x a set difference
        for lhs, rhs, op in ((t.lhs, t.rhs, t.op), (t.rhs, t.lhs, _flip(t.op))):
            if (
                isinstance(lhs, Call)
                and lhs.path == ("count",)
                and isinstance(rhs, Scalar)
                and isinstance(rhs.value, int)
            ):
                arg = self._resolve(lhs.args[0], env, state, allow_compr=True)
                if isinstance(arg, SSetDiff):
                    return self._setcount(arg, op, rhs.value)
                raise _Unsupported()
        # `input.parameters.X[_] == v`: exists over the parameter array
        for lhs, rhs, op in ((t.lhs, t.rhs, t.op), (t.rhs, t.lhs, _flip(t.op))):
            pp = self._try_param_elem_ref(lhs)
            if pp is not None:
                other = self._operand(self._resolve(rhs, env, state), state)
                self.param_arrays.setdefault(pp, set()).add(())
                return AnyParam(pp, (Cmp(op, ParamElemRef(pp), other),))
        a = self._operand(self._resolve(t.lhs, env, state), state)
        b = self._operand(self._resolve(t.rhs, env, state), state)
        return Cmp(t.op, a, b)

    @staticmethod
    def _param_array_elem_path(t: Node):
        """input.parameters.<pp>[_](.<sub>)* -> ((pp,), (sub,)) or None."""
        if not (
            isinstance(t, Ref)
            and isinstance(t.head, Var)
            and t.head.name == "input"
            and len(t.operands) >= 2
            and isinstance(t.operands[0], Scalar)
            and t.operands[0].value == "parameters"
        ):
            return None
        pp: List[str] = []
        sub: List[str] = []
        seen_wild = False
        for op in t.operands[1:]:
            if isinstance(op, Var) and op.is_wildcard:
                if seen_wild:
                    return None
                seen_wild = True
            elif isinstance(op, Scalar) and isinstance(op.value, str):
                (sub if seen_wild else pp).append(op.value)
            else:
                return None
        if not seen_wild:
            return None
        return tuple(pp), tuple(sub)

    @staticmethod
    def _try_param_elem_ref(t: Node):
        """input.parameters.<path>[_] -> ppath, else None."""
        if not (
            isinstance(t, Ref)
            and isinstance(t.head, Var)
            and t.head.name == "input"
            and len(t.operands) >= 2
            and isinstance(t.operands[0], Scalar)
            and t.operands[0].value == "parameters"
            and isinstance(t.operands[-1], Var)
            and t.operands[-1].is_wildcard
        ):
            return None
        pp = []
        for op in t.operands[1:-1]:
            if isinstance(op, Scalar) and isinstance(op.value, str):
                pp.append(op.value)
            else:
                return None
        return tuple(pp)

    def _setcount(self, diff: SSetDiff, op: str, n: int):
        def side(s):
            if isinstance(s, SKeySet):
                spec = ColumnSpec("keyset", s.iter_paths, s.rel, s.exclude)
                self.columns[spec.key] = spec
                return ("keyset", spec.key)
            if isinstance(s, SParamIds):
                self.param_arrays.setdefault(s.ppath, set()).add(s.subpath)
                return ("paramids", (s.ppath, s.subpath))
            raise _Unsupported()

        return SetCountCmp(side(diff.left), side(diff.right), op, n)

    def _compile_call_cond(self, t: Call, env, state, exact_required):
        name = ".".join(t.path)
        if name in _STR_PREDS:
            return self._make_strpred(t, env, state)
        if name == "any" and len(t.args) == 1:
            sym = self._resolve(t.args[0], env, state)
            if isinstance(sym, SPredAny):
                return sym.node
            raise _Unsupported()
        if len(t.path) == 1 and t.path[0] in self.cm.rules:
            return self._inline_helper(t.path[0], t.args, env, state)
        raise _Unsupported()

    def _make_strpred(self, t: Call, env, state, param_elem=None):
        pred = t.path[0]
        if len(t.args) != 2:
            raise _Unsupported()
        a0, a1 = t.args
        if pred == "re_match":
            pattern, value = a0, a1
        else:
            value, pattern = a0, a1
        col_sym = self._resolve(value, env, state)
        col = self._operand(col_sym, state)
        if not isinstance(col, ColRef):
            raise _Unsupported()
        # pattern side: param scalar / param elem / literal
        if param_elem and isinstance(pattern, Var) and pattern.name == param_elem[0]:
            rhs: Any = ParamElemRef(param_elem[1])
        else:
            sym = self._resolve(pattern, env, state)
            if isinstance(sym, SConst) and isinstance(sym.value, str):
                rhs = Lit(sym.value)
                self.literals.add(sym.value)
            elif isinstance(sym, SPath) and sym.root == "params":
                self.param_scalars.add(sym.segs)
                rhs = ParamRef(sym.segs)
            else:
                raise _Unsupported()
        node = StrPred(pred, col, rhs, pred_id=len(self.str_preds))
        self.str_preds.append(node)
        return node

    def _inline_helper(self, name: str, args, env, state, depth: int = 0):
        """Boolean helper function -> disjunction of clause conjunctions.
        Every statement of every clause must compile (exactness under the
        possibility of negation is enforced by the caller chain)."""
        if depth > 4:
            raise _Unsupported()
        rules = self.cm.rules.get(name, [])
        arg_syms = [self._resolve(a, env, state) for a in args]
        disjuncts: List = []
        for r in rules:
            if not r.is_function or len(r.args or ()) != len(args):
                raise _Unsupported()
            if r.els is not None:
                # `else` is ordered choice, not disjunction; leave these
                # helpers to the interpreter.
                raise _Unsupported()
            if r.value is not None and not (
                isinstance(r.value, Scalar) and r.value.value is True
            ):
                raise _Unsupported()  # non-boolean helper
            env2: Dict[str, Any] = {}
            for p, s in zip(r.args, arg_syms):
                if isinstance(p, Var):
                    env2[p.name] = s
                else:
                    raise _Unsupported()  # literal-arg clauses unsupported
            conds: List = []
            state2 = dict(state)
            # helper-body assignment guards are DISJUNCT-scoped (a failing
            # helper body only falsifies its own disjunct, never the outer
            # clause) — collect them separately; the plan compiler accepts
            # only always-defined ones and otherwise sends the template to
            # the interpreter tier
            state2["guards"] = []
            for stmt in r.body:
                self._compile_stmt(stmt, env2, conds, state2, exact_required=True)
            # classify NOW, in the helper's own env: always-defined rhs
            # (literals, comprehension-derived sets/arrays) carry no
            # definedness risk and drop; anything else is recorded and
            # makes the template interpreter-tier for rendering
            for g in state2["guards"]:
                try:
                    gsym = self._resolve(g, env2, state2, allow_compr=True)
                except _Unsupported:
                    gsym = None
                if not isinstance(
                    gsym, (SConst, SKeySet, SParamIds, SSetDiff, SPredAny)
                ):
                    state["helper_guards"].append(g)
            if state2["slot"] != state["slot"]:
                # The helper clause opened its own iteration axis: reduce it
                # locally so sibling clauses stay resource-level (a pod with
                # hostNetwork but no containers must still violate).
                if state["slot"] is not None:
                    raise _Unsupported()  # would be a second axis
                from .vexpr import ReduceSlots

                disjuncts.append(ReduceSlots(tuple(conds), state2["slot"]))
                continue
            disjuncts.append(BoolOp("and", tuple(conds)) if conds else Const(True))
        if not disjuncts:
            raise _Unsupported()
        return BoolOp("or", tuple(disjuncts))

    # ---- operands ---------------------------------------------------------

    def _operand(self, sym, state):
        if isinstance(sym, SConst):
            return Lit(sym.value) if not isinstance(sym.value, str) else self._lit(sym.value)
        if isinstance(sym, SPath):
            if sym.root == "review":
                spec = ColumnSpec("scalar", (), tuple(sym.segs))
                self.columns[spec.key] = spec
                return ColRef(spec.key, slot=False)
            if sym.root == "params":
                self.param_scalars.add(sym.segs)
                return ParamRef(sym.segs)
            if isinstance(sym.root, tuple) and sym.root[0] == "slot":
                iter_paths = sym.root[1]
                spec = ColumnSpec("slot", iter_paths, tuple(sym.segs))
                self.columns[spec.key] = spec
                return ColRef(spec.key, slot=True)
        raise _Unsupported()

    def _lit(self, s: str):
        self.literals.add(s)
        return Lit(s)


def _flip(op: str) -> str:
    return {"<": ">", ">": "<", "<=": ">=", ">=": "<=", "==": "==", "!=": "!="}[op]


def _flip_unknown_defaults(node):
    """Under (odd-depth) negation, runtime-unknown comparison cells must
    resolve False so the negated result stays an over-approximation."""
    from dataclasses import replace

    if isinstance(node, Cmp):
        return replace(node, unknown_default=not node.unknown_default)
    from .vexpr import JoinCmp

    if isinstance(node, JoinCmp):
        return replace(node, unknown_default=not node.unknown_default)
    if isinstance(node, BoolOp):
        return BoolOp(node.op, tuple(_flip_unknown_defaults(c) for c in node.children))
    if isinstance(node, AnyParam):
        return AnyParam(node.ppath, tuple(_flip_unknown_defaults(c) for c in node.inner))
    from .vexpr import ReduceSlots

    if isinstance(node, ReduceSlots):
        return ReduceSlots(
            tuple(_flip_unknown_defaults(c) for c in node.inner), node.iter_key
        )
    return node


def vectorize(policy: TemplatePolicy) -> Optional[VProgram]:
    """Compile a template policy to a vectorized program, or None when
    nothing at all is recognizable (callers then use an all-true mask)."""
    try:
        return Vectorizer(policy).compile()
    except _Unsupported:
        return None
    except Exception:
        return None
