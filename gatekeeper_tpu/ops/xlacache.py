"""Persistent XLA compilation cache (SURVEY.md §5.4: device buffers and
executables are derived state; an on-disk compile cache is the one
optimization kept across restarts).

A gatekeeper restart rebuilds all engine state from the API server, but
the fused executables' XLA compiles dominate cold start (~20s+ for a
500-template corpus).  With the cache enabled, a restarted pod reloads
each executable from disk in milliseconds as long as its HLO is unchanged
(same template set/shapes/jax version)."""

from __future__ import annotations

import logging

log = logging.getLogger("gatekeeper.xlacache")

_enabled_dir = None
_listener_installed = False
_listener_failed = False  # logged-once guard for the absence warning


def _install_cache_listener():
    """Best-effort hit/miss counters for jax's persistent compile cache:
    jax emits monitoring events on every cache consult; mirror them into
    the metrics catalog's cache_requests_total counter and the compile
    telemetry (obs/compilestats.py cold-vs-warm provenance).

    Absence contract (ISSUE 13 satellite, per the PR 10 counted-drops
    discipline): on jax builds without the monitoring events this
    instrumentation used to vanish SILENTLY — an operator staring at a
    missing cache_requests_total{cache="xlacache"} row could not tell
    "no cache traffic" from "no counters".  Now the absence logs once at
    warning and exports ``xlacache_counters_available`` 0/1 either way."""
    global _listener_installed, _listener_failed
    if _listener_installed or _listener_failed:
        return
    from ..obs import compilestats

    try:
        from jax._src import monitoring

        from ..metrics.catalog import record_cache

        def _on_event(event, **_kw):
            if event == "/jax/compilation_cache/cache_hits":
                record_cache("xlacache", True)
                compilestats.get_stats().note_xla_event(True)
            elif event == "/jax/compilation_cache/cache_misses":
                record_cache("xlacache", False)
                compilestats.get_stats().note_xla_event(False)

        monitoring.register_event_listener(_on_event)
        _listener_installed = True
        compilestats.get_stats().set_xla_counters_available(True)
    except Exception:
        _listener_failed = True
        # logged ONCE (the guard above keeps re-enables out) and
        # exported: cache hit/miss telemetry is absent on this build,
        # and compile provenance degrades to "unknown"
        log.warning(
            "jax persistent-cache monitoring events unavailable: "
            "cache_requests_total{cache=\"xlacache\"} will not be "
            "recorded and compile provenance degrades to 'unknown' "
            "(xlacache_counters_available=0)", exc_info=True,
        )
        compilestats.get_stats().set_xla_counters_available(False)


def enable(cache_dir: str) -> bool:
    """Idempotently point jax's persistent compilation cache at cache_dir.
    Returns False (with a log line) when the running jax lacks support."""
    global _enabled_dir
    if not cache_dir or _enabled_dir == cache_dir:
        return _enabled_dir is not None
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:
        log.exception("persistent XLA cache unavailable")
        return False
    _enabled_dir = cache_dir
    _install_cache_listener()
    # best-effort: cache every executable (the fused policy programs are
    # small by XLA standards but expensive to rebuild behind a network
    # relay); absent knobs on older jax leave the dir active with defaults
    for knob, val in (
        ("jax_persistent_cache_min_entry_size_bytes", 0),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:
            log.warning("xla cache knob %s unavailable; using jax default", knob)
    log.info("persistent XLA compilation cache at %s", cache_dir)
    return True
