"""Columnar feature extraction: JSON resources -> padded numpy arrays.

Column kinds:
- scalar: one value per resource at a []-free path                   -> [R]
- slot:   per-entity values, where entities come from iteration
          paths (arrays, flattened across all [] levels and unioned
          over paths — e.g. containers[] + initContainers[]) and the
          value is read at a []-free path relative to the entity.
          All slot columns sharing the same iteration paths are
          ALIGNED on the slot axis                                   -> [R, S]
- keyset: the set of (truthy) object keys found at paths (arrays
          allowed), minus excluded literals, per resource            -> [R, K]

Scalar/slot columns carry a type code per cell plus the representation
arrays the predicates need:

  tcode: 0 undefined, 1 null, 2 false, 3 true, 4 number, 5 string, 6 composite
  sid:   interned string id (tcode 5)
  num:   float value (tcode 4)

Rego statement truthiness == tcode not in {0, 2}; OPA's cross-type ordering
(null < bool < number < string < composites) maps to tcode rank for exact
vectorized comparisons.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from .interning import Interner

Path = Tuple[str, ...]

T_UNDEF, T_NULL, T_FALSE, T_TRUE, T_NUM, T_STR, T_COMP = range(7)


def parse_path(dotted: str) -> Path:
    """'spec.containers[].image' -> ('spec', 'containers', '[]', 'image')."""
    out: List[str] = []
    for seg in dotted.split("."):
        while seg.endswith("[]"):
            seg = seg[:-2]
            if seg:
                out.append(seg)
            out.append("[]")
            seg = ""
        if seg:
            out.append(seg)
    return tuple(out)


def _walk(obj: Any, path: Path, i: int, out: List[Any]):
    if i == len(path):
        out.append(obj)
        return
    seg = path[i]
    if seg == "[]":
        if isinstance(obj, list):
            for item in obj:
                _walk(item, path, i + 1, out)
        return
    if isinstance(obj, dict) and seg in obj:
        _walk(obj[seg], path, i + 1, out)


def _get_rel(obj: Any, path: Path):
    """[]-free relative path; returns _ABSENT when missing."""
    cur = obj
    for seg in path:
        if isinstance(cur, dict) and seg in cur:
            cur = cur[seg]
        else:
            return _ABSENT
    return cur


class _Absent:
    def __repr__(self):
        return "<absent>"


_ABSENT = _Absent()


@dataclass(frozen=True)
class ColumnSpec:
    kind: str  # "scalar" | "slot" | "keyset" | "joinkey"
    iter_paths: Tuple[Path, ...]  # slot/keyset entity sources ([] allowed)
    rel_path: Path = ()  # []-free value path (scalar: the full path)
    exclude: Tuple[str, ...] = ()  # keyset: excluded key literals

    @property
    def key(self):
        return (self.kind, self.iter_paths, self.rel_path, self.exclude)

    @property
    def iter_key(self):
        """Slot-axis alignment group."""
        return self.iter_paths


def _bucket(n: int, minimum: int = 1) -> int:
    b = max(minimum, 1)
    while b < n:
        b *= 2
    return b


def _encode(values: List[Any], interner: Interner, shape) -> Dict[str, np.ndarray]:
    n = len(values)
    tcode = np.zeros(n, np.int8)
    sid = np.full(n, Interner.MISSING, np.int32)
    num = np.zeros(n, np.float64)
    for i, v in enumerate(values):
        if v is _ABSENT:
            tcode[i] = T_UNDEF
        elif v is None:
            tcode[i] = T_NULL
        elif v is True:
            tcode[i] = T_TRUE
        elif v is False:
            tcode[i] = T_FALSE
        elif isinstance(v, str):
            tcode[i] = T_STR
            sid[i] = interner.intern(v)
        elif isinstance(v, (int, float)):
            tcode[i] = T_NUM
            num[i] = float(v)
        else:
            tcode[i] = T_COMP
    return {
        "tcode": tcode.reshape(shape),
        "sid": sid.reshape(shape),
        "num": num.reshape(shape),
    }


def _extract_joinkey(
    resources, spec: "ColumnSpec", interner: Interner, rows: int
) -> Dict[str, np.ndarray]:
    """Cross-resource join-key column (ops/joinkernel.py): values at the
    spec's path, NORMALIZED through the one type-tagged key form
    (normalize_join_key) and interned — so an int label value and its
    string twin can never coerce into one key group.  Scalar keys ->
    {"sid" [R]}; slot keys (iteration paths) -> {"sid", "mask"} [R, S]
    with the slot width bucketed exactly like slot columns over the same
    iteration group (shared axes stay aligned)."""
    from .joinkernel import UNKNOWN_KEY, intern_join_key

    if not spec.iter_paths:  # scalar key
        sid = np.full(rows, Interner.MISSING, np.int32)
        for i, r in enumerate(resources):
            hits: List[Any] = []
            _walk(r, spec.rel_path, 0, hits)
            if hits:
                sid[i] = intern_join_key(hits[0], interner)
        return {"sid": sid}
    ents: List[List[Any]] = []
    for r in resources:
        hits: List[Any] = []
        for p in spec.iter_paths:
            _walk(r, p, 0, hits)
        ents.append(hits)
    width = _bucket(max((len(e) for e in ents), default=0), 1)
    sid = np.full((rows, width), Interner.MISSING, np.int32)
    mask = np.zeros((rows, width), bool)
    for i, row_ents in enumerate(ents):
        for j, ent in enumerate(row_ents):
            mask[i, j] = True
            v = _get_rel(ent, spec.rel_path)
            if v is not _ABSENT:
                sid[i, j] = intern_join_key(v, interner)
    return {"sid": sid, "mask": mask}


def _extract_columns_native(
    native, resources, specs, interner, rows
) -> Dict[Tuple, Dict[str, np.ndarray]]:
    """C++ extraction (same layout/semantics as the Python body below;
    differentially tested in tests/test_native.py)."""
    out: Dict[Tuple, Dict[str, np.ndarray]] = {}
    resources = list(resources)
    n = len(resources)
    ids, strings = interner._ids, interner._strings

    slot_groups: Dict[Tuple, List[ColumnSpec]] = {}
    for spec in specs:
        if spec.kind == "slot":
            slot_groups.setdefault(spec.iter_key, []).append(spec)
    group_entities: Dict[Tuple, list] = {}
    group_width: Dict[Tuple, int] = {}
    for ik in slot_groups:
        ents, maxw = native.slot_entities(resources, tuple(ik))
        group_entities[ik] = ents
        group_width[ik] = _bucket(maxw, 1)

    for spec in specs:
        if spec.kind == "scalar":
            tcode = np.zeros(rows, np.int8)
            sid = np.full(rows, Interner.MISSING, np.int32)
            num = np.zeros(rows, np.float64)
            native.extract_scalar(
                resources, spec.rel_path, tcode, sid, num, ids, strings
            )
            out[spec.key] = {"tcode": tcode, "sid": sid, "num": num}
        elif spec.kind == "slot":
            width = group_width[spec.iter_key]
            tcode = np.zeros((rows, width), np.int8)
            sid = np.full((rows, width), Interner.MISSING, np.int32)
            num = np.zeros((rows, width), np.float64)
            mask = np.zeros((rows, width), bool)
            native.encode_slots(
                group_entities[spec.iter_key], spec.rel_path, width,
                tcode, sid, num, mask, ids, strings,
            )
            out[spec.key] = {"tcode": tcode, "sid": sid, "num": num,
                             "mask": mask}
        elif spec.kind == "keyset":
            flat, counts = native.keyset(
                resources, tuple(spec.iter_paths), spec.rel_path,
                tuple(spec.exclude), ids, strings,
            )
            width = _bucket(int(counts.max()) if n else 0, 1)
            arr = np.full((rows, width), Interner.PAD, np.int32)
            if len(flat):
                starts = np.cumsum(counts) - counts
                rows_idx = np.repeat(np.arange(n), counts)
                cols_idx = np.arange(len(flat)) - np.repeat(starts, counts)
                arr[rows_idx, cols_idx] = flat
            out[spec.key] = {"ids": arr}
        elif spec.kind == "joinkey":
            # normalized-key extraction stays host-Python on the native
            # path too: the normalization contract lives in ONE place
            # (joinkernel.normalize_join_key), and join columns are a
            # small fraction of a referential corpus's column set
            out[spec.key] = _extract_joinkey(resources, spec, interner, rows)
        else:
            raise ValueError(f"unknown column kind {spec.kind}")
    return out


def extract_columns(
    resources: Sequence[dict],
    specs: Sequence[ColumnSpec],
    interner: Interner,
    rows: int,
) -> Dict[Tuple, Dict[str, np.ndarray]]:
    """Extract requested columns over `resources`, padded to `rows` rows.
    Slot columns in the same iter group share entity extraction and width."""
    from ..native import load as _load_native

    native = _load_native()
    if native is not None:
        return _extract_columns_native(
            native, resources, specs, interner, rows
        )

    out: Dict[Tuple, Dict[str, np.ndarray]] = {}

    # Group slot specs by iteration source so their slot axes align.
    slot_groups: Dict[Tuple, List[ColumnSpec]] = {}
    for spec in specs:
        if spec.kind == "slot":
            slot_groups.setdefault(spec.iter_key, []).append(spec)

    group_entities: Dict[Tuple, List[List[Any]]] = {}
    group_width: Dict[Tuple, int] = {}
    for ik in slot_groups:
        ents: List[List[Any]] = []
        for r in resources:
            hits: List[Any] = []
            for p in ik:
                _walk(r, p, 0, hits)
            ents.append(hits)
        group_entities[ik] = ents
        group_width[ik] = _bucket(max((len(e) for e in ents), default=0), 1)

    for spec in specs:
        if spec.kind == "scalar":
            values = []
            for r in resources:
                hits: List[Any] = []
                _walk(r, spec.rel_path, 0, hits)
                values.append(hits[0] if hits else _ABSENT)
            values += [_ABSENT] * (rows - len(resources))
            out[spec.key] = _encode(values, interner, (rows,))
        elif spec.kind == "slot":
            ik = spec.iter_key
            ents = group_entities[ik]
            width = group_width[ik]
            mask = np.zeros((rows, width), bool)
            values = []
            for i in range(rows):
                row_ents = ents[i] if i < len(ents) else []
                for j in range(width):
                    if j < len(row_ents):
                        mask[i, j] = True
                        values.append(_get_rel(row_ents[j], spec.rel_path))
                    else:
                        values.append(_ABSENT)
            arrs = _encode(values, interner, (rows, width))
            arrs["mask"] = mask
            out[spec.key] = arrs
        elif spec.kind == "keyset":
            per_row_keys: List[List[int]] = []
            for r in resources:
                hits = []
                for p in spec.iter_paths:
                    _walk(r, p, 0, hits)
                keys: List[int] = []
                seen = set()
                for h in hits:
                    target = _get_rel(h, spec.rel_path) if spec.rel_path else h
                    if isinstance(target, dict):
                        for k, v in target.items():
                            # key enumeration is a body statement: a
                            # false-valued key fails it and is excluded
                            if (
                                isinstance(k, str)
                                and v is not False
                                and k not in spec.exclude
                                and k not in seen
                            ):
                                seen.add(k)
                                keys.append(interner.intern(k))
                per_row_keys.append(keys)
            width = _bucket(max((len(k) for k in per_row_keys), default=0), 1)
            ids = np.full((rows, width), Interner.PAD, np.int32)
            for i, keys in enumerate(per_row_keys):
                ids[i, : len(keys)] = keys
            out[spec.key] = {"ids": ids}
        elif spec.kind == "joinkey":
            out[spec.key] = _extract_joinkey(resources, spec, interner, rows)
        else:
            raise ValueError(f"unknown column kind {spec.kind}")
    return out
