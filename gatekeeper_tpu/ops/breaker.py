"""Circuit breaker for the TPU evaluation backend.

Wraps the driver's compile/dispatch seams: after `failure_threshold`
CONSECUTIVE backend failures the breaker trips OPEN and the driver serves
every evaluation from the inherited interpreter tier (semantically
identical — the device mask is only ever a pruning over-approximation of
the interpreter walk).  While open, a background probe thread re-tries a
tiny real dispatch on a fixed cadence (half-open); one probe success
closes the breaker and evaluation returns to the device.  Without a
probe_fn the breaker degrades to lazy half-open: after `cooldown_s` the
next real call is admitted as the trial.

State is exported through `status()` (driver -> metrics catalog + the
webhook health endpoint): state, trip count, consecutive failures, and
cumulative seconds spent degraded.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 3,
        cooldown_s: float = 5.0,
        probe_fn: Optional[Callable[[], None]] = None,
        probe_interval_s: Optional[float] = None,
        on_transition: Optional[Callable[[str, str], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = cooldown_s
        self.probe_fn = probe_fn
        self.probe_interval_s = (
            probe_interval_s if probe_interval_s is not None else cooldown_s
        )
        self.on_transition = on_transition
        self._clock = clock
        # RLock: transition hooks run under the lock and may read status()
        self._lock = threading.RLock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._trips = 0
        # _opened_at paces the cooldown (reset on every re-open);
        # _degraded_since anchors the degraded-time metric (set once on
        # leaving CLOSED, cleared only on return to CLOSED) — a failed
        # half-open trial must NOT zero accumulated degradation
        self._opened_at: Optional[float] = None
        self._degraded_since: Optional[float] = None
        self._degraded_s = 0.0  # cumulative, completed degraded intervals
        self._trial_inflight = False
        self._last_error: Optional[str] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_wake = threading.Event()
        # True once the probe thread has DECIDED to exit (set under the
        # lock): a trip landing between that decision and the thread's
        # return must start a fresh thread, not signal a dying one
        self._probe_exiting = True

    # ---- state machine -----------------------------------------------------

    def _set_state(self, new: str):
        """Caller holds the lock."""
        old = self._state
        if old == new:
            return
        now = self._clock()
        if old == CLOSED:
            self._opened_at = now
            self._degraded_since = now
        if new == CLOSED:
            if self._degraded_since is not None:
                self._degraded_s += now - self._degraded_since
            self._opened_at = None
            self._degraded_since = None
        self._state = new
        self._notify(old, new)

    def allow(self) -> bool:
        """May the caller attempt a device operation right now?"""
        with self._lock:
            if self._state == CLOSED:
                return True
            if (
                self._state == OPEN
                and self.probe_fn is None
                and self._opened_at is not None
                and self._clock() - self._opened_at >= self.cooldown_s
            ):
                # lazy half-open: no background prober, so real traffic
                # supplies the trial call
                self._set_state(HALF_OPEN)
            if self._state == HALF_OPEN and not self._trial_inflight:
                self._trial_inflight = True
                return True
            return False

    def record_success(self):
        with self._lock:
            self._trial_inflight = False
            self._consecutive_failures = 0
            self._last_error = None
            if self._state != CLOSED:
                self._set_state(CLOSED)

    def record_failure(self, error: Optional[BaseException] = None):
        with self._lock:
            self._trial_inflight = False
            self._consecutive_failures += 1
            if error is not None:
                self._last_error = f"{type(error).__name__}: {error}"
            if self._state == HALF_OPEN:
                # failed trial: back to open, restarting the COOLDOWN
                # clock only — _degraded_since keeps the original anchor
                # (degraded-seconds spans the whole outage) and _trips is
                # NOT incremented (trips count closed->open transitions,
                # i.e. distinct incidents, not failed recovery probes)
                self._state = OPEN
                self._opened_at = self._clock()
                self._notify(HALF_OPEN, OPEN)
            elif (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._trips += 1
                self._set_state(OPEN)
                self._start_probe_locked()

    def _notify(self, old: str, new: str):
        hook = self.on_transition
        if hook is not None:
            try:
                hook(old, new)
            except Exception:
                # a transition-hook defect must not wedge the breaker's
                # state machine, but losing a degradation signal (mesh
                # re-shard, SLO trip) silently would be worse — log it;
                # transitions are rare so this cannot spam
                import logging

                logging.getLogger("gatekeeper.breaker").warning(
                    "breaker transition hook failed (%s -> %s)", old, new,
                    exc_info=True,
                )

    def trip(self):
        """Force the breaker open (tests / admin)."""
        with self._lock:
            if self._state == CLOSED:
                self._trips += 1
                self._set_state(OPEN)
                self._start_probe_locked()

    # ---- recovery probes ---------------------------------------------------

    def _start_probe_locked(self):
        if self.probe_fn is None:
            return
        t = self._probe_thread
        if t is not None and t.is_alive() and not self._probe_exiting:
            self._probe_wake.set()
            return
        self._probe_exiting = False
        self._probe_wake.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="tpu-breaker-probe", daemon=True
        )
        self._probe_thread.start()

    def _probe_loop(self):
        """Half-open recovery on a background cadence: the thread lives
        only while the breaker is degraded.  The exit decision is made
        ONLY at the top of the loop under the lock (setting
        _probe_exiting in the same critical section), so a trip racing a
        successful probe either reaches this still-live thread or sees
        _probe_exiting and starts a fresh one — never neither."""
        while True:
            self._probe_wake.wait(self.probe_interval_s)
            self._probe_wake.clear()
            with self._lock:
                if self._state == CLOSED:
                    self._probe_exiting = True
                    return
                # refresh transition-hook consumers (metrics gauges) while
                # the outage lasts: degraded_seconds would otherwise stay
                # frozen at its trip-time value for the whole outage
                self._notify(self._state, self._state)
                if (
                    self._opened_at is not None
                    and self._clock() - self._opened_at < self.cooldown_s
                ):
                    continue
                self._set_state(HALF_OPEN)
                self._trial_inflight = True
            try:
                self.probe_fn()
            except Exception as e:
                self.record_failure(e)
            else:
                self.record_success()
                # loop once more: the CLOSED check above decides exit
                # under the lock, so a trip landing right now is not
                # orphaned

    def probe_now(self) -> bool:
        """Run one synchronous recovery probe (deterministic tests).
        Returns True when the probe closed the breaker."""
        if self.probe_fn is None:
            return False
        with self._lock:
            if self._state == CLOSED:
                return True
            self._set_state(HALF_OPEN)
            self._trial_inflight = True
        try:
            self.probe_fn()
        except Exception as e:
            self.record_failure(e)
            return False
        self.record_success()
        return True

    # ---- introspection -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def trips(self) -> int:
        with self._lock:
            return self._trips

    def status(self) -> dict:
        with self._lock:
            degraded = self._degraded_s
            if self._degraded_since is not None:
                degraded += self._clock() - self._degraded_since
            return {
                "state": self._state,
                "state_code": STATE_CODES[self._state],
                "trips": self._trips,
                "consecutive_failures": self._consecutive_failures,
                "degraded_seconds": round(degraded, 6),
                "last_error": self._last_error,
            }
