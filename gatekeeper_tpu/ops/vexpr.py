"""VExpr: the vectorized predicate IR for violation rules.

A template's violation rules compile (ops/vectorizer.py) into a VProgram:
clauses OR-ed over [C, R] (C constraints of the template's kind, R
resources), each clause an AND of conditions, optionally reduced over a slot
axis S (one flattened array-iteration) and/or a constraint-parameter axis P.

Soundness contract: a program may OVER-approximate the true violation
predicate (false positives are filtered by the host-side interpreter render)
but must never under-approximate.  Conditions whose exact value cannot be
computed on device resolve to a compile-time `unknown_default` chosen by
polarity: True in positive positions, False under negation.

Cross-type comparisons follow OPA's total order via type-code ranks
(null < bool < number < string < composites), making </==/etc exact for
every case the corpus produces.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .columns import T_COMP, T_FALSE, T_NUM, T_STR, T_TRUE, T_UNDEF

# ---- operands -------------------------------------------------------------


@dataclass(frozen=True)
class ColRef:
    """A scalar [R] or slot [R, S] column."""

    colkey: Tuple
    slot: bool


@dataclass(frozen=True)
class ParamRef:
    """Per-constraint scalar parameter [C]."""

    ppath: Tuple[str, ...]


@dataclass(frozen=True)
class ParamElemRef:
    """Per-element field of the active AnyParam axis [C, P]."""

    ppath: Tuple[str, ...]
    subpath: Tuple[str, ...] = ()


@dataclass(frozen=True)
class Lit:
    value: Any


Operand = Union[ColRef, ParamRef, ParamElemRef, Lit]

# ---- nodes ----------------------------------------------------------------


@dataclass(frozen=True)
class Const:
    value: bool


@dataclass(frozen=True)
class Truthy:
    operand: Operand
    negate: bool = False


@dataclass(frozen=True)
class Cmp:
    op: str  # == != < <= > >=
    lhs: Operand
    rhs: Operand
    unknown_default: bool = True


@dataclass(frozen=True)
class StrPred:
    """pred(col_string, rhs_string): startswith/endswith/contains/re_match
    (re_match: rhs is the pattern).  Evaluated via host-precomputed lookup
    tables over the vocabulary; `pred_id` keys the table set in the env."""

    pred: str
    operand: Operand  # the string column tested
    rhs: Operand  # ParamRef/ParamElemRef/Lit supplying the pattern/affix
    pred_id: int = -1
    negate: bool = False


@dataclass(frozen=True)
class AnyParam:
    """Exists over a parameter-array axis."""

    ppath: Tuple[str, ...]
    inner: Tuple[Any, ...]  # conjunction over [C, P(, R, S)]


@dataclass(frozen=True)
class SetCountCmp:
    """count(left - right) <op> n over id sets."""

    left: Tuple[str, Any]  # ("keyset", colkey) | ("paramids", ppath)
    right: Tuple[str, Any]
    op: str
    n: int


@dataclass(frozen=True)
class JoinCmp:
    """Cross-resource aggregate comparison (ops/joinkernel.py): the
    distinct-provider-row count at the row's interned join key, compared
    to ``rhs`` under the engine's exact total order.  ``plan_id`` indexes
    the program's ``join_plans``.  Without a join binding on the EvalEnv
    (the admission/review path and the numpy host tier, where no global
    inventory is resident) the node resolves to ``unknown_default`` —
    over-approximation, filtered by the interpreter render."""

    plan_id: int
    op: str  # == != < <= > >=
    rhs: Operand
    slot: bool = False
    unknown_default: bool = True
    # duplicate detection: subtract the local row's OWN provider
    # contribution (1 when the row participates in the aggregate) so
    # "another object has my key" is exact whether or not the evaluated
    # row is itself a provider — requires local and remote key columns
    # to coincide (enforced by the classifier)
    exclude_self: bool = False


@dataclass(frozen=True)
class AnySlots:
    inner: Tuple[Any, ...]  # conjunction, may reference slot columns


@dataclass(frozen=True)
class ReduceSlots:
    """any over a slot axis of (inner conjunction & slot mask), producing a
    resource-level value — used when an inlined helper clause iterates an
    array while the enclosing violation clause does not."""

    inner: Tuple[Any, ...]
    iter_key: Tuple


@dataclass(frozen=True)
class BoolOp:
    """Generic combinators for inlined helper disjunctions: op in
    {'and', 'or', 'not'}.  'not' is STATEMENT negation: true when the child
    statement fails (false or undefined) — children already encode
    undefined-as-False, so plain logical negation is correct."""

    op: str
    children: Tuple[Any, ...]


VNode = Union[
    Const, Truthy, Cmp, StrPred, AnyParam, SetCountCmp, JoinCmp, AnySlots,
    BoolOp, ReduceSlots,
]


@dataclass
class Clause:
    conds: Tuple[VNode, ...]
    slot_iter: Optional[Tuple] = None  # iteration group key, if any


@dataclass
class VProgram:
    clauses: List[Clause]
    column_specs: List  # ColumnSpec list
    param_scalars: List[Tuple[str, ...]]
    param_arrays: List[Tuple[Tuple[str, ...], Tuple[Tuple[str, ...], ...]]]
    # (ppath, needed elem subpaths)
    str_preds: List[StrPred] = field(default_factory=list)
    literals: List[str] = field(default_factory=list)
    exact: bool = True
    # classified cross-resource aggregates (ops/joinkernel.py JoinPlan),
    # indexed by JoinCmp.plan_id; () for row-local programs
    join_plans: Tuple = ()
    # per-clause compiled violation-object (message) plans, parallel to
    # `clauses` (ops/renderplan.py); None entries render via the
    # interpreter.  Deliberately NOT part of structure_key: message
    # literals never affect the traced device computation.
    clause_plans: Optional[Tuple] = None

    def structure_key(self) -> str:
        """Template-clone batching key: programs with identical structure
        (same clauses/columns/param layout, parameters varying per
        constraint) evaluate together on one constraint axis — so N clones
        of a template family cost one traced subgraph, not N.  Memoized:
        the IR is immutable after vectorize()."""
        key = getattr(self, "_structure_key", None)
        if key is None:
            sig = (
                [(c.conds, c.slot_iter) for c in self.clauses],
                sorted(s.key for s in self.column_specs),
                self.param_scalars,
                self.param_arrays,
                self.literals,
            )
            if self.join_plans:
                # appended only when present so row-local programs keep
                # their pre-referential keys (warm AOT caches survive)
                sig = sig + (self.join_plans,)
            key = repr(sig)
            self._structure_key = key
        return key


# ---- evaluation -----------------------------------------------------------

_RANK = np.array([-1, 0, 1, 1, 2, 3, 4], np.int8)  # tcode -> OPA order rank


class EvalEnv:
    """Bound arrays for one (constraint batch, resource batch) evaluation.

    cols:    colkey -> {tcode[R(,S)], sid, num, mask?}
    params:  ppath -> {tcode[C], sid, num}
    elems:   (ppath, subpath) -> {tcode[C,P], sid, num, mask[C,P]}
    tables:  pred_id -> (table [U, vocab] uint8, idx [C] or [C, P])
    keysets: colkey -> ids [R, K]
    """

    def __init__(self, cols, params, elems, tables, keysets, C, R, xp=jnp):
        self.cols = cols
        self.params = params
        self.elems = elems
        self.tables = tables
        self.keysets = keysets
        self.C = C
        self.R = R
        # Array namespace: jnp under jit (the device path), numpy for the
        # host-serving path (ops/npside.py) — same IR, same semantics, no
        # trace/compile.  Everything below goes through env.xp.
        self.xp = xp
        # cross-resource join binding (ops/joinkernel.py JoinBinding);
        # None — the review/np paths — resolves every JoinCmp to its
        # polarity's unknown_default (sound over-approximation)
        self.joins = None


def _operand_arrays(op: Operand, env: EvalEnv, axes: str, pidx=None):
    """Return dict with tcode/sid/num arrays broadcast to `axes` layout
    ('CR' or 'CRS').  Inside an AnyParam unroll, `pidx` selects the current
    parameter element (ParamElemRef arrays are [C, P])."""
    xp = env.xp

    def shape_col(a, slot):
        x = xp.asarray(a)  # [R] or [R, S]
        if slot and not axes.endswith("S"):
            raise ValueError("slot column outside slot context")
        x = x[None]
        if not slot and axes.endswith("S"):
            x = x[..., None]
        return x

    if isinstance(op, ColRef):
        d = env.cols[op.colkey]
        return {k: shape_col(v, op.slot) for k, v in d.items() if k != "mask"}
    if isinstance(op, ParamRef):
        d = env.params[op.ppath]
        out = {}
        for k, v in d.items():
            x = xp.asarray(v)[..., None]  # [C, 1]
            if axes.endswith("S"):
                x = x[..., None]
            out[k] = x
        return out
    if isinstance(op, ParamElemRef):
        if pidx is None:
            raise ValueError("ParamElemRef outside AnyParam")
        d = env.elems[(op.ppath, op.subpath)]
        out = {}
        for k, v in d.items():
            if k == "mask":
                continue
            x = xp.asarray(v)[:, pidx][:, None]  # [C, 1]
            if axes.endswith("S"):
                x = x[..., None]
            out[k] = x
        return out
    if isinstance(op, Lit):
        v = op.value
        if isinstance(v, str):
            # literal string ids are interned at pack time into env.params
            # under the pseudo-path ("__lit__", v); [1]-shaped scalars
            d = env.params[("__lit__", v)]
            return {
                "tcode": xp.asarray(d["tcode"])[0],
                "sid": xp.asarray(d["sid"])[0],
                "num": xp.asarray(0.0),
            }
        if isinstance(v, bool):
            return {
                "tcode": xp.asarray(T_TRUE if v else T_FALSE, xp.int8),
                "sid": xp.asarray(-1, xp.int32),
                "num": xp.asarray(0.0),
            }
        if isinstance(v, (int, float)):
            return {
                "tcode": xp.asarray(T_NUM, xp.int8),
                "sid": xp.asarray(-1, xp.int32),
                "num": xp.asarray(float(v)),
            }
        raise ValueError(f"unsupported literal {v!r}")
    raise TypeError(op)


def _eval_node(node: VNode, env: EvalEnv, axes: str, pidx=None):
    xp = env.xp
    if isinstance(node, Const):
        return xp.asarray(node.value)
    if isinstance(node, Truthy):
        d = _operand_arrays(node.operand, env, axes, pidx)
        truthy = (d["tcode"] != T_UNDEF) & (d["tcode"] != T_FALSE)
        return ~truthy if node.negate else truthy
    if isinstance(node, Cmp):
        a = _operand_arrays(node.lhs, env, axes, pidx)
        b = _operand_arrays(node.rhs, env, axes, pidx)
        return _cmp_values(a, b, node.op, node.unknown_default, env.xp)
    if isinstance(node, StrPred):
        return _eval_strpred(node, env, axes, pidx)
    if isinstance(node, AnyParam):
        # unroll the parameter axis: peak transient stays at [C, R(, S)]
        mask = xp.asarray(env.elems[(node.ppath, ())]["mask"])  # [C, P]
        P = mask.shape[1]
        acc = None
        for p in range(P):
            m = mask[:, p][:, None]
            if axes.endswith("S"):
                m = m[..., None]
            part = m
            for n in node.inner:
                part = part & _eval_node(n, env, axes, pidx=p)
            acc = part if acc is None else (acc | part)
        return acc if acc is not None else xp.asarray(False)
    if isinstance(node, SetCountCmp):
        return _eval_setcount(node, env, axes)
    if isinstance(node, JoinCmp):
        return _eval_joincmp(node, env, axes, pidx)
    if isinstance(node, BoolOp):
        parts = [_eval_node(c, env, axes, pidx) for c in node.children]
        if node.op == "not":
            return ~parts[0]
        acc = parts[0]
        for p in parts[1:]:
            acc = (acc & p) if node.op == "and" else (acc | p)
        return acc
    if isinstance(node, ReduceSlots):
        if axes.endswith("S"):
            raise ValueError("nested slot reduction is not supported")
        mask = _slot_mask(env, node.iter_key)  # [R, S]
        acc = mask[None]
        for n in node.inner:
            acc = acc & _eval_node(n, env, axes + "S", pidx)
        return xp.any(acc, axis=-1)
    if isinstance(node, AnySlots):
        raise ValueError("AnySlots must be handled at clause level")
    raise TypeError(node)


def _cmp_values(a, b, op: str, unknown_default: bool, xp=jnp):
    ra = _RANK_LOOKUP(a["tcode"], xp)
    rb = _RANK_LOOKUP(b["tcode"], xp)
    defined = (a["tcode"] != T_UNDEF) & (b["tcode"] != T_UNDEF)
    both_comp = (a["tcode"] == T_COMP) & (b["tcode"] == T_COMP)

    same_rank = ra == rb
    # per-rank equality (composite unknown)
    eq_val = xp.where(
        a["tcode"] == T_NUM, a["num"] == b["num"],
        xp.where(
            a["tcode"] == T_STR, a["sid"] == b["sid"],
            a["tcode"] == b["tcode"],  # null/bools: tcode equality decides
        ),
    )
    eq = same_rank & eq_val & (a["tcode"] == b["tcode"])

    if op in ("==", "!="):
        res = eq if op == "==" else defined & ~eq
        return xp.where(both_comp, unknown_default, defined & res)

    # ordering: rank decides across types; within rank use value
    lt_val = xp.where(
        a["tcode"] == T_NUM, a["num"] < b["num"],
        xp.where(
            a["tcode"] == T_STR, xp.asarray(False),  # string<string: unknown
            (a["tcode"] == T_FALSE) & (b["tcode"] == T_TRUE),
        ),
    )
    lt = xp.where(same_rank, lt_val, ra < rb)
    unknown = both_comp | (same_rank & (a["tcode"] == T_STR))
    if op == "<":
        res = lt
    elif op == ">":
        res = ~lt & ~eq
    elif op == "<=":
        res = lt | eq
    else:  # >=
        res = ~lt
    return xp.where(unknown, unknown_default, defined & res)


def _RANK_LOOKUP(tcode, xp=jnp):
    return xp.asarray(_RANK)[xp.clip(tcode, 0, 6)]


def _eval_strpred(node: StrPred, env: EvalEnv, axes: str, pidx=None):
    xp = env.xp
    table, idx = env.tables[node.pred_id]  # [U, vocab], [C] or [C, P]
    d = _operand_arrays(node.operand, env, axes, pidx)
    sid = d["sid"]
    is_str = d["tcode"] == T_STR
    idx = xp.asarray(idx)
    if idx.ndim == 2:  # per param element
        if pidx is None:
            raise ValueError("per-element StrPred outside AnyParam")
        idx = idx[:, pidx]
    table = xp.asarray(table)
    U = table.shape[0]
    sidc = xp.clip(sid, 0, table.shape[1] - 1)
    if xp is np:
        # Host (numpy) mode: the batch is admission-sized, so the naive
        # broadcast gather is the fast form — no MXU to feed, and the
        # einsum would pay a [C, U] one-hot materialization for nothing.
        idx_b = idx[:, None]
        if axes.endswith("S"):
            idx_b = idx_b[..., None]
        hit = table[idx_b, sidc] != 0
    elif sid.shape[0] == 1:
        # Review-side operand ([1, R(,S)] — the hot case): two-stage
        # lookup shaped for the TPU.  Gather CONTIGUOUS U-byte rows of
        # the transposed table per string id (a sublane gather), then
        # contract the constraint axis in with a one-hot int8 matmul on
        # the MXU.  The naive per-element form table[idx[c], sid[r]] is
        # B x R x S random byte reads — measured ~3s for one 128x131k
        # group, the whole full-resweep budget.
        rowhit = xp.swapaxes(table, 0, 1)[sidc[0]].astype(xp.int8)
        onehot = (idx[:, None] == xp.arange(U)[None, :]).astype(xp.int8)
        if rowhit.ndim == 3:  # [R, S, U]
            hit = xp.einsum(
                "cu,rsu->crs", onehot, rowhit,
                preferred_element_type=xp.int32,
            ) > 0
        else:  # [R, U]
            hit = xp.einsum(
                "cu,ru->cr", onehot, rowhit,
                preferred_element_type=xp.int32,
            ) > 0
    else:
        # constraint-side operand (tiny [C, 1(,1)]): plain gather
        idx_b = idx[:, None]
        if axes.endswith("S"):
            idx_b = idx_b[..., None]
        hit = table[idx_b, sidc] != 0
    res = is_str & (sid >= 0) & hit
    return ~res if node.negate else res


def _eval_setcount(node: SetCountCmp, env: EvalEnv, axes: str):
    xp = env.xp
    from .interning import Interner

    def side(ref):
        kind, key = ref
        if kind == "keyset":
            ids = xp.asarray(env.keysets[key])  # [R, K]
            return ids, ids != Interner.PAD, "R"
        # key is (ppath, subpath)
        ids = xp.asarray(env.elems[key]["sid"])  # [C, P]
        mask = xp.asarray(env.elems[key]["mask"])
        return ids, mask, "C"

    lids, lmask, lax = side(node.left)
    rids, rmask, rax = side(node.right)

    # Count elements of `left` missing from `right`, with the small static
    # widths (P param elements, K keyset slots) unrolled so transients stay
    # at [C, R].
    if lax == "C" and rax == "R":
        C, P = lids.shape
        R, K = rids.shape
        cnt = xp.zeros((C, R), xp.int32)
        for p in range(P):
            lid = lids[:, p][:, None]  # [C, 1]
            inr = xp.zeros((C, R), bool)
            for k in range(K):
                inr = inr | ((lid == rids[None, :, k]) & rmask[None, :, k])
            cnt = cnt + (lmask[:, p][:, None] & ~inr)
    elif lax == "R" and rax == "C":
        R, K = lids.shape
        C, P = rids.shape
        cnt = xp.zeros((C, R), xp.int32)
        for k in range(K):
            lid = lids[None, :, k]  # [1, R]
            inr = xp.zeros((C, R), bool)
            for p in range(P):
                inr = inr | ((lid == rids[:, p][:, None]) & rmask[:, p][:, None])
            cnt = cnt + (lmask[None, :, k] & ~inr)
    else:
        raise ValueError("unsupported SetCountCmp side combination")

    n = node.n
    return {
        ">": cnt > n, ">=": cnt >= n, "<": cnt < n,
        "<=": cnt <= n, "==": cnt == n, "!=": cnt != n,
    }[node.op]


def _eval_joincmp(node: JoinCmp, env: EvalEnv, axes: str, pidx=None):
    """Distinct-provider-rows-per-key aggregate vs ``rhs``: one table
    gather + the exact cross-type comparison.  Key-undefined cells
    (missing field) compare as undefined, exactly like the interpreter's
    failed assignment; UNKNOWN_KEY cells (unnormalizable values) resolve
    to the polarity default so the render filter decides."""
    xp = env.xp
    jb = env.joins
    if jb is None:
        return xp.asarray(node.unknown_default)
    from .joinkernel import UNKNOWN_KEY, lookup_counts

    plan = jb.plans[node.plan_id]
    uk, uc = jb.table(node.plan_id, env)
    sid = xp.asarray(env.cols[plan.local_colkey]["sid"])
    q = sid[None]  # [1, R] or [1, R, S]
    if not plan.local_slot and axes.endswith("S"):
        q = q[..., None]
    counts = lookup_counts(uk, uc, q, xp)
    if node.exclude_self:
        part = jb.self_mask(node.plan_id, env)  # [R] bool
        part = xp.where(part, 1, 0)[None]
        if axes.endswith("S"):
            part = part[..., None]
        counts = counts - part
    lhs = {
        "tcode": xp.where(q >= 0, T_NUM, T_UNDEF).astype(xp.int8),
        "sid": xp.full_like(q, -1),
        # float32 is exact for any row count this engine can pack; jnp
        # without x64 would noisily truncate an explicit float64 request
        "num": counts.astype(xp.float64 if xp is np else xp.float32),
    }
    rhs = _operand_arrays(node.rhs, env, axes, pidx)
    res = _cmp_values(lhs, rhs, node.op, node.unknown_default, xp)
    return xp.where(q == UNKNOWN_KEY, node.unknown_default, res)


def _slot_mask(env: EvalEnv, iter_key: Tuple):
    xp = env.xp
    for spec_key, arrs in env.cols.items():
        if "mask" in arrs and spec_key[1] == iter_key:
            return xp.asarray(arrs["mask"])
    raise ValueError("no slot column for iteration group")


def eval_program(prog: VProgram, env: EvalEnv):
    """-> bool[C, R]: OR over clauses."""
    xp = env.xp
    total = xp.zeros((env.C, env.R), bool)
    for clause in prog.clauses:
        r_conds: List = []
        s_conds: List = []
        for cond in clause.conds:
            if _clause_uses_slot(cond):
                s_conds.append(cond)
            else:
                r_conds.append(cond)
        acc = xp.ones((env.C, env.R), bool)
        for cond in r_conds:
            acc = acc & _eval_node(cond, env, "CR")
        if clause.slot_iter is not None:
            mask = _slot_mask(env, clause.slot_iter)
            sacc = mask[None, :, :]  # [1, R, S]
            for cond in s_conds:
                sacc = sacc & _eval_node(cond, env, "CRS")
            acc = acc & xp.any(sacc, axis=2)
        elif s_conds:
            raise ValueError("slot conditions without slot_iter")
        total = total | acc
    return total


def _clause_uses_slot(node: VNode) -> bool:
    if isinstance(node, JoinCmp):
        return node.slot
    if isinstance(node, Truthy):
        return isinstance(node.operand, ColRef) and node.operand.slot
    if isinstance(node, Cmp):
        return any(
            isinstance(o, ColRef) and o.slot for o in (node.lhs, node.rhs)
        )
    if isinstance(node, StrPred):
        return isinstance(node.operand, ColRef) and node.operand.slot
    if isinstance(node, AnyParam):
        return any(_clause_uses_slot(n) for n in node.inner)
    if isinstance(node, BoolOp):
        return any(_clause_uses_slot(n) for n in node.children)
    return False
