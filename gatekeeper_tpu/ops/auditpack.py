"""Incremental audit packing: the inventory as resident columnar arrays.

The production audit loop sweeps a mostly-unchanged inventory every interval
(reference pkg/audit/manager.go:406-431 re-lists everything; here the
replicated store IS the source).  Rebuilding reviews + packed tensors for
100k resources costs seconds; this cache keeps the packed row-major arrays
resident and applies only the store's change log per sweep:

  - one row per cached object, stable across sweeps (tombstoned on delete,
    reused from a free list)
  - per-row re-pack on object change (pack_reviews/extract_columns on a
    single review, written into the row slot with width growth as needed)
  - Namespace objects re-pack every row in that namespace: packed rows bake
    in namespaceSelector label resolution + autoreject against the cached
    Namespace (ops/pack.py ns_mode), and a stale row could UNDER-approximate
    the device mask, which the exactness filter cannot repair
  - wipes, subtree deletions, layout changes (new column specs) and
    change-log overruns fall back to a full rebuild

Array shapes are bucketed (powers of two) so the fused executable survives
row growth until a bucket boundary.  SURVEY.md section 7 stage 4:
"inventory store as columnar host arrays with incremental device updates".
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .columns import extract_columns
from .interning import Interner
from .pack import PAD, UNDEF, pack_reviews

# fill values per review-pack key: what an empty/padded row must contain
_RP_FILL = {
    "group": UNDEF,
    "kind": UNDEF,
    "ns_name": UNDEF,
    "ns_mode": 0,
    "always": False,
    "ns_empty": False,
    "is_ns": False,
    "obj_empty": True,
    "old_empty": True,
    "autoreject": False,
    "valid": False,
    "obj_labels": PAD,
    "old_labels": PAD,
    "ns_labels": PAD,
}

# fill values per column leaf (ops/columns.py encoding)
_COL_FILL = {
    "tcode": 0,  # T_UNDEF
    "sid": Interner.MISSING,
    "num": 0.0,
    "mask": False,
    "ids": Interner.PAD,
}

_NS_PATH_PREFIX = ("cluster", "v1", "Namespace")


def _bucket(n: int, minimum: int = 8) -> int:
    b = max(minimum, 1)
    while b < n:
        b *= 2
    return b


def _path_identity(seg: Tuple[str, ...]) -> Optional[Tuple[str, str, str, str]]:
    """(api, kind, name, namespace) for an object-depth path, else None."""
    if seg[0] == "cluster" and len(seg) == 4:
        return seg[1], seg[2], seg[3], ""
    if seg[0] == "namespace" and len(seg) == 5:
        return seg[2], seg[3], seg[4], seg[1]
    return None  # subtree-depth path: caller falls back to rebuild


class AuditPackCache:
    """Resident packed audit inputs, synced to an InventoryStore's change
    log.  All access happens under the owning driver's lock."""

    # beyond this many pending changes a batch rebuild is cheaper than
    # per-row packing (native batch pack is ~15us/row vs ~200us/row here)
    REBUILD_FRACTION = 8

    def __init__(self):
        self.synced_epoch = -1
        self.col_keys: Optional[tuple] = None
        self.reviews: List[Optional[dict]] = []
        self.row_of: Dict[Tuple[str, ...], int] = {}
        self.row_path: List[Optional[Tuple[str, ...]]] = []
        self.row_ns: List[str] = []
        self.row_gen: List[int] = []  # bumped per re-pack; memo invalidation
        self.ns_rows: Dict[str, set] = {}
        self.free: List[int] = []
        self.rp: Optional[Dict[str, np.ndarray]] = None
        self.cols: Optional[Dict[Tuple, Dict[str, np.ndarray]]] = None
        self.capacity = 0
        self.n_rows = 0
        self._gen = 0
        # device-residency bookkeeping (consumed by the driver): rows whose
        # packed contents changed since the last take_dirty(), and a layout
        # generation bumped whenever array identities/shapes change (rebuild,
        # capacity growth, width growth, new column leaf) — a layout bump
        # means per-row scatter updates can no longer patch the device copy
        # and a full re-upload is required.
        self.dirty: set = set()
        self.layout_gen = 0
        # bumped ONLY when row identities are reassigned (full rebuild /
        # snapshot adoption) — distinct from layout_gen, which also bumps
        # on capacity/width growth where row ids stay stable.  The join
        # index (ops/joinkernel.py JoinState) keys on this: across growth
        # it can diff old-vs-new key groups by row id; across a rebuild
        # it must start fresh (every row generation was reset anyway).
        self.rebuild_gen = 0
        # second dirty channel, drained by the incremental delta sweep
        # (ops/deltasweep.py) independently of the device-scatter channel
        # above, so neither consumer starves the other and the delta path
        # never rescans cumulative churn (advisor r3)
        self.delta_dirty: set = set()

    # ---- snapshot restore (gatekeeper_tpu/snapshot/) ----------------------

    def adopt_restored(self, rp, cols, col_keys, reviews, row_path, row_ns,
                       row_gen, free, n_rows, synced_epoch):
        """Install state deserialized from a snapshot (under the owning
        driver's lock).  Arrays arrive writable and exactly as a previous
        process's _rebuild/_pack_row left them; reviews and row
        generations are restored verbatim (generations key the render
        caches, so preserving them is what lets an unchanged constraint
        reuse its persisted rendered results).  layout_gen bumps so
        device copies re-place."""
        self.rp = rp
        self.cols = cols
        self.col_keys = col_keys
        self.capacity = len(next(iter(rp.values())))
        self.n_rows = n_rows
        self.reviews = list(reviews)
        self.row_path = [tuple(p) if p is not None else None for p in row_path]
        self.row_of = {
            p: i for i, p in enumerate(self.row_path) if p is not None
        }
        self.row_ns = list(row_ns)
        self.row_gen = [int(g) for g in row_gen]
        self._gen = max(self.row_gen, default=0)
        self.ns_rows = {}
        for i, ns in enumerate(self.row_ns):
            if ns:
                self.ns_rows.setdefault(ns, set()).add(i)
        self.free = list(free)
        self.synced_epoch = synced_epoch
        self.dirty = set()
        self.delta_dirty = set()
        self.layout_gen += 1
        self.rebuild_gen += 1

    def bump_row_gen(self, rows):
        """Invalidate the render-cache generations of `rows` WITHOUT
        marking them dirty: their packed content is unchanged (the device
        state is current), but something they render from — a join key
        group's aggregate — moved (ops/joinkernel.py)."""
        for r in rows:
            if 0 <= r < len(self.row_gen):
                self._gen += 1
                self.row_gen[r] = self._gen

    def take_dirty(self) -> set:
        d = self.dirty
        self.dirty = set()
        return d

    def take_delta_dirty(self) -> set:
        d = self.delta_dirty
        self.delta_dirty = set()
        return d

    # ---- public -----------------------------------------------------------

    def sync(self, driver, col_specs) -> bool:
        """Bring the resident arrays up to date with driver.store.  Returns
        True when anything changed (mask-level caches must invalidate)."""
        store = driver.store
        keys = tuple(sorted(s.key for s in col_specs))
        if self.rp is None or self.col_keys != keys:
            self._rebuild(driver, col_specs)
            self.col_keys = keys
            return True
        if store.epoch == self.synced_epoch:
            return False
        changes = store.changes_since(self.synced_epoch)
        if changes is None:
            self._rebuild(driver, col_specs)
            return True
        seen = set()
        ordered_changes = []
        for seg in reversed(changes):  # keep only the LAST change per path
            if seg is None or _path_identity(seg) is None:
                self._rebuild(driver, col_specs)
                return True
            if seg in seen:
                continue
            seen.add(seg)
            ordered_changes.append(seg)
        # threshold on UNIQUE paths (a flapping object logs many entries
        # for one row; the rebuild-vs-patch tradeoff is about rows touched)
        if len(ordered_changes) > max(
            1024, self.n_rows // self.REBUILD_FRACTION
        ):
            self._rebuild(driver, col_specs)
            return True
        ns_repack: set = set()
        for seg in reversed(ordered_changes):
            self._apply(driver, seg, col_specs)
            if seg[:3] == _NS_PATH_PREFIX:
                ns_repack.add(seg[3])
        for ns in ns_repack:
            for r in list(self.ns_rows.get(ns, ())):
                review = self.reviews[r]
                if review is not None:
                    self._pack_row(driver, r, review, col_specs)
        self.synced_epoch = store.epoch
        return True

    # ---- rebuild ----------------------------------------------------------

    def _rebuild(self, driver, col_specs):
        from ..engine.value import thaw

        store = driver.store
        objs = list(store.iter_objects())
        reviews = []
        paths = []
        for obj_frozen, api, kind, name, ns in objs:
            reviews.append(
                driver.target.make_audit_review(thaw(obj_frozen), api, kind, name, ns)
            )
            if ns:
                paths.append(("namespace", ns, api, kind, name))
            else:
                paths.append(("cluster", api, kind, name))
        rp = pack_reviews(reviews, driver.interner, store.cached_namespace)
        rows = len(rp.arrays["valid"])
        cols = extract_columns(reviews, col_specs, driver.interner, rows)
        self.rp = dict(rp.arrays)
        self.cols = {k: dict(v) for k, v in cols.items()}
        self.capacity = rows
        self.n_rows = len(reviews)
        self.reviews = list(reviews)
        self.row_path = list(paths)
        self.row_of = {p: i for i, p in enumerate(paths)}
        self.row_ns = [r.get("namespace", "") or "" for r in reviews]
        self._gen += 1
        self.row_gen = [self._gen] * len(reviews)
        self.ns_rows = {}
        for i, ns in enumerate(self.row_ns):
            if ns:
                self.ns_rows.setdefault(ns, set()).add(i)
        self.free = []
        self.synced_epoch = store.epoch
        self.dirty = set()
        self.delta_dirty = set()
        self.layout_gen += 1
        self.rebuild_gen += 1

    # ---- incremental ------------------------------------------------------

    def _apply(self, driver, seg: Tuple[str, ...], col_specs):
        from ..engine.value import thaw

        api, kind, name, ns = _path_identity(seg)
        obj = driver.store.get(seg)
        row = self.row_of.get(seg)
        if obj is None:
            if row is not None:
                self._tombstone(row, seg)
            return
        review = driver.target.make_audit_review(thaw(obj), api, kind, name, ns)
        if row is None:
            row = self._alloc_row()
            self.row_of[seg] = row
            self.row_path[row] = seg
        self.reviews[row] = review
        old_ns = self.row_ns[row]
        if old_ns and old_ns != ns:
            self.ns_rows.get(old_ns, set()).discard(row)
        self.row_ns[row] = ns
        if ns:
            self.ns_rows.setdefault(ns, set()).add(row)
        self._pack_row(driver, row, review, col_specs)

    def _tombstone(self, row: int, seg: Tuple[str, ...]):
        self.reviews[row] = None
        self.row_of.pop(seg, None)
        self.row_path[row] = None
        ns = self.row_ns[row]
        if ns:
            self.ns_rows.get(ns, set()).discard(row)
        self.row_ns[row] = ""
        self.rp["valid"][row] = False
        self._gen += 1
        self.row_gen[row] = self._gen
        self.dirty.add(row)
        self.delta_dirty.add(row)
        self.free.append(row)

    def _alloc_row(self) -> int:
        if self.free:
            return self.free.pop()
        if self.n_rows >= self.capacity:
            self._grow_rows(_bucket(self.n_rows + 1))
        r = self.n_rows
        self.n_rows += 1
        self.reviews.append(None)
        self.row_path.append(None)
        self.row_ns.append("")
        self.row_gen.append(0)
        return r

    def _grow_rows(self, new_capacity: int):
        def grow(arr: np.ndarray, fill):
            out = np.full((new_capacity,) + arr.shape[1:], fill, dtype=arr.dtype)
            out[: arr.shape[0]] = arr
            return out

        self.rp = {k: grow(v, _RP_FILL[k]) for k, v in self.rp.items()}
        self.cols = {
            ck: {leaf: grow(arr, _COL_FILL[leaf]) for leaf, arr in leaves.items()}
            for ck, leaves in self.cols.items()
        }
        self.capacity = new_capacity
        self.layout_gen += 1

    def _write_leaf(self, holder: dict, key, row: int, src: np.ndarray, fill):
        """Write one packed row into its slot, growing trailing (width)
        dims when this row exceeds them.  Rows are reset to the fill value
        first so narrower rows leave no stale tail."""
        dst = holder[key]
        if src.shape != dst.shape[1:]:
            target = tuple(
                max(a, b) for a, b in zip(dst.shape[1:], src.shape)
            )
            if target != dst.shape[1:]:
                grown = np.full((dst.shape[0],) + target, fill, dtype=dst.dtype)
                grown[tuple(slice(0, s) for s in dst.shape)] = dst
                holder[key] = grown
                dst = grown
                self.layout_gen += 1  # shape changed: device copy is stale
        dst[row] = fill
        if src.ndim:
            dst[(row,) + tuple(slice(0, s) for s in src.shape)] = src
        else:
            dst[row] = src

    def _pack_row(self, driver, row: int, review: dict, col_specs):
        rp1 = pack_reviews(
            [review], driver.interner, driver.store.cached_namespace,
            bucket_rows=False,
        )
        for key, arr in rp1.arrays.items():
            self._write_leaf(self.rp, key, row, arr[0], _RP_FILL[key])
        cols1 = extract_columns([review], col_specs, driver.interner, 1)
        for ckey, leaves in cols1.items():
            holder = self.cols.setdefault(ckey, {})
            for leaf, arr in leaves.items():
                if leaf not in holder:
                    holder[leaf] = np.full(
                        (self.capacity,) + arr.shape[1:],
                        _COL_FILL[leaf], dtype=arr.dtype,
                    )
                    self.layout_gen += 1  # new leaf: device tree is stale
                self._write_leaf(holder, leaf, row, arr[0], _COL_FILL[leaf])
        self._gen += 1
        self.row_gen[row] = self._gen
        self.dirty.add(row)
        self.delta_dirty.add(row)
