"""Referential policies: the cross-resource join/aggregate kernel subsystem.

Every workload before this module was row-local: a cell's verdict depended
only on (constraint, resource).  Gatekeeper's real capability surface also
includes constraints that need data *across* rows — unique ingress hosts,
required owner references, quota-by-label — which templates express by
iterating ``data.inventory``.  The interpreter answers those exactly but at
O(inventory) per evaluated cell, so a referential audit sweep is O(R^2).

This module keeps referential templates inside the vectorized sweep:

- ``classify_join_clause`` (called from ops/vectorizer.py) pattern-matches a
  violation clause against three referential plan families —
  duplicate-key detection (unique ingress host), existence-of-referenced-row
  (required storage class), and count/group-by vs a parameter quota — and
  compiles it to a :class:`JoinPlan` + a ``JoinCmp`` IR node
  (ops/vexpr.py) instead of bailing to the interpreter.
- all three families reduce to ONE aggregate: **distinct provider rows per
  interned join key**.  Key values are normalized type-tagged strings
  (:func:`normalize_join_key`) interned into the global vocabulary, so
  int-vs-str label values can never coerce into one group (the engine's
  ``values_equal`` is type-strict; the packed path must be too).
- device-side kernels build the per-key table inside the packed [C, R]
  sweep: in-row dedup of slot keys, a sort + segment-reduce group-by over
  the interned key column, and under a mesh a per-shard segment-reduce
  followed by an ``all_gather`` cross-shard merge (the [C, 1+K]-style
  reduce-then-merge idiom from parallel/mesh.py) for keys spanning shards.
  Verdicts are then one ``searchsorted`` gather + the engine's exact
  total-order comparison.
- :class:`JoinState` is the host-side join-group index (key -> provider
  rows, key -> reader rows) that gives the delta sweep O(churn) dispatch:
  a churned row invalidates only its key group (old keys + new keys), and
  only those readers re-evaluate / re-render.  The index is persisted in
  the snapshot sweep basis (gatekeeper_tpu/snapshot/) so warm restores
  keep the delta path; plan drift drops the basis for a rebase.

Soundness: a JoinCmp in the REVIEW path (admission batches — no inventory
on the device) resolves to its polarity's ``unknown_default`` and the
interpreter render filters, exactly like an unclassified template.  On the
AUDIT path the plan is exact modulo one documented corner (two inventory
objects of the same kind/namespace/name under different groupVersions count
as two provider rows where the reference's ``identical`` helper sees one) —
over-approximation only, filtered by the interpreter render.

Divergence assertion (GK_JOIN_ASSERT=1, disabled by GK_BUG_COMPAT=1): a
cell an exact join plan flagged whose interpreter render comes back empty
raises :class:`JoinDivergence` — the fuzz-oracle posture of docs/parity.md
applied to the referential tier.  See docs/referential.md.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .interning import Interner

#: in-trace sentinel for "no key at this position": sorts past every real
#: interned id, so sort-based kernels compact invalid entries to the tail
KEY_INVALID = np.int32(2**31 - 1)

#: packed-column sentinel for a PRESENT key value the normalizer cannot
#: represent faithfully (NaN-bearing values: NaN != NaN under values_equal,
#: but any table key would equal itself).  JoinCmp resolves these cells to
#: the polarity's unknown_default — over-approximation, interpreter-exact.
UNKNOWN_KEY = -5

#: minimum padded width of a (uk, uc) key table
TABLE_MIN = 8

# ONE power-of-two bucketing helper repo-wide: joinkey slot widths
# (columns.py) and delta-table widths must stay consistent with the
# executables' shape buckets, so they share the same implementation
from .columns import _bucket as _pow2_bucket  # noqa: E402


# ---------------------------------------------------------------------------
# Key normalization (the interned-key contract)
# ---------------------------------------------------------------------------


def normalize_join_key(v: Any) -> Optional[str]:
    """Canonical type-tagged string for a JSON value used as a join key,
    or None when the value cannot be normalized faithfully (NaN anywhere).

    Injective over the engine's ``values_equal`` equivalence classes:
    two values normalize to the same string iff the interpreter oracle
    would consider them equal — ``5`` and ``5.0`` share ``n:5`` (numbers
    compare by value), but ``5`` / ``"5"`` / ``true`` stay distinct
    (type-strict equality, engine/value.py).  The packed path and any
    host-side oracle twin MUST share this one function; a second
    normalization is how int-vs-str label coercion bugs are born."""
    if isinstance(v, str):
        return "s:" + v
    if isinstance(v, bool):
        return "b:1" if v else "b:0"
    if isinstance(v, (int, float)):
        if isinstance(v, float):
            if v != v:  # NaN: self-unequal, no faithful table key exists
                return None
            if v.is_integer():
                v = int(v)
        return "n:" + repr(v)
    if v is None:
        return "z:"
    # composite (dict/list): canonical JSON — sorted keys, no whitespace,
    # and NESTED numbers canonicalized like the scalar branch (the
    # interpreter pools {"a": 5} with {"a": 5.0}; json.dumps alone would
    # split them into two keys and the aggregate would UNDER-approximate).
    # allow_nan=False so a nested NaN degrades to UNKNOWN instead of
    # producing a self-equal key the oracle would never match.
    try:
        return "j:" + json.dumps(
            _canon_numbers(v), sort_keys=True, separators=(",", ":"),
            allow_nan=False,
        )
    except (TypeError, ValueError):
        return None


def _canon_numbers(v: Any):
    """Recursively collapse int-valued floats to ints (the engine's
    numeric equality classes) inside a composite key value."""
    if isinstance(v, bool):
        return v
    if isinstance(v, float) and v == v and v.is_integer():
        return int(v)
    if isinstance(v, list):
        return [_canon_numbers(x) for x in v]
    if isinstance(v, tuple):
        return [_canon_numbers(x) for x in v]
    if isinstance(v, dict):
        return {k: _canon_numbers(x) for k, x in v.items()}
    return v


def intern_join_key(v: Any, interner: Interner) -> int:
    """Packed-column id for one extracted key value (ops/columns.py)."""
    norm = normalize_join_key(v)
    if norm is None:
        return UNKNOWN_KEY
    return interner.intern(norm)


# ---------------------------------------------------------------------------
# Plans
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class JoinPlan:
    """One classified cross-resource aggregate.

    ``agg`` names the family for observability ('dup' | 'exists' |
    'count'); the aggregate itself is always *distinct provider rows per
    key*.  ``local_colkey`` / ``remote_colkey`` are joinkey
    ColumnSpec.key tuples (ops/columns.py); providers are the inventory
    rows of ``remote_kind`` in ``remote_scope`` ('namespace' | 'cluster')
    whose remote key column yields the key."""

    agg: str
    local_colkey: Tuple
    local_slot: bool
    remote_scope: str
    remote_kind: str
    remote_colkey: Tuple
    remote_slot: bool

    @property
    def sig(self) -> str:
        """Stable identity for snapshot drift checks and dedup."""
        return repr((
            self.agg, self.local_colkey, self.local_slot,
            self.remote_scope, self.remote_kind,
            self.remote_colkey, self.remote_slot,
        ))


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------


def _scatter_add(n: int, idx, w, xp):
    if xp is np:
        tot = np.zeros(n, np.int64)
        np.add.at(tot, idx, w)
        return tot
    import jax.numpy as jnp

    return jnp.zeros(n, jnp.int32).at[idx].add(w)


def compact_key_table(keys, weights, xp):
    """Sort + segment-reduce group-by: ``(keys [N], weights [N])`` ->
    ``(uk [N], uc [N])`` where uk holds each distinct valid key once
    (ascending, KEY_INVALID-padded tail) and uc its summed weight.

    The segment reduce is the classic sorted-run trick: sort, mark run
    starts, scatter-add weights per run id.  Shape-stable (no nonzero/
    compaction), so it traces once per column layout."""
    n = keys.shape[0]
    order = xp.argsort(keys)
    sk = keys[order]
    w = weights[order]
    first = xp.concatenate(
        [xp.ones(1, bool), sk[1:] != sk[:-1]]
    )
    run = xp.cumsum(first.astype(xp.int32)) - 1
    tot = _scatter_add(n, run, w, xp)
    valid = sk != KEY_INVALID
    uk = xp.where(first & valid, sk, KEY_INVALID)
    uc = xp.where(first & valid, tot[run], 0)
    o2 = xp.argsort(uk)
    return uk[o2], uc[o2].astype(xp.int32)


def row_distinct_slot_keys(sid, mask, xp):
    """[R, S] slot key ids + validity mask -> flat [R*S] keys with each
    row's duplicate keys collapsed to one entry (a row providing the same
    host twice is ONE provider for that host — the reference's
    ``identical`` self-exclusion is object-level, not entry-level)."""
    s = xp.where(mask, sid, KEY_INVALID)
    ss = xp.sort(s, axis=1)
    keep = xp.concatenate(
        [xp.ones((ss.shape[0], 1), bool), ss[:, 1:] != ss[:, :-1]],
        axis=1,
    )
    return xp.where(keep & (ss != KEY_INVALID), ss, KEY_INVALID).reshape(-1)


def provider_key_table(plan: JoinPlan, kind_id, rv, cols, xp,
                       axis_name: Optional[str] = None):
    """The per-key distinct-provider-row table, computed INSIDE the packed
    sweep from the resident columns.  Single device: one segment-reduce
    over the full row axis.  Mesh (``axis_name`` set): each shard
    segment-reduces its own row slab to a compact (keys, counts) table,
    then an ``all_gather`` + second segment-reduce merges the per-shard
    tables — counts for keys spanning shards sum exactly, so the merged
    table is bit-identical at every width."""
    valid = xp.asarray(rv["valid"])
    part = valid & (xp.asarray(rv["kind"]) == kind_id)
    ns_empty = xp.asarray(rv["ns_empty"])
    if plan.remote_scope == "namespace":
        part = part & ~ns_empty
    else:
        part = part & ns_empty
    rcol = cols[plan.remote_colkey]
    sid = xp.asarray(rcol["sid"])
    if plan.remote_slot:
        ok = xp.asarray(rcol["mask"]) & (sid >= 0) & part[:, None]
        flat = row_distinct_slot_keys(sid, ok, xp)
    else:
        flat = xp.where(part & (sid >= 0), sid, KEY_INVALID)
    uk, uc = compact_key_table(
        flat, (flat != KEY_INVALID).astype(xp.int32), xp
    )
    if axis_name is not None:
        from jax import lax

        ku = lax.all_gather(uk, axis_name).reshape(-1)
        cu = lax.all_gather(uc, axis_name).reshape(-1)
        uk, uc = compact_key_table(ku, cu, xp)
    return uk, uc


def lookup_counts(uk, uc, q, xp):
    """Gather per-key counts at query ids ``q`` (any shape): one
    ``searchsorted`` into the compact table; absent or invalid keys
    answer 0."""
    n = uk.shape[0]
    i = xp.clip(xp.searchsorted(uk, q), 0, n - 1)
    found = (uk[i] == q) & (q >= 0)
    return xp.where(found, uc[i], 0)


class JoinBinding:
    """Per-evaluation join context attached to an EvalEnv (vexpr).

    mode 'trace':  tables are computed in-trace from the resident columns
                   (full audit sweeps; ``plan_args[i]`` carries the
                   runtime ``kind_id`` scalar so interner ids are never
                   baked into a cached executable).
    mode 'tables': tables arrive as runtime arrays (delta sweeps — the
                   dispatched rows are a churn slice, so the global
                   aggregate must come from the host join index).
    ``cache`` is shared across the sweep's program groups: 500 template
    clones of one referential family cost ONE table build."""

    __slots__ = ("mode", "plans", "plan_args", "rv", "axis_name", "cache")

    def __init__(self, mode: str, plans, plan_args, rv=None,
                 axis_name: Optional[str] = None, cache: Optional[dict] = None):
        self.mode = mode
        self.plans = plans
        self.plan_args = plan_args
        self.rv = rv
        self.axis_name = axis_name
        self.cache = cache if cache is not None else {}

    def table(self, plan_id: int, env):
        plan = self.plans[plan_id]
        hit = self.cache.get(plan)
        if hit is None:
            xp = env.xp
            arg = self.plan_args[plan_id]
            if self.mode == "tables":
                hit = (xp.asarray(arg["uk"]), xp.asarray(arg["uc"]))
            else:
                hit = provider_key_table(
                    plan, xp.asarray(arg["kind_id"]), self.rv, env.cols,
                    xp, axis_name=self.axis_name,
                )
            self.cache[plan] = hit
        return hit

    def self_mask(self, plan_id: int, env):
        """[R] bool: does the row itself participate in the aggregate
        (JoinCmp.exclude_self)?  Both modes carry the review arrays —
        delta dispatches slice them row-aligned with the columns."""
        plan = self.plans[plan_id]
        xp = env.xp
        arg = self.plan_args[plan_id]
        rv = self.rv
        part = xp.asarray(rv["valid"]) & (
            xp.asarray(rv["kind"]) == xp.asarray(arg["kind_id"])
        )
        ns_empty = xp.asarray(rv["ns_empty"])
        if plan.remote_scope == "namespace":
            return part & ~ns_empty
        return part & ns_empty


# ---------------------------------------------------------------------------
# Host-side join-group index (delta-sweep locality + table source)
# ---------------------------------------------------------------------------


def _pairs_for_side(plan: JoinPlan, colkey: Tuple, slot: bool, ap,
                    part: Optional[np.ndarray]) -> np.ndarray:
    """(row, key_sid) pairs for one side of a plan over the resident
    audit pack, distinct per row.  ``part`` masks participating rows
    (None = every valid row)."""
    col = ap.cols.get(colkey)
    if col is None:
        return np.empty((0, 2), np.int64)
    sid = np.asarray(col["sid"])
    if part is None:
        part = np.asarray(ap.rp["valid"])
    if slot:
        ok = np.asarray(col["mask"]) & (sid >= 0) & part[:, None]
        rows, slots = np.nonzero(ok)
        pairs = np.stack([rows, sid[rows, slots]], axis=1)
        if len(pairs):
            pairs = np.unique(pairs, axis=0)
        return pairs.astype(np.int64)
    rows = np.nonzero(part & (sid >= 0))[0]
    return np.stack([rows, sid[rows]], axis=1).astype(np.int64)


def _provider_part(plan: JoinPlan, ap, interner: Interner) -> np.ndarray:
    kind_id = interner.intern(plan.remote_kind)
    part = np.asarray(ap.rp["valid"]) & (
        np.asarray(ap.rp["kind"]) == kind_id
    )
    ns_empty = np.asarray(ap.rp["ns_empty"])
    if plan.remote_scope == "namespace":
        return part & ~ns_empty
    return part & ns_empty


def _keys_of_row(plan, colkey, slot, ap, row, part_ok: bool) -> Tuple[int, ...]:
    if not part_ok:
        return ()
    col = ap.cols.get(colkey)
    if col is None:
        return ()
    sid = np.asarray(col["sid"])
    if slot:
        ok = np.asarray(col["mask"])[row] & (sid[row] >= 0)
        return tuple(sorted(set(int(s) for s in sid[row][ok])))
    s = int(sid[row])
    return (s,) if s >= 0 else ()


class JoinState:
    """The join-group index: per plan, key -> provider rows (drives the
    aggregate) and key -> reader rows (rows whose verdict/message reads
    that key's aggregate).  All access under the owning driver's lock.

    Full sweeps rebuild it (O(R) numpy grouping) and DIFF against the
    previous index: keys whose provider set changed have their readers'
    row generations bumped, so the render caches (driver._render_memo +
    the per-constraint render_cache) can never serve a message whose
    group aggregate moved underneath it.  Delta sweeps update it
    incrementally (O(churn)) and return the affected reader rows — the
    key-group locality contract ``tools/check_join_parity.py`` asserts."""

    def __init__(self, plans: Tuple[JoinPlan, ...], rebuild_gen: int):
        self.plans = tuple(plans)
        self.sig = tuple(p.sig for p in self.plans)
        self.rebuild_gen = rebuild_gen
        self.built = False
        n = len(self.plans)
        self.providers: List[Dict[int, set]] = [{} for _ in range(n)]
        self.readers: List[Dict[int, set]] = [{} for _ in range(n)]
        self.row_pkeys: List[Dict[int, Tuple[int, ...]]] = [
            {} for _ in range(n)
        ]
        self.row_rkeys: List[Dict[int, Tuple[int, ...]]] = [
            {} for _ in range(n)
        ]

    # ---- build / diff ------------------------------------------------------

    @staticmethod
    def _index(pairs: np.ndarray):
        by_key: Dict[int, set] = {}
        by_row: Dict[int, Tuple[int, ...]] = {}
        if len(pairs):
            order = np.lexsort((pairs[:, 1], pairs[:, 0]))
            pairs = pairs[order]
            rows = pairs[:, 0]
            starts = np.concatenate(
                [[0], np.nonzero(rows[1:] != rows[:-1])[0] + 1, [len(rows)]]
            )
            for a, b in zip(starts[:-1], starts[1:]):
                r = int(rows[a])
                ks = tuple(int(k) for k in pairs[a:b, 1])
                by_row[r] = ks
                for k in ks:
                    by_key.setdefault(k, set()).add(r)
        return by_key, by_row

    def rebuild(self, ap, interner: Interner) -> set:
        """Re-derive the index from the resident packed columns; returns
        the reader rows whose key group changed since the previous index
        (empty on first build — nothing was cached against it)."""
        bump: set = set()
        for i, plan in enumerate(self.plans):
            part = _provider_part(plan, ap, interner)
            prov_pairs = _pairs_for_side(
                plan, plan.remote_colkey, plan.remote_slot, ap, part
            )
            new_prov, new_rowp = self._index(prov_pairs)
            read_pairs = _pairs_for_side(
                plan, plan.local_colkey, plan.local_slot, ap, None
            )
            new_read, new_rowr = self._index(read_pairs)
            if self.built:
                old_prov, old_read = self.providers[i], self.readers[i]
                for k in set(old_prov) | set(new_prov):
                    if old_prov.get(k) != new_prov.get(k):
                        bump |= old_read.get(k, set())
                        bump |= new_read.get(k, set())
            self.providers[i] = new_prov
            self.readers[i] = new_read
            self.row_pkeys[i] = new_rowp
            self.row_rkeys[i] = new_rowr
        self.built = True
        return bump

    # ---- delta -------------------------------------------------------------

    def affected(self, ap, interner: Interner, dirty) -> set:
        """Reader rows (beyond the dirty set) whose key-group aggregate a
        churn batch changes — WITHOUT mutating the index (eligibility
        preview; ``commit`` applies)."""
        out: set = set()
        for i, plan in enumerate(self.plans):
            part = _provider_part(plan, ap, interner)
            changed: set = set()
            for r in dirty:
                old = set(self.row_pkeys[i].get(r, ()))
                new = set(_keys_of_row(
                    plan, plan.remote_colkey, plan.remote_slot, ap, r,
                    bool(part[r]),
                ))
                changed |= old ^ new
            readers = self.readers[i]
            for k in changed:
                out |= readers.get(k, set())
        return out - set(dirty)

    def commit(self, ap, interner: Interner, dirty) -> set:
        """Apply a churn batch to the index; returns the affected reader
        rows (beyond the dirty set) and bumps their pack row generations
        so stale rendered results cannot be reused."""
        out: set = set()
        dirty = set(dirty)
        for i, plan in enumerate(self.plans):
            part = _provider_part(plan, ap, interner)
            prov, read = self.providers[i], self.readers[i]
            rowp, rowr = self.row_pkeys[i], self.row_rkeys[i]
            changed: set = set()
            for r in dirty:
                old = set(rowp.get(r, ()))
                new = set(_keys_of_row(
                    plan, plan.remote_colkey, plan.remote_slot, ap, r,
                    bool(part[r]),
                ))
                changed |= old ^ new
                for k in old - new:
                    s = prov.get(k)
                    if s is not None:
                        s.discard(r)
                        if not s:
                            del prov[k]
                for k in new - old:
                    prov.setdefault(k, set()).add(r)
                if new:
                    rowp[r] = tuple(sorted(new))
                else:
                    rowp.pop(r, None)
                # reader side: the row's own local keys
                oldr = set(rowr.get(r, ()))
                valid = bool(np.asarray(ap.rp["valid"])[r])
                newr = set(_keys_of_row(
                    plan, plan.local_colkey, plan.local_slot, ap, r, valid
                ))
                for k in oldr - newr:
                    s = read.get(k)
                    if s is not None:
                        s.discard(r)
                        if not s:
                            del read[k]
                for k in newr - oldr:
                    read.setdefault(k, set()).add(r)
                if newr:
                    rowr[r] = tuple(sorted(newr))
                else:
                    rowr.pop(r, None)
            for k in changed:
                out |= read.get(k, set())
        out -= dirty
        if out:
            ap.bump_row_gen(out)
        return out

    # ---- tables ------------------------------------------------------------

    def delta_tables(self) -> List[Dict[str, np.ndarray]]:
        """The per-plan (uk, uc) runtime tables for 'tables'-mode
        dispatches, padded to power-of-two widths so the delta executable
        survives group-count drift."""
        out = []
        for prov in self.providers:
            n = len(prov)
            width = _pow2_bucket(n, TABLE_MIN)
            uk = np.full(width, KEY_INVALID, np.int32)
            uc = np.zeros(width, np.int32)
            if n:
                keys = np.fromiter(prov.keys(), np.int64, n)
                counts = np.fromiter(
                    (len(prov[int(k)]) for k in keys), np.int64, n
                )
                order = np.argsort(keys)
                uk[:n] = keys[order]
                uc[:n] = counts[order]
            out.append({"uk": uk, "uc": uc})
        return out

    def shapes(self) -> List[dict]:
        """Observability summary for /debug/routez (bounded, cheap)."""
        out = []
        for i, plan in enumerate(self.plans):
            prov = self.providers[i]
            out.append({
                "agg": plan.agg,
                "kind": plan.remote_kind,
                "scope": plan.remote_scope,
                "slot_key": plan.local_slot,
                "groups": len(prov),
                "provider_rows": sum(len(s) for s in prov.values()),
                "reader_rows": sum(
                    len(s) for s in self.readers[i].values()
                ),
            })
        return out

    # ---- snapshot persistence ---------------------------------------------

    def persist(self) -> dict:
        """Pickle-friendly form for the snapshot sweep basis."""
        return {
            "sig": list(self.sig),
            "providers": [
                {int(k): sorted(v) for k, v in prov.items()}
                for prov in self.providers
            ],
            "readers": [
                {int(k): sorted(v) for k, v in read.items()}
                for read in self.readers
            ],
            "row_pkeys": [
                {int(r): list(ks) for r, ks in rp.items()}
                for rp in self.row_pkeys
            ],
            "row_rkeys": [
                {int(r): list(ks) for r, ks in rr.items()}
                for rr in self.row_rkeys
            ],
        }

    @classmethod
    def restore(cls, plans: Tuple[JoinPlan, ...], data: dict,
                rebuild_gen: int) -> Optional["JoinState"]:
        """Rebuild a persisted index; None on plan drift (the caller then
        drops the whole sweep basis and rebases via a full sweep)."""
        st = cls(plans, rebuild_gen)
        if list(st.sig) != list(data.get("sig", ())):
            return None
        try:
            st.providers = [
                {int(k): set(v) for k, v in prov.items()}
                for prov in data["providers"]
            ]
            st.readers = [
                {int(k): set(v) for k, v in read.items()}
                for read in data["readers"]
            ]
            st.row_pkeys = [
                {int(r): tuple(ks) for r, ks in rp.items()}
                for rp in data["row_pkeys"]
            ]
            st.row_rkeys = [
                {int(r): tuple(ks) for r, ks in rr.items()}
                for rr in data["row_rkeys"]
            ]
        except (KeyError, TypeError, ValueError):
            return None
        if (
            len(st.providers) != len(st.plans)
            or len(st.readers) != len(st.plans)
        ):
            return None
        st.built = True
        return st


# ---------------------------------------------------------------------------
# Divergence assertion (satellite: interned-key parity oracle)
# ---------------------------------------------------------------------------


class JoinDivergence(AssertionError):
    """An exact join plan flagged a cell the interpreter oracle renders
    empty — the packed aggregate and the oracle disagree."""


def assert_enabled() -> bool:
    """GK_JOIN_ASSERT=1 arms the divergence assertion (parity tools and
    tests); GK_BUG_COMPAT=1 disarms it even then — compat mode reproduces
    reference quirks the strict tables deliberately do not."""
    if os.environ.get("GK_JOIN_ASSERT", "0") != "1":
        return False
    from ..engine.compat import bug_compat_enabled

    return not bug_compat_enabled()


def gv_twin_corner(js: "JoinState", plans, ap, row: int) -> bool:
    """True when a flagged-but-renders-empty cell is explained by the
    DOCUMENTED over-approximation corner (docs/referential.md "Known
    limits"): a dup/count plan's key group for this row contains two
    provider ROWS sharing one object identity (namespace, name) — two
    groupVersions of one object, which the reference's ``identical``
    helper and the count comprehension's [ns, name] head see as one.
    Such cells are legitimate filter work, not a divergence."""
    for plan in plans:
        if plan.agg not in ("dup", "count"):
            continue
        try:
            i = js.plans.index(plan)
        except ValueError:
            continue
        for k in js.row_rkeys[i].get(int(row), ()):
            rows = js.providers[i].get(k, ())
            idents = set()
            for r in rows:
                rv = ap.reviews[r] if r < len(ap.reviews) else None
                if rv is None:
                    continue
                idents.add((rv.get("namespace", ""), rv.get("name", "")))
            if len(idents) < len(rows):
                return True
    return False


def note_false_positive(kind: str, name: str, row: int):
    """Record (and under GK_JOIN_ASSERT raise on) an exact-join-plan cell
    whose interpreter render produced nothing."""
    from ..metrics.catalog import record_join_divergence

    record_join_divergence(kind)
    if assert_enabled():
        raise JoinDivergence(
            f"join plan flagged ({kind}/{name}, row {row}) but the "
            "interpreter oracle renders no violation — interned-key "
            "normalization or aggregate divergence"
        )


# ---------------------------------------------------------------------------
# Clause classification (called from ops/vectorizer.py)
# ---------------------------------------------------------------------------


def _is_wild(op) -> bool:
    from ..rego.ast import Var

    return isinstance(op, Var) and op.is_wildcard


def _scalar_str(op) -> Optional[str]:
    from ..rego.ast import Scalar

    if isinstance(op, Scalar) and isinstance(op.value, str):
        return op.value
    return None


def _inventory_iter(rhs) -> Optional[Tuple[str, str, Dict[str, Any]]]:
    """Recognize ``data.inventory.namespace[ns][gv][Kind][name]`` /
    ``data.inventory.cluster[gv][Kind][name]`` -> (scope, kind,
    {"ns": var|None, "name": var|None}).  Non-kind operands must be
    wildcards or plain vars (bound only inside a comprehension head)."""
    from ..rego.ast import Ref, Var

    if not (isinstance(rhs, Ref) and isinstance(rhs.head, Var)
            and rhs.head.name == "data"):
        return None
    ops = rhs.operands
    if not ops or _scalar_str(ops[0]) != "inventory":
        return None
    ops = ops[1:]
    scope = _scalar_str(ops[0]) if ops else None
    if scope == "namespace" and len(ops) == 5:
        ns_op, gv_op, kind_op, name_op = ops[1], ops[2], ops[3], ops[4]
    elif scope == "cluster" and len(ops) == 4:
        ns_op, gv_op, kind_op, name_op = None, ops[1], ops[2], ops[3]
    else:
        return None
    kind = _scalar_str(kind_op)
    if kind is None:
        return None

    def var_or_wild(op):
        return op is None or isinstance(op, Var)

    if not (var_or_wild(ns_op) and var_or_wild(gv_op)
            and var_or_wild(name_op)):
        return None
    return scope, kind, {"ns": ns_op, "gv": gv_op, "name": name_op}


def _remote_rel_path(rhs, inv_var: str) -> Optional[Tuple[str, ...]]:
    """``other.spec.rules[_].host`` -> ('spec', 'rules', '[]', 'host')."""
    from ..rego.ast import Ref, Var

    if not (isinstance(rhs, Ref) and isinstance(rhs.head, Var)
            and rhs.head.name == inv_var):
        return None
    segs: List[str] = []
    for op in rhs.operands:
        s = _scalar_str(op)
        if s is not None:
            segs.append(s)
        elif _is_wild(op):
            segs.append("[]")
        else:
            return None
    return tuple(segs)


def _remote_colspec(rel: Tuple[str, ...]):
    """Remote rel path (object-relative) -> joinkey ColumnSpec over the
    packed review rows (which nest the raw object under 'object')."""
    from .columns import ColumnSpec

    segs = ("object",) + rel
    if "[]" in segs:
        last = len(segs) - 1 - segs[::-1].index("[]")
        return ColumnSpec(
            "joinkey", (tuple(segs[: last + 1]),), tuple(segs[last + 1:])
        ), True
    return ColumnSpec("joinkey", (), segs), False


def _vars_in(node) -> set:
    """Non-wildcard variable names referenced anywhere under a term."""
    from ..rego.ast import (
        ArrayCompr, ArrayTerm, BinOp, Call, ObjectCompr, ObjectTerm, Ref,
        SetCompr, SetTerm, UnaryMinus, Var,
    )

    out: set = set()
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, Var):
            if not n.is_wildcard:
                out.add(n.name)
        elif isinstance(n, Ref):
            stack.append(n.head)
            stack.extend(n.operands)
        elif isinstance(n, Call):
            stack.extend(n.args)
        elif isinstance(n, (ArrayTerm, SetTerm)):
            stack.extend(n.items)
        elif isinstance(n, ObjectTerm):
            for k, v in n.pairs:
                stack.append(k)
                stack.append(v)
        elif isinstance(n, (ArrayCompr, SetCompr)):
            stack.append(n.head)
            for e in n.body:
                stack.extend(e.terms)
        elif isinstance(n, ObjectCompr):
            stack.append(n.key)
            stack.append(n.value)
            for e in n.body:
                stack.extend(e.terms)
        elif isinstance(n, BinOp):
            stack.append(n.lhs)
            stack.append(n.rhs)
        elif isinstance(n, UnaryMinus):
            stack.append(n.operand)
    return out


class _NoMatch(Exception):
    pass


class _ClauseScan:
    """Order-insensitive partition of a violation clause body into the
    roles the family matchers consume.  The rego safety pass may reorder
    statements, so nothing here depends on source order."""

    def __init__(self, vec, rule):
        self.vec = vec
        self.rule = rule
        self.assigns: List = []        # (lhs_name, rhs, stmt)
        self.conds: List = []          # plain term statements
        self.nots: List = []           # 'not' statements
        for stmt in rule.body:
            if stmt.withs:
                raise _NoMatch()  # document patching: interpreter-only
            if stmt.kind == "some":
                continue
            if stmt.kind in ("assign", "unify"):
                from ..rego.ast import Var

                lhs = stmt.terms[0]
                if isinstance(lhs, Var):
                    self.assigns.append((lhs.name, stmt.terms[1], stmt))
                    continue
                raise _NoMatch()
            if stmt.kind == "not":
                self.nots.append(stmt)
                continue
            self.conds.append(stmt)


def _local_key_operand(vec, rhs, state):
    """Resolve a local key source (iteration -> slot, or a review-rooted
    scalar path) and register its joinkey column.  Returns
    (colkey, slot?)."""
    from .columns import ColumnSpec
    from .vectorizer import SPath, _Unsupported

    try:
        it = vec._try_iteration(rhs, {}, state)
    except _Unsupported:
        it = None
    if it is not None:
        spec = ColumnSpec("joinkey", it.root[1], tuple(it.segs))
        vec.columns[spec.key] = spec
        return spec.key, True
    try:
        sym = vec._resolve(rhs, {}, state)
    except _Unsupported:
        raise _NoMatch()
    if isinstance(sym, SPath) and sym.root == "review":
        spec = ColumnSpec("joinkey", (), tuple(sym.segs))
        vec.columns[spec.key] = spec
        return spec.key, False
    raise _NoMatch()


def _check_benign_guards(scan, consumed: set, remote_vars: set):
    """Assignments the matcher did not consume must be benign calls
    (sprintf & friends) referencing no remote entity — a message that
    embeds the OTHER row's fields depends on group content the delta
    invalidation cannot see, so such clauses stay on the interpreter
    tier.  The violation head is checked the same way."""
    from ..rego.ast import Call

    from .vectorizer import _BENIGN_CALLS

    for name, rhs, _stmt in scan.assigns:
        if name in consumed:
            continue
        if not (isinstance(rhs, Call)
                and ".".join(rhs.path) in _BENIGN_CALLS):
            raise _NoMatch()
        if _vars_in(rhs) & remote_vars:
            raise _NoMatch()
    if scan.rule.key is not None and _vars_in(scan.rule.key) & remote_vars:
        raise _NoMatch()


def _match_dup(vec, scan: _ClauseScan):
    """unique-key family: local (slot or scalar) key, an inventory
    iteration of the same kind, a remote key equal to the local key, and
    an object-identity self-exclusion helper under ``not``."""
    from ..rego.ast import BinOp, Call, Ref, Var

    state = {"slot": None}
    inv = None
    inv_var = None
    for name, rhs, _stmt in scan.assigns:
        got = _inventory_iter(rhs)
        if got is not None:
            if inv is not None:
                raise _NoMatch()
            # violation-clause inventory vars must be wildcards: a bound
            # scope var would correlate with the local row (unsupported)
            scope, kind, vs = got
            for v in (vs["ns"], vs["gv"], vs["name"]):
                if v is not None and not v.is_wildcard:
                    raise _NoMatch()
            inv, inv_var = (scope, kind), name
    if inv is None:
        raise _NoMatch()
    scope, kind = inv
    # remote key: either a var assigned from `other.<path>[_]...` or a
    # direct `other.<path> == key` comparison side
    remote_key_vars: Dict[str, Tuple[str, ...]] = {}
    for name, rhs, _stmt in scan.assigns:
        if name == inv_var:
            continue
        rel = _remote_rel_path(rhs, inv_var)
        if rel is not None:
            remote_key_vars[name] = rel

    # the equality condition joining local and remote keys decides which
    # local var is the key
    remote_rel = None
    local_var = None
    for stmt in scan.conds:
        t = stmt.terms[0]
        if not (isinstance(t, BinOp) and t.op == "=="):
            raise _NoMatch()
        for a, b in ((t.lhs, t.rhs), (t.rhs, t.lhs)):
            if not isinstance(a, Var) or a.name in remote_key_vars \
                    or a.name == inv_var:
                continue
            rel = (
                remote_key_vars.get(b.name)
                if isinstance(b, Var) else _remote_rel_path(b, inv_var)
            )
            if rel is not None:
                if remote_rel is not None:
                    raise _NoMatch()  # one join equality per clause
                remote_rel, local_var = rel, a.name
                break
        else:
            raise _NoMatch()
    if remote_rel is None or local_var is None:
        raise _NoMatch()
    local_key = None
    for name, rhs, _stmt in scan.assigns:
        if name == local_var:
            local_key = _local_key_operand(vec, rhs, state)
    if local_key is None:
        raise _NoMatch()

    # the self-exclusion: not identical(other, input.review)
    if len(scan.nots) != 1:
        raise _NoMatch()
    inner = scan.nots[0].terms[0]
    t = inner.terms[0] if getattr(inner, "kind", None) == "term" else None
    if not (isinstance(t, Call) and len(t.path) == 1 and len(t.args) == 2):
        raise _NoMatch()
    a0, a1 = t.args
    if not (isinstance(a0, Var) and a0.name == inv_var):
        raise _NoMatch()
    if not (isinstance(a1, Ref) and isinstance(a1.head, Var)
            and a1.head.name == "input"
            and [_scalar_str(op) for op in a1.operands] == ["review"]):
        raise _NoMatch()
    _check_identity_helper(vec, t.path[0], scope)

    remote_vars = {inv_var} | set(remote_key_vars)
    _check_benign_guards(scan, {local_var, inv_var} | set(remote_key_vars),
                         remote_vars)

    from .vexpr import Clause, JoinCmp, Lit

    rspec, rslot = _remote_colspec(remote_rel)
    if (rspec.key, rslot) != (local_key[0], local_key[1]):
        # self-exclusion (counts - own contribution) is only exact when
        # the local key IS the row's provider key — different local and
        # remote paths stay on the interpreter tier
        raise _NoMatch()
    vec.columns[rspec.key] = rspec
    plan = JoinPlan(
        agg="dup", local_colkey=local_key[0], local_slot=local_key[1],
        remote_scope=scope, remote_kind=kind,
        remote_colkey=rspec.key, remote_slot=rslot,
    )
    pid = _register_plan(vec, plan)
    # "another object provides my key": distinct provider rows at the
    # key, minus this row's own contribution, >= 1
    node = JoinCmp(pid, ">=", Lit(1), slot=local_key[1],
                   exclude_self=True)
    return Clause(conds=(node,), slot_iter=state["slot"])


def _check_identity_helper(vec, name: str, scope: str):
    """The self-exclusion helper must compare exactly the fields that
    identify an object in the plan's scope: metadata.name (+ namespace
    when namespace-scoped).  Anything else narrows or widens identity in
    ways the distinct-row aggregate cannot express."""
    from ..rego.ast import BinOp, Ref, Var

    rules = vec.cm.rules.get(name) or []
    if len(rules) != 1:
        raise _NoMatch()
    r = rules[0]
    if not r.is_function or len(r.args or ()) != 2 or r.els is not None:
        raise _NoMatch()
    if r.value is not None:
        from ..rego.ast import Scalar

        if not (isinstance(r.value, Scalar) and r.value.value is True):
            raise _NoMatch()
    o_var, rv_var = r.args
    if not (isinstance(o_var, Var) and isinstance(rv_var, Var)):
        raise _NoMatch()
    fields = set()
    for stmt in r.body:
        if stmt.kind != "term" or not isinstance(stmt.terms[0], BinOp):
            raise _NoMatch()
        b = stmt.terms[0]
        if b.op != "==":
            raise _NoMatch()

        def field_of(t, head, prefix):
            if not (isinstance(t, Ref) and isinstance(t.head, Var)
                    and t.head.name == head):
                return None
            segs = [_scalar_str(op) for op in t.operands]
            if None in segs or segs[:-1] != prefix:
                return None
            return segs[-1]

        for a, b2 in ((b.lhs, b.rhs), (b.rhs, b.lhs)):
            f1 = field_of(a, o_var.name, ["metadata"])
            f2 = field_of(b2, rv_var.name, ["object", "metadata"])
            if f1 is not None and f2 is not None and f1 == f2:
                fields.add(f1)
                break
        else:
            raise _NoMatch()
    want = {"name", "namespace"} if scope == "namespace" else {"name"}
    if fields != want:
        raise _NoMatch()


def _match_exists(vec, scan: _ClauseScan):
    """required-reference family: a local reference value and a ``not
    exists(ref)`` helper iterating the inventory for a row whose remote
    key equals it."""
    from ..rego.ast import BinOp, Call, Ref, Var

    if len(scan.nots) != 1 or scan.conds:
        raise _NoMatch()
    inner = scan.nots[0].terms[0]
    t = inner.terms[0] if getattr(inner, "kind", None) == "term" else None
    if not (isinstance(t, Call) and len(t.path) == 1 and len(t.args) == 1):
        raise _NoMatch()
    arg = t.args[0]
    if not isinstance(arg, Var):
        raise _NoMatch()
    local_var = arg.name
    state = {"slot": None}
    local_key = None
    for name, rhs, _stmt in scan.assigns:
        if name == local_var:
            local_key = _local_key_operand(vec, rhs, state)
    if local_key is None:
        raise _NoMatch()

    # the helper: one clause, one inventory iteration + one equality
    rules = vec.cm.rules.get(t.path[0]) or []
    if len(rules) != 1:
        raise _NoMatch()
    r = rules[0]
    if not r.is_function or len(r.args or ()) != 1 or r.els is not None:
        raise _NoMatch()
    p = r.args[0]
    if not isinstance(p, Var):
        raise _NoMatch()
    inv = None
    inv_var = None
    eqs = []
    for stmt in r.body:
        if stmt.withs:
            raise _NoMatch()
        if stmt.kind in ("assign", "unify") and isinstance(
            stmt.terms[0], Var
        ):
            got = _inventory_iter(stmt.terms[1])
            if got is not None and inv is None:
                scope, kind, vs = got
                for v in (vs["ns"], vs["gv"], vs["name"]):
                    if v is not None and not v.is_wildcard:
                        raise _NoMatch()
                inv, inv_var = (scope, kind), stmt.terms[0].name
                continue
            raise _NoMatch()
        if stmt.kind == "term" and isinstance(stmt.terms[0], BinOp):
            eqs.append(stmt.terms[0])
            continue
        raise _NoMatch()
    if inv is None or len(eqs) != 1:
        raise _NoMatch()
    scope, kind = inv
    b = eqs[0]
    if b.op != "==":
        raise _NoMatch()
    remote_rel = None
    for a, c in ((b.lhs, b.rhs), (b.rhs, b.lhs)):
        rel = _remote_rel_path(a, inv_var)
        if rel is not None and isinstance(c, Var) and c.name == p.name:
            remote_rel = rel
    if remote_rel is None:
        raise _NoMatch()

    _check_benign_guards(scan, {local_var}, set())

    from .vexpr import Clause, JoinCmp, Lit

    rspec, rslot = _remote_colspec(remote_rel)
    vec.columns[rspec.key] = rspec
    plan = JoinPlan(
        agg="exists", local_colkey=local_key[0], local_slot=local_key[1],
        remote_scope=scope, remote_kind=kind,
        remote_colkey=rspec.key, remote_slot=rslot,
    )
    pid = _register_plan(vec, plan)
    node = JoinCmp(pid, "==", Lit(0), slot=local_key[1])
    return Clause(conds=(node,), slot_iter=state["slot"])


def _match_count(vec, scan: _ClauseScan):
    """count-quota family: ``n := count({ident | p := data.inventory...;
    p.<path> == key})`` compared against a parameter (or literal)."""
    from ..rego.ast import ArrayTerm, BinOp, Call, SetCompr, Var

    from .vectorizer import SConst, SPath, _Unsupported

    if scan.nots:
        raise _NoMatch()
    count_var = None
    compr = None
    for name, rhs, _stmt in scan.assigns:
        if (isinstance(rhs, Call) and rhs.path == ("count",)
                and len(rhs.args) == 1
                and isinstance(rhs.args[0], SetCompr)):
            if count_var is not None:
                raise _NoMatch()
            count_var, compr = name, rhs.args[0]
    if compr is None:
        raise _NoMatch()

    # the comprehension body: inventory iteration (scope vars may bind)
    # + one equality between a remote rel path and an outer-scope key
    inv = None
    inv_var = None
    inv_vars: Dict[str, Any] = {}
    eqs = []
    for stmt in compr.body:
        if stmt.withs:
            raise _NoMatch()
        if stmt.kind in ("assign", "unify") and isinstance(
            stmt.terms[0], Var
        ):
            got = _inventory_iter(stmt.terms[1])
            if got is not None and inv is None:
                scope, kind, vs = got
                inv, inv_var = (scope, kind), stmt.terms[0].name
                inv_vars = vs
                continue
            raise _NoMatch()
        if stmt.kind == "term" and isinstance(stmt.terms[0], BinOp):
            eqs.append(stmt.terms[0])
            continue
        raise _NoMatch()
    if inv is None or len(eqs) != 1:
        raise _NoMatch()
    scope, kind = inv
    b = eqs[0]
    if b.op != "==":
        raise _NoMatch()
    remote_rel = None
    key_var = None
    for a, c in ((b.lhs, b.rhs), (b.rhs, b.lhs)):
        rel = _remote_rel_path(a, inv_var)
        if rel is not None and isinstance(c, Var):
            remote_rel, key_var = rel, c.name
    if remote_rel is None:
        raise _NoMatch()

    # the head must enumerate object IDENTITY so count() counts distinct
    # inventory rows: [ns, name] when namespaced, the name var clusterwide
    def head_ok():
        ns_v = inv_vars.get("ns")
        name_v = inv_vars.get("name")
        name_name = name_v.name if isinstance(name_v, Var) and not \
            name_v.is_wildcard else None
        if name_name is None:
            return False
        if scope == "cluster":
            h = compr.head
            return isinstance(h, Var) and h.name == name_name
        ns_name = ns_v.name if isinstance(ns_v, Var) and not \
            ns_v.is_wildcard else None
        h = compr.head
        if ns_name is None or not isinstance(h, ArrayTerm):
            return False
        names = [
            x.name for x in h.items
            if isinstance(x, Var) and not x.is_wildcard
        ]
        return len(h.items) == 2 and sorted(names) == sorted(
            [ns_name, name_name]
        )

    if not head_ok():
        raise _NoMatch()

    # local key: the outer assignment the comprehension's key var names
    state = {"slot": None}
    local_key = None
    for name, rhs, _stmt in scan.assigns:
        if name == key_var:
            local_key = _local_key_operand(vec, rhs, state)
    if local_key is None or local_key[1]:
        raise _NoMatch()  # quota keys are scalar (one group per row)

    # the threshold comparison: n <op> parameter/literal
    cmp_node = None
    for stmt in scan.conds:
        t = stmt.terms[0]
        if not isinstance(t, BinOp):
            raise _NoMatch()
        from .vectorizer import _CMP_OPS, _flip

        if t.op not in _CMP_OPS:
            raise _NoMatch()
        for a, c, op in ((t.lhs, t.rhs, t.op), (t.rhs, t.lhs, _flip(t.op))):
            if isinstance(a, Var) and a.name == count_var:
                try:
                    sym = vec._resolve(c, {}, state)
                except _Unsupported:
                    raise _NoMatch()
                from .vexpr import Lit, ParamRef

                if isinstance(sym, SPath) and sym.root == "params":
                    vec.param_scalars.add(sym.segs)
                    rhs_op = ParamRef(sym.segs)
                elif isinstance(sym, SConst) and isinstance(
                    sym.value, (int, float)
                ) and not isinstance(sym.value, bool):
                    rhs_op = Lit(sym.value)
                else:
                    raise _NoMatch()
                if cmp_node is not None:
                    raise _NoMatch()
                cmp_node = (op, rhs_op)
                break
        else:
            raise _NoMatch()
    if cmp_node is None:
        raise _NoMatch()

    _check_benign_guards(scan, {key_var, count_var}, set())

    from .vexpr import Clause, JoinCmp

    rspec, rslot = _remote_colspec(remote_rel)
    vec.columns[rspec.key] = rspec
    plan = JoinPlan(
        agg="count", local_colkey=local_key[0], local_slot=False,
        remote_scope=scope, remote_kind=kind,
        remote_colkey=rspec.key, remote_slot=rslot,
    )
    pid = _register_plan(vec, plan)
    node = JoinCmp(pid, cmp_node[0], cmp_node[1], slot=False)
    return Clause(conds=(node,), slot_iter=None)


def _register_plan(vec, plan: JoinPlan) -> int:
    plans = vec.join_plans
    for i, p in enumerate(plans):
        if p == plan:
            return i
    plans.append(plan)
    return len(plans) - 1


def classify_join_clause(vec, rule):
    """Try every referential family matcher against a violation clause.
    Returns a vexpr Clause (with the JoinPlan registered on the
    vectorizer) or None when no family matches — the caller then falls
    back to the generic (over-approximate) compilation."""
    try:
        scan = _ClauseScan(vec, rule)
    except _NoMatch:
        return None
    for matcher in (_match_count, _match_dup, _match_exists):
        try:
            return matcher(vec, scan)
        except _NoMatch:
            continue
    return None
