"""Vectorized constraint matching: bool[C, R] on device.

The selector logic of target_template_source.go:127-386 as boolean tensor
algebra over interned ids: kind selectors, namespaces/excludedNamespaces,
scope, labelSelector and namespaceSelector (matchLabels + matchExpressions),
plus the autoreject mask.  One fused XLA computation replaces the per-cell
Rego scan of matching_constraints (the reference's linear scan at
target_template_source.go:27-44).

All arrays come from ops/pack.py; dims: C constraints, R reviews, KP kind
pairs, N/X namespace ids, L label pairs, E match expressions, V values.
"""

from __future__ import annotations

import jax.numpy as jnp

from .pack import (
    PAD,
    SCOPE_NONE,
    SCOPE_OTHER,
    UNDEF,
    WILD,
)


def _selector_match(lab_pairs, cs_ml, cs_op, cs_key, cs_vals, cs_nvals, xp=jnp):
    """matches_label_selector over [R, L, 2] labels x [C, ...] selectors
    -> bool[C, R].

    The small static widths (Lc matchLabels pairs, E expressions, V values,
    L label slots) are unrolled as Python loops so no transient ever exceeds
    [C, R] — materializing [C, E, V, R, L] broadcasts OOMs at audit scale."""
    lab_key = lab_pairs[:, :, 0]  # [R, L]
    lab_val = lab_pairs[:, :, 1]
    lab_ok = lab_key != PAD  # [R, L]
    L = lab_key.shape[1]

    def key_val_hit(k, v):  # k,v: [C, 1] -> any label slot matches both
        acc = xp.zeros((k.shape[0], lab_key.shape[0]), bool)
        for l in range(L):
            acc = acc | (
                (lab_key[None, :, l] == k) & (lab_val[None, :, l] == v)
                & lab_ok[None, :, l]
            )
        return acc  # [C, R]

    def key_hit(k):  # [C, 1] -> any label slot has this key
        acc = xp.zeros((k.shape[0], lab_key.shape[0]), bool)
        for l in range(L):
            acc = acc | ((lab_key[None, :, l] == k) & lab_ok[None, :, l])
        return acc

    C = cs_ml.shape[0]
    R = lab_key.shape[0]

    # matchLabels: every (k, v) pair (non-pad) must be satisfied.
    ml_ok = xp.ones((C, R), bool)
    for i in range(cs_ml.shape[1]):
        k = cs_ml[:, i, 0][:, None]
        v = cs_ml[:, i, 1][:, None]
        sat = key_val_hit(k, v)
        ml_ok = ml_ok & (sat | (k == PAD))

    # matchExpressions
    ex_ok = xp.ones((C, R), bool)
    for e in range(cs_op.shape[1]):
        op = cs_op[:, e][:, None]  # [C, 1]
        key = cs_key[:, e][:, None]
        has = key_hit(key)  # [C, R]
        val_in = xp.zeros((C, R), bool)
        for v in range(cs_vals.shape[2]):
            val_in = val_in | key_val_hit(key, cs_vals[:, e, v][:, None])
        nvals = cs_nvals[:, e][:, None]
        violated = xp.where(
            op == 0, ~has | ((nvals > 0) & ~val_in),  # In
            xp.where(
                op == 1, has & (nvals > 0) & val_in,  # NotIn
                xp.where(
                    op == 2, ~has,  # Exists
                    xp.where(op == 3, has, False),  # DoesNotExist / unknown
                ),
            ),
        )
        ex_ok = ex_ok & ~(violated & (op != -1))
    return ml_ok & ex_ok


def _any_labelselector_match(rv, cs_ml, cs_op, cs_key, cs_vals, cs_nvals, xp=jnp):
    """any_labelselector_match (target_template_source.go:233-278)
    -> bool[C, R]."""
    sm_obj = _selector_match(rv["obj_labels"], cs_ml, cs_op, cs_key, cs_vals, cs_nvals, xp)
    sm_old = _selector_match(rv["old_labels"], cs_ml, cs_op, cs_key, cs_vals, cs_nvals, xp)
    empty = xp.full_like(rv["obj_labels"][:1], PAD)
    sm_empty = _selector_match(empty, cs_ml, cs_op, cs_key, cs_vals, cs_nvals, xp)  # [C, 1]
    obj_e = rv["obj_empty"][None, :]
    old_e = rv["old_empty"][None, :]
    return xp.where(
        obj_e & old_e, sm_empty,
        xp.where(
            old_e, sm_obj,
            xp.where(obj_e, sm_old, sm_obj | sm_old),
        ),
    )


def _no_selectors(ml, op) -> bool:
    """True when every row's selector is empty (all-PAD matchLabels, no
    matchExpressions) — the common cluster shape.  Host-mode fast path
    only: under jit the reduction would trace, and the compiled kernel
    doesn't pay the Python unroll anyway."""
    return bool((ml[:, :, 0] == PAD).all() and (op == -1).all())


def match_kernel(rv: dict, cs: dict, xp=jnp):
    """-> (match bool[C, R], autoreject bool[C, R])."""
    import numpy as _np

    host = xp is _np
    group = rv["group"][None, :]  # [1, R]
    kind = rv["kind"][None, :]

    C = cs["kind_pairs"].shape[0]
    R = group.shape[1]

    # kind selectors: any (group, kind) pair matches (KP unrolled)
    kinds_ok = xp.zeros((C, R), bool)
    for p in range(cs["kind_pairs"].shape[1]):
        kp_g = cs["kind_pairs"][:, p, 0][:, None]  # [C, 1]
        kp_k = cs["kind_pairs"][:, p, 1][:, None]
        kinds_ok = kinds_ok | (
            ((kp_g == WILD) | (kp_g == group))
            & ((kp_k == WILD) | (kp_k == kind))
            & (kp_g != PAD)
        )

    # namespaces / excludedNamespaces (N unrolled)
    ns_name = rv["ns_name"][None, :]  # [1, R]
    ns_def = ns_name != UNDEF
    always = rv["always"][None, :]

    def member(ids):
        acc = xp.zeros((C, R), bool)
        for i in range(ids.shape[1]):
            col = ids[:, i][:, None]
            acc = acc | ((col == ns_name) & (col != PAD))
        return acc

    ns_ok = ~cs["has_ns"][:, None] | always | (ns_def & member(cs["ns_ids"]))
    ex_ok = ~cs["has_ex"][:, None] | always | (ns_def & ~member(cs["ex_ids"]))

    # scope
    scope = cs["scope"][:, None]  # [C, 1]
    ns_empty = rv["ns_empty"][None, :]
    scope_ok = xp.where(
        (scope == SCOPE_NONE) | (scope == 1), True,
        xp.where(
            scope == 2, ~ns_empty,
            xp.where(scope == 3, ns_empty, False),  # SCOPE_OTHER -> False
        ),
    )

    # labelSelector (host fast path: an empty selector matches everything,
    # and clusters overwhelmingly install constraints without selectors)
    if host and _no_selectors(cs["ls_ml"], cs["ls_op"]):
        ls_ok = xp.ones((C, R), bool)
    else:
        ls_ok = _any_labelselector_match(
            rv, cs["ls_ml"], cs["ls_op"], cs["ls_key"], cs["ls_vals"],
            cs["ls_nvals"], xp,
        )

    # namespaceSelector by mode: 0 always-T, 1 ns labels, 2 uncached-F, 3 is_ns
    if host and not cs["has_nssel"].any():
        nssel_ok = xp.ones((C, R), bool)
    else:
        sm_ns = _selector_match(
            rv["ns_labels"], cs["nssel_ml"], cs["ns_op"], cs["ns_key"],
            cs["ns_vals"], cs["ns_nvals"], xp,
        )
        alm_ns = _any_labelselector_match(
            rv, cs["nssel_ml"], cs["ns_op"], cs["ns_key"], cs["ns_vals"],
            cs["ns_nvals"], xp,
        )
        mode = rv["ns_mode"][None, :]
        nssel_result = xp.where(
            mode == 0, True,
            xp.where(mode == 1, sm_ns, xp.where(mode == 3, alm_ns, False)),
        )
        nssel_ok = ~cs["has_nssel"][:, None] | nssel_result

    valid = cs["valid"][:, None] & rv["valid"][None, :]
    match = kinds_ok & ns_ok & ex_ok & scope_ok & ls_ok & nssel_ok & valid
    autoreject = cs["has_nssel"][:, None] & rv["autoreject"][None, :] & valid
    return match, autoreject
