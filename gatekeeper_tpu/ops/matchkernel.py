"""Vectorized constraint matching: bool[C, R] on device.

The selector logic of target_template_source.go:127-386 as boolean tensor
algebra over interned ids: kind selectors, namespaces/excludedNamespaces,
scope, labelSelector and namespaceSelector (matchLabels + matchExpressions),
plus the autoreject mask.  One fused XLA computation replaces the per-cell
Rego scan of matching_constraints (the reference's linear scan at
target_template_source.go:27-44).

All arrays come from ops/pack.py; dims: C constraints, R reviews, KP kind
pairs, N/X namespace ids, L label pairs, E match expressions, V values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .pack import (
    OP_UNKNOWN,
    PAD,
    SCOPE_NONE,
    SCOPE_OTHER,
    UNDEF,
    WILD,
)


def _selector_match(lab_pairs, cs_ml, cs_op, cs_key, cs_vals, cs_nvals):
    """matches_label_selector over [R, L, 2] labels x [C, ...] selectors
    -> bool[C, R]."""
    lab_key = lab_pairs[:, :, 0]  # [R, L]
    lab_val = lab_pairs[:, :, 1]
    lab_ok = lab_key != PAD  # [R, L]

    # matchLabels: every (k, v) pair (non-pad) must be satisfied.
    mlk = cs_ml[:, :, 0][:, :, None, None]  # [C, Lc, 1, 1]
    mlv = cs_ml[:, :, 1][:, :, None, None]
    hit = (
        (lab_key[None, None, :, :] == mlk)
        & (lab_val[None, None, :, :] == mlv)
        & lab_ok[None, None, :, :]
    )  # [C, Lc, R, L]
    sat = jnp.any(hit, axis=-1)  # [C, Lc, R]
    pair_pad = (cs_ml[:, :, 0] == PAD)[:, :, None]  # [C, Lc, 1]
    ml_ok = jnp.all(sat | pair_pad, axis=1)  # [C, R]

    # matchExpressions
    key = cs_key[:, :, None, None]  # [C, E, 1, 1]
    key_hit = (lab_key[None, None, :, :] == key) & lab_ok[None, None, :, :]
    has = jnp.any(key_hit, axis=-1)  # [C, E, R]
    vals = cs_vals[:, :, :, None, None]  # [C, E, V, 1, 1]
    val_hit = key_hit[:, :, None, :, :] & (
        lab_val[None, None, None, :, :] == vals
    )  # [C, E, V, R, L]
    val_in = jnp.any(val_hit, axis=(2, 4))  # [C, E, R]
    nvals = cs_nvals[:, :, None]  # [C, E, 1]
    op = cs_op[:, :, None]  # [C, E, 1]

    violated = jnp.where(
        op == 0, ~has | ((nvals > 0) & ~val_in),  # In
        jnp.where(
            op == 1, has & (nvals > 0) & val_in,  # NotIn
            jnp.where(
                op == 2, ~has,  # Exists
                jnp.where(op == 3, has, False),  # DoesNotExist / unknown
            ),
        ),
    )
    expr_pad = (cs_op == -1)[:, :, None]
    ex_ok = ~jnp.any(violated & ~expr_pad, axis=1)  # [C, R]
    return ml_ok & ex_ok


def _any_labelselector_match(rv, cs_ml, cs_op, cs_key, cs_vals, cs_nvals):
    """any_labelselector_match (target_template_source.go:233-278)
    -> bool[C, R]."""
    sm_obj = _selector_match(rv["obj_labels"], cs_ml, cs_op, cs_key, cs_vals, cs_nvals)
    sm_old = _selector_match(rv["old_labels"], cs_ml, cs_op, cs_key, cs_vals, cs_nvals)
    empty = jnp.full_like(rv["obj_labels"][:1], PAD)
    sm_empty = _selector_match(empty, cs_ml, cs_op, cs_key, cs_vals, cs_nvals)  # [C, 1]
    obj_e = rv["obj_empty"][None, :]
    old_e = rv["old_empty"][None, :]
    return jnp.where(
        obj_e & old_e, sm_empty,
        jnp.where(
            old_e, sm_obj,
            jnp.where(obj_e, sm_old, sm_obj | sm_old),
        ),
    )


def match_kernel(rv: dict, cs: dict):
    """-> (match bool[C, R], autoreject bool[C, R])."""
    group = rv["group"][None, :]  # [1, R]
    kind = rv["kind"][None, :]

    # kind selectors: any (group, kind) pair matches
    kp_g = cs["kind_pairs"][:, :, 0][:, :, None]  # [C, KP, 1]
    kp_k = cs["kind_pairs"][:, :, 1][:, :, None]
    pair_ok = (
        ((kp_g == WILD) | (kp_g == group[:, None, :]))
        & ((kp_k == WILD) | (kp_k == kind[:, None, :]))
        & (kp_g != PAD)
    )
    kinds_ok = jnp.any(pair_ok, axis=1)  # [C, R]

    # namespaces / excludedNamespaces
    ns_name = rv["ns_name"][None, :]  # [1, R]
    ns_def = ns_name != UNDEF
    always = rv["always"][None, :]
    member_ns = jnp.any(
        (cs["ns_ids"][:, :, None] == ns_name[:, None, :])
        & (cs["ns_ids"][:, :, None] != PAD),
        axis=1,
    )
    ns_ok = ~cs["has_ns"][:, None] | always | (ns_def & member_ns)
    member_ex = jnp.any(
        (cs["ex_ids"][:, :, None] == ns_name[:, None, :])
        & (cs["ex_ids"][:, :, None] != PAD),
        axis=1,
    )
    ex_ok = ~cs["has_ex"][:, None] | always | (ns_def & ~member_ex)

    # scope
    scope = cs["scope"][:, None]  # [C, 1]
    ns_empty = rv["ns_empty"][None, :]
    scope_ok = jnp.where(
        (scope == SCOPE_NONE) | (scope == 1), True,
        jnp.where(
            scope == 2, ~ns_empty,
            jnp.where(scope == 3, ns_empty, False),  # SCOPE_OTHER -> False
        ),
    )

    # labelSelector
    ls_ok = _any_labelselector_match(
        rv, cs["ls_ml"], cs["ls_op"], cs["ls_key"], cs["ls_vals"], cs["ls_nvals"]
    )

    # namespaceSelector by mode: 0 always-T, 1 ns labels, 2 uncached-F, 3 is_ns
    sm_ns = _selector_match(
        rv["ns_labels"], cs["nssel_ml"], cs["ns_op"], cs["ns_key"],
        cs["ns_vals"], cs["ns_nvals"],
    )
    alm_ns = _any_labelselector_match(
        rv, cs["nssel_ml"], cs["ns_op"], cs["ns_key"], cs["ns_vals"], cs["ns_nvals"]
    )
    mode = rv["ns_mode"][None, :]
    nssel_result = jnp.where(
        mode == 0, True,
        jnp.where(mode == 1, sm_ns, jnp.where(mode == 3, alm_ns, False)),
    )
    nssel_ok = ~cs["has_nssel"][:, None] | nssel_result

    valid = cs["valid"][:, None] & rv["valid"][None, :]
    match = kinds_ok & ns_ok & ex_ok & scope_ok & ls_ok & nssel_ok & valid
    autoreject = cs["has_nssel"][:, None] & rv["autoreject"][None, :] & valid
    return match, autoreject


match_kernel_jit = jax.jit(match_kernel)
