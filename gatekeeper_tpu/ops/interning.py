"""String interning: the bridge between JSON documents and integer tensors.

All strings that participate in device-side comparisons (kinds, groups,
namespaces, names, label keys/values, image strings, ...) are interned into
one global vocabulary.  String predicates against constraint parameters
(startswith, regex, ...) become host-precomputed boolean lookup tables over
the vocabulary, gathered on device — the classic dictionary-encoding trick,
which turns per-string work into O(unique values) host work and O(1) device
gathers.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

import numpy as np


class Interner:
    """Append-only string -> int32 id table.  id 0 is reserved for the empty
    string; negative ids are sentinels (-1 missing, -2 pad, ...)."""

    MISSING = -1
    PAD = -2
    NON_STRING = -3

    def __init__(self):
        self._ids: Dict[str, int] = {"": 0}
        self._strings: List[str] = [""]
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._strings)

    def intern(self, s: str) -> int:
        i = self._ids.get(s)
        if i is not None:
            return i
        with self._lock:
            i = self._ids.get(s)
            if i is None:
                i = len(self._strings)
                self._ids[s] = i
                self._strings.append(s)
            return i

    def intern_value(self, v) -> int:
        """Intern strings; map non-strings to sentinels so id-equality stays
        sound (two equal strings share an id; a non-string never equals)."""
        if isinstance(v, str):
            return self.intern(v)
        return self.NON_STRING

    def lookup(self, i: int) -> str:
        return self._strings[i]

    def snapshot_size(self) -> int:
        return len(self._strings)


class PredicateTable:
    """Lazy bool table over the vocabulary for a unary string predicate
    (e.g. 'startswith with prefix P').  Grows with the vocabulary; the
    device side sees a dense uint8 vector indexed by string id."""

    def __init__(self, interner: Interner, fn: Callable[[str], bool]):
        self.interner = interner
        self.fn = fn
        self._table = np.zeros(0, dtype=np.uint8)

    def dense(self) -> np.ndarray:
        n = self.interner.snapshot_size()
        if len(self._table) < n:
            old = len(self._table)
            grown = np.zeros(n, dtype=np.uint8)
            grown[:old] = self._table
            for i in range(old, n):
                try:
                    grown[i] = 1 if self.fn(self.interner.lookup(i)) else 0
                except Exception:
                    grown[i] = 0
            self._table = grown
        return self._table


class ValueMap:
    """Lazy float/flag map over the vocabulary for a pure unary function of a
    string value (e.g. canonify_cpu): host computes once per unique value,
    device gathers per row."""

    def __init__(self, interner: Interner, fn: Callable[[str], float]):
        self.interner = interner
        self.fn = fn  # returns float or raises/None for "undefined"
        self._vals = np.zeros(0, dtype=np.float64)
        self._ok = np.zeros(0, dtype=np.uint8)

    def dense(self):
        n = self.interner.snapshot_size()
        if len(self._vals) < n:
            old = len(self._vals)
            vals = np.zeros(n, dtype=np.float64)
            ok = np.zeros(n, dtype=np.uint8)
            vals[:old] = self._vals
            ok[:old] = self._ok
            for i in range(old, n):
                try:
                    v = self.fn(self.interner.lookup(i))
                    if v is not None:
                        vals[i] = float(v)
                        ok[i] = 1
                # gklint: disable=swallowed-exception -- by contract a
                # per-value extractor failure means "feature absent":
                # ok[i] stays 0 and the kernel masks the cell out
                except Exception:
                    pass
            self._vals, self._ok = vals, ok
        return self._vals, self._ok
